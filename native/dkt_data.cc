// dkt_data — native columnar data kernels for distkeras_tpu.
//
// The reference outsources its data plane to Apache Spark (partition
// shuffles, row marshalling inside executors — SURVEY §3.1 flags the
// per-row path as a bottleneck). The TPU build replaces that with columnar
// host arrays; these kernels are the multithreaded hot ops behind them:
//
//   dkt_gather        epoch permutation gather (the per-epoch shuffle)
//   dkt_one_hot       label -> one-hot matrix (transformers.OneHotTransformer)
//   dkt_minmax        min/max reduce + affine rescale (MinMaxTransformer)
//   dkt_csv_parse_f32 ASCII float CSV -> flat f32 (examples' CSV ingest)
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread (see native/Makefile).
// Python binding: distkeras_tpu/data/native.py (ctypes, numpy fallback).

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

namespace {

int clamp_threads(int requested, int64_t work_items, int64_t min_per_thread) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 4;
  int64_t by_work = std::max<int64_t>(1, work_items / min_per_thread);
  int n = std::min<int64_t>({requested > 0 ? requested : hw, hw, by_work});
  return std::max(1, n);
}

// run fn(begin, end) over [0, n) split across threads
template <typename Fn>
void parallel_for(int64_t n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n < 2) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t b = t * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    threads.emplace_back([=] { fn(b, e); });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// out[i, :] = src[perm[i], :] over row-major rows of row_bytes each.
// Dtype-agnostic (byte copy); perm values must be in [0, n_src_rows).
void dkt_gather(const char* src, const int64_t* perm, char* out,
                int64_t n_rows, int64_t row_bytes, int n_threads) {
  int nt = clamp_threads(n_threads, n_rows * row_bytes, 1 << 20);
  parallel_for(n_rows, nt, [=](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      std::memcpy(out + i * row_bytes, src + perm[i] * row_bytes, row_bytes);
    }
  });
}

// out[n, k] one-hot of labels[n]; out must be zero-initialized by caller.
// Out-of-range labels are left all-zero (matches the tolerant reference
// behavior of vector assembly). Returns count of out-of-range labels.
int64_t dkt_one_hot(const int64_t* labels, float* out, int64_t n, int64_t k,
                    int n_threads) {
  std::atomic<int64_t> bad{0};
  int nt = clamp_threads(n_threads, n, 1 << 16);
  parallel_for(n, nt, [&](int64_t b, int64_t e) {
    int64_t local_bad = 0;
    for (int64_t i = b; i < e; ++i) {
      int64_t y = labels[i];
      if (y >= 0 && y < k) {
        out[i * k + y] = 1.0f;
      } else {
        ++local_bad;
      }
    }
    bad.fetch_add(local_bad, std::memory_order_relaxed);
  });
  return bad.load();
}

// Column-wise min/max over x[n, d] into mins[d], maxs[d].
void dkt_col_minmax(const float* x, int64_t n, int64_t d, float* mins,
                    float* maxs, int n_threads) {
  int nt = clamp_threads(n_threads, n * d, 1 << 18);
  std::vector<std::vector<float>> tmins(nt, std::vector<float>(
      d, std::numeric_limits<float>::infinity()));
  std::vector<std::vector<float>> tmaxs(nt, std::vector<float>(
      d, -std::numeric_limits<float>::infinity()));
  std::atomic<int> tid{0};
  parallel_for(n, nt, [&](int64_t b, int64_t e) {
    int t = tid.fetch_add(1);
    float* mn = tmins[t].data();
    float* mx = tmaxs[t].data();
    for (int64_t i = b; i < e; ++i) {
      const float* row = x + i * d;
      for (int64_t j = 0; j < d; ++j) {
        mn[j] = std::min(mn[j], row[j]);
        mx[j] = std::max(mx[j], row[j]);
      }
    }
  });
  for (int64_t j = 0; j < d; ++j) {
    mins[j] = std::numeric_limits<float>::infinity();
    maxs[j] = -std::numeric_limits<float>::infinity();
  }
  for (int t = 0; t < nt; ++t) {
    for (int64_t j = 0; j < d; ++j) {
      mins[j] = std::min(mins[j], tmins[t][j]);
      maxs[j] = std::max(maxs[j], tmaxs[t][j]);
    }
  }
}

// out = (x - mn) / (mx - mn) * (hi - lo) + lo, column-wise, degenerate
// columns (mx == mn) map to lo.
void dkt_minmax_scale(const float* x, int64_t n, int64_t d, const float* mins,
                      const float* maxs, float lo, float hi, float* out,
                      int n_threads) {
  int nt = clamp_threads(n_threads, n * d, 1 << 18);
  std::vector<float> scale(d), off(d);
  for (int64_t j = 0; j < d; ++j) {
    float range = maxs[j] - mins[j];
    scale[j] = range > 0 ? (hi - lo) / range : 0.0f;
    off[j] = lo - mins[j] * scale[j];
  }
  const float* sc = scale.data();
  const float* of = off.data();
  parallel_for(n, nt, [=](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const float* row = x + i * d;
      float* orow = out + i * d;
      for (int64_t j = 0; j < d; ++j) orow[j] = row[j] * sc[j] + of[j];
    }
  });
}

// Parse ASCII-delimited floats from buf[0:len] into out (capacity max_vals).
// Any of {sep, '\n', '\r', '\t', ' '} delimit; empty fields are skipped.
// Returns number of values written, or -1 on malformed input / overflow.
int64_t dkt_csv_parse_f32(const char* buf, int64_t len, char sep, float* out,
                          int64_t max_vals) {
  int64_t count = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    while (p < end && (*p == sep || *p == '\n' || *p == '\r' || *p == '\t' ||
                       *p == ' '))
      ++p;
    if (p >= end) break;
    char* next = nullptr;
    float v = std::strtof(p, &next);
    if (next == p) return -1;  // not a number
    if (count >= max_vals) return -1;
    out[count++] = v;
    p = next;
  }
  return count;
}

int dkt_version() { return 1; }

}  // extern "C"
