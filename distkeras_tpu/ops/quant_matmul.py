"""Fused dequant-matmul for quantized decode-GEMM weights.

The serving engine's decode step is HBM-bandwidth-bound: at batch
sizes that fit a slot pool, every projection matmul (QKV, attention
out, MLP up/down) streams its whole weight matrix from HBM to multiply
a few rows of activations. Quantizing those weights to int8 halves the
per-step weight traffic vs bf16 (4x vs f32); int4 halves it again.
This module owns the weight-side quantized format and the Pallas
kernel that DEQUANTIZES IN-REGISTER inside the matmul — the int8/int4
bytes are the only thing that ever crosses HBM, the f32 weights never
materialize. It is the decode-shape sibling of ``moe_kernels``'s
grouped expert GEMM and follows the same backend conventions
(``fused_supported`` / ``force_interpret`` / interpreter-mode oracle
tests).

Quantized-weight format (one dict per weight leaf, original leaf
shape preserved so every non-kernel consumer can dequantize blind):

  * int8 — ``{"q": int8 (same shape as w), "scale": f32}``
  * int4 — values on the [-7, 7] grid; when the leading axis is even
    the rows are NIBBLE-PACKED along axis 0 as ``{"q4": int8
    [s0 // 2, ...], "scale": f32}`` (byte row r holds logical row r in
    the low nibble and row ``r + s0//2`` in the high nibble — the same
    half-split ``ops.paged_attention`` uses for int4 KV pages); an odd
    leading axis falls back to one byte per entry under ``"q"`` (same
    4-bit value grid, no packing).

``scale`` is per-output-channel and broadcast-ready against the
TRAILING axes of the unpacked ``q`` (e.g. wq [d, h, e] carries scale
[h, e]; wo [h, e, d] carries scale [d]), so ``dequant_weight`` needs
no shape metadata — which is what lets a whole params tree of these
dicts pass through ``jax.jit`` as a plain argument
(``dequant_params_tree``).

Matmul layout: ``quant_matmul(x, wq)`` contracts ``x [..., K]``
against the 2D view of the weight. Both decode layouts resolve from
shapes alone: ``q.shape[0] == K`` is the projection layout (wq/wk/wv
[d, h, e] -> [d, h*e]); otherwise ``prod(q.shape[:-1]) == K`` is the
output-projection layout (wo [h, e, d] -> [h*e, d]). The axis-0
nibble packing commutes with both flattenings, so the packed kernel's
in-register unpack (concat lo||hi along the contraction axis) is
exact in either layout.

Alignment: the kernel wants K % 128 == 0 (f32 lane tiling of the x
block; also covers the int8 [32, 128] sublane rule for the weight
tile, packed or not) and a block-N divisor of N that is % 128.
``fused_supported(k, n)`` gates; misaligned shapes take
``reference_matmul`` — plain XLA dequant + matmul, also the off-TPU
serving path (XLA fuses the dequant into the consuming matmul, so
int8/int4 stays the HBM-resident form there too).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from distkeras_tpu.compat import backend_is_tpu, tpu_compiler_params

#: upper bound on the output-channel tile. 512 f32 lanes x the whole
#: K column block stays well inside VMEM at decode batch sizes.
MAX_BLOCK_N = 512

_FORCE_INTERPRET = False


@contextlib.contextmanager
def force_interpret():
    """Run the kernel in Pallas interpreter mode regardless of backend
    — the CPU test suite's hook (tier-1 runs JAX_PLATFORMS=cpu, where
    the production path is ``reference_matmul``). Trace-time flag: an
    engine built inside this context bakes the interpreter kernel into
    its compiled decode programs."""
    global _FORCE_INTERPRET
    prev = _FORCE_INTERPRET
    _FORCE_INTERPRET = True
    try:
        yield
    finally:
        _FORCE_INTERPRET = prev


def is_qdict(p) -> bool:
    """Whether a params-tree node is one quantized weight leaf."""
    return (isinstance(p, dict) and "scale" in p
            and ("q" in p or "q4" in p))


def choose_block_n(n: int, cap: int = MAX_BLOCK_N) -> Optional[int]:
    """Largest divisor of ``n`` that is a multiple of 128 and <= cap
    (Mosaic lane tiling; divisor tiling keeps every block fully
    in-bounds). None when no such divisor exists -> reference path."""
    best = None
    for b in range(128, min(n, cap) + 1, 128):
        if n % b == 0:
            best = b
    return best


def kernel_enabled() -> bool:
    """The backend half of the kernel gate — same trace-time
    convention as every Pallas-vs-XLA fork in this repo
    (``compat.backend_is_tpu``, or a test forcing interpreter mode).
    The serving engine consults this once at construction to decide
    whether its decode programs keep attention projections quantized
    (shape misalignments still degrade per-leaf to the reference
    inside :func:`quant_matmul`)."""
    return pltpu is not None and (_FORCE_INTERPRET or backend_is_tpu())


def fused_supported(k: int, n: int) -> bool:
    """Whether a [*, k] @ [k, n] quantized matmul takes the kernel:
    :func:`kernel_enabled` plus the Mosaic alignment rules (see module
    docstring)."""
    if not kernel_enabled():
        return False
    return k % 128 == 0 and choose_block_n(n) is not None


# --- quantize / dequantize ------------------------------------------------


def pack_rows(q: jnp.ndarray) -> jnp.ndarray:
    """Nibble-pack int4-valued int8 rows along axis 0 (even length):
    byte row r = logical row r (low nibble) | row r + s0/2 << 4.
    int32 math for portable two's-complement handling."""
    s0 = q.shape[0]
    lo = q[: s0 // 2].astype(jnp.int32) & 15
    hi = q[s0 // 2:].astype(jnp.int32) & 15
    b = (hi << 4) | lo
    return (b - 256 * (b > 127)).astype(jnp.int8)


def unpack_rows(b: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_rows`: [s0/2, ...] bytes -> [s0, ...]
    int8 values in [-7, 7], low-nibble rows first."""
    b32 = b.astype(jnp.int32) & 255
    lo = b32 & 15
    lo = lo - 16 * (lo > 7)
    hi = (b32 >> 4) & 15
    hi = hi - 16 * (hi > 7)
    return jnp.concatenate([lo, hi], axis=0).astype(jnp.int8)


def quantize_weight(w, bits: int = 8,
                    reduce_axes: Optional[Tuple[int, ...]] = None
                    ) -> Dict[str, np.ndarray]:
    """Symmetric per-channel quantization of one weight matrix.

    ``reduce_axes`` are the CONTRACTION axes the scale absorbs
    (default: all but the last — the ``models.quantize`` convention);
    the scale keeps the non-reduced trailing axes, so ``q * scale``
    broadcasts back to ``w`` without metadata. ``bits=4`` packs along
    axis 0 when its length is even (see module docstring)."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    w = np.asarray(w, np.float32)
    if w.ndim < 2:
        raise ValueError(f"need a matrix-shaped weight, got {w.shape}")
    if reduce_axes is None:
        reduce_axes = tuple(range(w.ndim - 1))
    reduce_axes = tuple(sorted(a % w.ndim for a in reduce_axes))
    if reduce_axes != tuple(range(len(reduce_axes))):
        raise ValueError(
            f"reduce_axes must be a leading prefix, got {reduce_axes}")
    qmax = 7.0 if bits == 4 else 127.0
    absmax = np.abs(w).max(axis=reduce_axes, keepdims=True)
    scale = (absmax / qmax).astype(np.float32)
    scale = np.where(scale == 0.0, 1.0, scale)          # all-zero channels
    q = np.clip(np.round(w / scale), -qmax, qmax).astype(np.int8)
    scale = scale.reshape(w.shape[len(reduce_axes):]).astype(np.float32)
    if bits == 4 and q.shape[0] % 2 == 0:
        return {"q4": np.asarray(pack_rows(jnp.asarray(q))),
                "scale": scale}
    return {"q": q, "scale": scale}


def dequant_weight(wq: Dict, dtype=jnp.float32) -> jnp.ndarray:
    """``q * scale`` back to the original weight shape (the in-graph
    consumer of the reference path; XLA fuses it into the next
    matmul so the int bytes stay the HBM-resident form)."""
    q = unpack_rows(wq["q4"]) if "q4" in wq else wq["q"]
    return (q.astype(jnp.float32) * wq["scale"]).astype(dtype)


def quant_error(w, wq) -> Dict[str, float]:
    """Per-leaf reconstruction error of one quantized weight — the
    numbers ``obs.report.weight_quant_report`` aggregates."""
    w = np.asarray(w, np.float32)
    deq = np.asarray(dequant_weight(wq), np.float32).reshape(w.shape)
    err = deq - w
    denom = float(np.sqrt(np.mean(w ** 2))) or 1.0
    return {"max_abs_err": float(np.abs(err).max()),
            "rel_rms": float(np.sqrt(np.mean(err ** 2)) / denom)}


# --- the kernel -----------------------------------------------------------


def _kernel(x_ref, q_ref, s_ref, o_ref, *, int4: bool):
    x = x_ref[...]                                   # [M, K]
    q = q_ref[...]                                   # [K or K/2, bn] int8
    if int4:
        q = unpack_rows(q)                           # [K, bn]
    acc = lax.dot_general(
        x.astype(jnp.float32), q.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [M, bn]
    o_ref[...] = acc * s_ref[...]                    # scale [1, bn]


def _resolve_2d(x_k: int, wq: Dict):
    """Resolve the weight dict against a contraction length: returns
    ``(q2d, scale1d, int4, n)`` with ``q2d`` the [K or K/2, N] byte
    view. Projection layout (``q.shape[0] == K``) wins; otherwise the
    output-projection layout (leading axes flatten to K)."""
    int4 = "q4" in wq
    q = wq["q4"] if int4 else wq["q"]
    mult = 2 if int4 else 1
    if q.shape[0] * mult == x_k:
        q2d = q.reshape(q.shape[0], -1)
    elif int(np.prod(q.shape[:-1])) * mult == x_k:
        q2d = q.reshape(-1, q.shape[-1])
    else:
        raise ValueError(
            f"quantized weight {q.shape} (packed={int4}) does not "
            f"contract with K={x_k}")
    n = q2d.shape[1]
    scale = wq["scale"].reshape(-1)
    if scale.shape[0] != n:
        raise ValueError(
            f"scale {wq['scale'].shape} does not flatten to the "
            f"{n} output channels of {q.shape}")
    return q2d, scale, int4, n


def reference_matmul(x, wq) -> jnp.ndarray:
    """XLA path: same factored math as the kernel — int-q matmul in
    f32, THEN the per-channel scale (the scale is constant along K, so
    it commutes out of the contraction). f32 result, caller casts."""
    lead, k = x.shape[:-1], x.shape[-1]
    q2d, scale, int4, n = _resolve_2d(k, wq)
    if int4:
        q2d = unpack_rows(q2d)
    out = jnp.dot(x.reshape(-1, k).astype(jnp.float32),
                  q2d.astype(jnp.float32),
                  preferred_element_type=jnp.float32) * scale
    return out.reshape(lead + (n,))


def quant_matmul(x, wq, *, interpret: Optional[bool] = None
                 ) -> jnp.ndarray:
    """``x [..., K] @ dequant(wq) -> [..., N]`` in f32, dequantizing
    in-register on the kernel path. Falls back to
    :func:`reference_matmul` when the shape gate or backend gate says
    no (``fused_supported``), so callers use it unconditionally."""
    lead, k = x.shape[:-1], x.shape[-1]
    q2d, scale, int4, n = _resolve_2d(k, wq)
    if not fused_supported(k, n):
        return reference_matmul(x, wq)
    if interpret is None:
        interpret = not backend_is_tpu()
    bn = choose_block_n(n)
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    mp = -(-m // 8) * 8                   # Mosaic sublane rule for x/out
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    kq = q2d.shape[0]                     # K or K/2 (packed)
    out = pl.pallas_call(
        functools.partial(_kernel, int4=int4),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((mp, k), lambda i: (0, 0)),
            pl.BlockSpec((kq, bn), lambda i: (0, i)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((mp, bn), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((mp, n), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, q2d, scale.reshape(1, n))
    return out[:m].reshape(lead + (n,))


# --- params-tree plumbing (the serving engine's weight side) --------------

#: attention projection leaves — the decode programs' kernel
#: consumers; ``dequant_params_tree(keep_attn=True)`` leaves these as
#: qdicts for ``models.decoding._project_qkv`` / ``_attn_out``.
ATTN_PROJ_NAMES = frozenset({"wq", "wk", "wv", "wo"})

#: scale reduction axes per attention leaf (the contraction axes of
#: the decode matmuls): wq/wk/wv [d, h, e] contract d; wo [h, e, d]
#: contracts (h, e). Everything else uses the ``models.quantize``
#: all-but-last default.
_ATTN_REDUCE = {"wq": (0,), "wk": (0,), "wv": (0,), "wo": (0, 1)}


def quantize_params_tree(params, bits: int = 8):
    """Quantize every ``models.quantize.QUANTIZABLE_NAMES`` leaf of a
    params tree into the qdict format (original shapes preserved);
    other leaves pass through by reference. The serving engine's
    weight-quant initializer."""
    from distkeras_tpu.models.quantize import _is_quantizable

    def walk(p, name=""):
        if isinstance(p, dict):
            return {k: walk(v, k) for k, v in p.items()}
        if isinstance(p, (list, tuple)):
            seq = [walk(v, name) for v in p]
            return seq if isinstance(p, list) else tuple(seq)
        if _is_quantizable(p, name):
            return quantize_weight(np.asarray(jax.device_get(p)), bits,
                                   reduce_axes=_ATTN_REDUCE.get(name))
        return p

    return walk(params)


def dequant_params_tree(params, dtype=jnp.float32, keep_attn=False):
    """In-graph dequant of a quantized params tree — the first op of
    every compiled serving program under ``weight_quant`` (the same
    trick ``models.quantize.QuantizedModel`` uses: int bytes are the
    traced arguments, XLA fuses ``q * scale`` into each consumer).
    ``keep_attn`` leaves the attention projections as qdicts for the
    decode programs' fused kernel path."""
    def walk(p, name=""):
        if isinstance(p, dict):
            if is_qdict(p):
                if keep_attn and name in ATTN_PROJ_NAMES:
                    return p
                return dequant_weight(p, dtype)
            return {k: walk(v, k) for k, v in p.items()}
        if isinstance(p, (list, tuple)):
            seq = [walk(v, name) for v in p]
            return seq if isinstance(p, list) else tuple(seq)
        return p

    return walk(params)


def tree_quant_errors(params, qtree) -> Dict[str, Dict[str, float]]:
    """Path-keyed :func:`quant_error` over every quantized leaf of
    ``qtree`` vs the float master tree — the engine's
    ``weight_quant_error`` payload."""
    out = {}

    def walk(p, q, path):
        if is_qdict(q):
            out["/".join(path)] = quant_error(p, q)
        elif isinstance(q, dict):
            for k in q:
                walk(p[k], q[k], path + [str(k)])
        elif isinstance(q, (list, tuple)):
            for i, v in enumerate(q):
                walk(p[i], v, path + [str(i)])

    walk(params, qtree, [])
    return out
