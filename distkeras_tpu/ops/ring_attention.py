"""Ring attention: exact sequence-parallel attention over a mesh axis.

Absent from the reference (SURVEY §5.7: no sequence dimension sharding of
any kind) — this is the TPU build's long-context core. Each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange) while each device accumulates its
queries' attention with the online-softmax recurrence. The full [S, S]
matrix never exists anywhere, and the K/V transfer overlaps with the block
computation under XLA's latency-hiding scheduler.

Memory soundness (round 3): the op carries a **custom VJP**. Autodiff
through the forward's ppermute ``fori_loop`` would stash one rotated K/V
copy per hop — O(ring_size) residuals per device, exactly wrong for the
long-context regime this op exists for. Instead the forward saves only
``(q, k, v, out, lse)`` (all O(local shard), ring-size-independent) and
the backward runs a SECOND ring pass: probabilities are recomputed from
the saved log-sum-exp (the flash-attention construction), ``dq``
accumulates locally, and the ``dk``/``dv`` accumulators rotate around the
ring **together with** their K/V blocks, arriving home after n hops.

Peak score memory per device is O(S_local * block) when ``block_size`` is
set (an inner ``lax.scan`` over sub-blocks of the received shard with the
same online-softmax merge), or O(S_local²) when it is None — set it once
local shards get long enough that the block-pair score tile no longer fits
comfortably in VMEM/HBM.

``ring_attention`` must be called **inside** a ``shard_map`` whose
``axis_name`` axis shards the sequence dimension (the trainer and
``MultiHeadAttention(attn_impl="ring")`` arrange this).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distkeras_tpu.ops.attention import NEG_INF


def _merge_block(m, l, acc, qf, ks, vs, q_pos, k_pos, causal,
                 q_seg=None, k_seg=None):
    """One online-softmax merge of a K/V block into the (m, l, acc) carry.

    q_pos: [Sl] global query positions; k_pos: [bk] global key positions
    (shards are equal-length by construction, so there are no padding keys
    to mask — only the causal constraint). Shapes: qf [B, Sl, H, D]
    (pre-scaled f32), ks/vs [B, bk, H, D], m/l [B, H, Sl, 1],
    acc [B, Sl, H, D]. ``q_seg`` [B, Sl] / ``k_seg`` [B, bk] (packed
    sequences): scores across unequal segment ids are masked — the
    k-side ids ROTATE around the ring with their K/V blocks.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if causal:
        valid = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(valid[None, None], s, NEG_INF)
    if q_seg is not None:
        same = q_seg[:, :, None] == k_seg[:, None, :]      # [B, Sl, bk]
        s = jnp.where(same[:, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha.transpose(0, 2, 1, 3) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, vs.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _vary(x, axis_name):
    """Tag initial loop carries with the axis's varying type (jax >= 0.7
    shard_map vma check). On jax versions predating the vma machinery
    (no ``pcast``/``pvary``) there is nothing to tag — the experimental
    shard_map runs with the replication check off (see ``compat``) —
    so the identity is the correct no-op."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return lax.pvary(x, axis_name)
    except AttributeError:
        return x


def _check_block(block_size, s_local):
    if block_size is not None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if block_size < s_local and s_local % block_size:
            raise ValueError(
                f"block_size {block_size} must divide the local shard "
                f"length {s_local}")
    if block_size is not None and block_size < s_local:
        return block_size, s_local // block_size
    return s_local, 1


def _ring_forward(q, k, v, scale, causal, block_size, axis_name,
                  segment_ids=None):
    """Forward ring pass; returns (out, lse) with lse [B, H, Sl, 1] f32.

    ``segment_ids`` is the LOCAL [B, Sl] shard of packed-sequence ids;
    the k-side copy rotates around the ring with its K/V blocks.
    """
    n = lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    qf = q.astype(jnp.float32) * scale
    # global positions exist ONLY for the causal mask. Computing them
    # unconditionally plants a dead `axis_index` in the non-causal body,
    # which the custom_vjp call shields from DCE — and older XLA SPMD
    # partitioners hard-error on the orphaned partition-id op.
    idx = lax.axis_index(axis_name) if causal else None
    q_pos = None if idx is None else idx * s_local + jnp.arange(s_local)
    block, nblk = _check_block(block_size, s_local)
    q_seg = None if segment_ids is None \
        else jnp.asarray(segment_ids, jnp.int32)

    def body(t, carry):
        m, l, acc, kc, vc, sc = carry
        # block owner (position bookkeeping, causal only)
        shard_pos0 = None if idx is None else ((idx - t) % n) * s_local

        def inner(inner_carry, kb):
            m, l, acc = inner_carry
            ks = lax.dynamic_slice_in_dim(kc, kb * block, block, axis=1)
            vs = lax.dynamic_slice_in_dim(vc, kb * block, block, axis=1)
            k_pos = None if shard_pos0 is None \
                else shard_pos0 + kb * block + jnp.arange(block)
            k_seg = None if sc is None else \
                lax.dynamic_slice_in_dim(sc, kb * block, block, axis=1)
            return _merge_block(m, l, acc, qf, ks, vs, q_pos, k_pos,
                                causal, q_seg, k_seg), None

        if nblk == 1:
            (m, l, acc), _ = inner((m, l, acc), 0)
        else:
            (m, l, acc), _ = lax.scan(inner, (m, l, acc),
                                      jnp.arange(nblk))
        # rotate K/V to the next device (wasted on the final step, but the
        # loop stays uniform — XLA overlaps it with the block compute)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        if sc is not None:
            sc = lax.ppermute(sc, axis_name, perm)
        return m, l, acc, kc, vc, sc

    m0 = _vary(jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32),
               axis_name)
    l0 = _vary(jnp.zeros((b, h, s_local, 1), jnp.float32), axis_name)
    acc0 = _vary(jnp.zeros((b, s_local, h, d), jnp.float32), axis_name)
    m, l, acc, _, _, _ = lax.fori_loop(0, n, body,
                                       (m0, l0, acc0, k, v, q_seg))

    l_safe = jnp.where(l == 0.0, 1.0, l)                     # [B, H, Sl, 1]
    out = (acc / l_safe.transpose(0, 2, 1, 3)).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring(q, k, v, segment_ids, scale, causal, block_size, axis_name):
    out, _ = _ring_forward(q, k, v, scale, causal, block_size, axis_name,
                           segment_ids)
    return out


def _ring_fwd_rule(q, k, v, segment_ids, scale, causal, block_size,
                   axis_name):
    out, lse = _ring_forward(q, k, v, scale, causal, block_size, axis_name,
                             segment_ids)
    # O(local shard) residuals, independent of the ring size — asserted by
    # tests/test_attention.py::test_ring_backward_residuals_ring_independent
    return out, (q, k, v, out, lse, segment_ids)


def _ring_bwd_rule(scale, causal, block_size, axis_name, res, g):
    """Second ring pass: dq accumulates at home; dk/dv accumulators rotate
    with their K/V blocks and arrive home after n hops."""
    q, k, v, out, lse, segment_ids = res
    n = lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    qf = q.astype(jnp.float32) * scale
    gf = g.astype(jnp.float32)
    # delta_i = rowsum(dO * O) (flash trick), shaped like lse [B, H, Sl, 1]
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1) \
        .transpose(0, 2, 1)[..., None]
    # positions causal-only, as in the forward (dead-axis_index hazard)
    idx = lax.axis_index(axis_name) if causal else None
    q_pos = None if idx is None else idx * s_local + jnp.arange(s_local)
    block, nblk = _check_block(block_size, s_local)
    q_seg = None if segment_ids is None \
        else jnp.asarray(segment_ids, jnp.int32)

    def body(t, carry):
        dq, kc, vc, dkc, dvc, sc = carry
        shard_pos0 = None if idx is None else ((idx - t) % n) * s_local

        def inner(inner_carry, kb):
            dq, dkc, dvc = inner_carry
            ks = lax.dynamic_slice_in_dim(kc, kb * block, block, axis=1) \
                .astype(jnp.float32)
            vs = lax.dynamic_slice_in_dim(vc, kb * block, block, axis=1) \
                .astype(jnp.float32)
            k_pos = None if shard_pos0 is None \
                else shard_pos0 + kb * block + jnp.arange(block)
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks,
                           preferred_element_type=jnp.float32)
            if causal:
                valid = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(valid[None, None], s, NEG_INF)
            if q_seg is not None:
                k_seg = lax.dynamic_slice_in_dim(sc, kb * block, block,
                                                 axis=1)
                same = q_seg[:, :, None] == k_seg[:, None, :]
                s = jnp.where(same[:, None], s, NEG_INF)
            p = jnp.exp(s - lse)                             # [B, H, Sl, bk]
            dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vs,
                            preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, ks,
                                 preferred_element_type=jnp.float32) * scale
            dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf,
                                preferred_element_type=jnp.float32)
            dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, gf,
                                preferred_element_type=jnp.float32)
            off = kb * block
            dkc = lax.dynamic_update_slice_in_dim(
                dkc, lax.dynamic_slice_in_dim(dkc, off, block, 1) + dk_blk,
                off, axis=1)
            dvc = lax.dynamic_update_slice_in_dim(
                dvc, lax.dynamic_slice_in_dim(dvc, off, block, 1) + dv_blk,
                off, axis=1)
            return (dq, dkc, dvc), None

        if nblk == 1:
            (dq, dkc, dvc), _ = inner((dq, dkc, dvc), 0)
        else:
            (dq, dkc, dvc), _ = lax.scan(inner, (dq, dkc, dvc),
                                         jnp.arange(nblk))
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dkc = lax.ppermute(dkc, axis_name, perm)
        dvc = lax.ppermute(dvc, axis_name, perm)
        if sc is not None:
            sc = lax.ppermute(sc, axis_name, perm)
        return dq, kc, vc, dkc, dvc, sc

    dq0 = _vary(jnp.zeros((b, s_local, h, d), jnp.float32), axis_name)
    dkv0 = _vary(jnp.zeros((b, s_local, h, d), jnp.float32), axis_name)
    dq, _, _, dk, dv, _ = lax.fori_loop(
        0, n, body, (dq0, k, v, dkv0, dkv0, q_seg))
    dseg = None if segment_ids is None \
        else np.zeros(segment_ids.shape, jax.dtypes.float0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dseg


_ring.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   block_size: Optional[int] = None,
                   use_custom_vjp: bool = True,
                   segment_ids=None) -> jnp.ndarray:
    """BSHD sequence-sharded attention. q/k/v: local shards [B, Sl, H, D].

    ``segment_ids`` (round 4): the LOCAL [B, Sl] shard of packed-sequence
    ids — attention is restricted to equal ids. The k-side ids rotate
    around the ring together with their K/V blocks, in the forward AND in
    the second (backward) ring pass, so packing composes with sequence
    parallelism (VERDICT r3 weak #4).

    ``use_custom_vjp=False`` falls back to plain autodiff through the
    forward loop (O(ring_size) residuals) — kept as the numerics oracle
    for the custom backward's tests only, and for forward-mode AD
    (``jax.jvp``/``jax.linearize``), which ``jax.custom_vjp`` does not
    support.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if segment_ids is not None and segment_ids.shape != q.shape[:2]:
        raise ValueError(
            f"segment_ids must be the local [B, S_local] shard "
            f"{q.shape[:2]}, got {segment_ids.shape}")
    if use_custom_vjp:
        return _ring(q, k, v, segment_ids, scale, causal, block_size,
                     axis_name)
    out, _ = _ring_forward(q, k, v, scale, causal, block_size, axis_name,
                           segment_ids)
    return out
