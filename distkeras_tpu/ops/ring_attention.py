"""Ring attention: exact sequence-parallel attention over a mesh axis.

Absent from the reference (SURVEY §5.7: no sequence dimension sharding of
any kind) — this is the TPU build's long-context core. Each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange) while each device accumulates its
queries' attention with the online-softmax recurrence. The full [S, S]
matrix never exists anywhere, and the K/V transfer overlaps with the block
computation under XLA's latency-hiding scheduler.

Peak score memory per device is O(S_local * block) when ``block_size`` is
set (an inner ``lax.scan`` over sub-blocks of the received shard with the
same online-softmax merge), or O(S_local²) when it is None — set it once
local shards get long enough that the block-pair score tile no longer fits
comfortably in VMEM/HBM.

``ring_attention`` must be called **inside** a ``shard_map`` whose
``axis_name`` axis shards the sequence dimension (the trainer and
``MultiHeadAttention(attn_impl="ring")`` arrange this).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.ops.attention import NEG_INF


def _merge_block(m, l, acc, qf, ks, vs, q_pos, k_pos, causal):
    """One online-softmax merge of a K/V block into the (m, l, acc) carry.

    q_pos: [Sl] global query positions; k_pos: [bk] global key positions
    (shards are equal-length by construction, so there are no padding keys
    to mask — only the causal constraint). Shapes: qf [B, Sl, H, D]
    (pre-scaled f32), ks/vs [B, bk, H, D], m/l [B, H, Sl, 1],
    acc [B, Sl, H, D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if causal:
        valid = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(valid[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha.transpose(0, 2, 1, 3) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, vs.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   block_size: Optional[int] = None) -> jnp.ndarray:
    """BSHD sequence-sharded attention. q/k/v: local shards [B, Sl, H, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    qf = q.astype(jnp.float32) * scale
    q_pos = idx * s_local + jnp.arange(s_local)

    if block_size is not None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if block_size < s_local and s_local % block_size:
            raise ValueError(
                f"block_size {block_size} must divide the local shard "
                f"length {s_local}")
    if block_size is not None and block_size < s_local:
        nblk = s_local // block_size
    else:
        block_size, nblk = s_local, 1

    def body(t, carry):
        m, l, acc, kc, vc = carry
        src = (idx - t) % n                                  # block owner
        shard_pos0 = src * s_local

        def inner(inner_carry, kb):
            m, l, acc = inner_carry
            ks = lax.dynamic_slice_in_dim(kc, kb * block_size, block_size,
                                          axis=1)
            vs = lax.dynamic_slice_in_dim(vc, kb * block_size, block_size,
                                          axis=1)
            k_pos = shard_pos0 + kb * block_size + jnp.arange(block_size)
            return _merge_block(m, l, acc, qf, ks, vs, q_pos, k_pos,
                                causal), None

        if nblk == 1:
            (m, l, acc), _ = inner((m, l, acc), 0)
        else:
            (m, l, acc), _ = lax.scan(inner, (m, l, acc),
                                      jnp.arange(nblk))
        # rotate K/V to the next device (wasted on the final step, but the
        # loop stays uniform — XLA overlaps it with the block compute)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    # initial accumulators must carry the same varying-axes type as the
    # loop body's outputs (jax >= 0.7 shard_map vma check)
    def _vary(x):
        try:
            return lax.pcast(x, axis_name, to="varying")
        except (AttributeError, TypeError):
            return lax.pvary(x, axis_name)

    m0 = _vary(jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, s_local, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((b, s_local, h, d), jnp.float32))
    m, l, acc, _, _ = lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))

    l_safe = jnp.where(l == 0.0, 1.0, l)                     # [B, H, Sl, 1]
    out = acc / l_safe.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)
