"""Ring attention: exact sequence-parallel attention over a mesh axis.

Absent from the reference (SURVEY §5.7: no sequence dimension sharding of
any kind) — this is the TPU build's long-context core. Each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange) while each device accumulates its
queries' attention with the online-softmax recurrence. Memory per device is
O(S_local²) scores; the full [S, S] matrix never exists anywhere, and the
K/V transfer overlaps with the block computation under XLA's latency-hiding
scheduler.

``ring_attention`` must be called **inside** a ``shard_map`` whose
``axis_name`` axis shards the sequence dimension (the trainer and
``MultiHeadAttention(attn_impl="ring")`` arrange this).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.ops.attention import NEG_INF, causal_mask


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """BSHD sequence-sharded attention. q/k/v: local shards [B, Sl, H, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    qf = q.astype(jnp.float32) * scale

    def body(t, carry):
        m, l, acc, kc, vc = carry
        src = (idx - t) % n                                  # block owner
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            allowed = causal_mask(s_local, s_local,
                                  q_offset=idx * s_local,
                                  k_offset=src * s_local)    # [Sl, Sl]
            s = jnp.where(allowed[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha.transpose(0, 2, 1, 3) + jnp.einsum(
            "bhqk,bkhd->bqhd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        # rotate K/V to the next device (wasted on the final step, but the
        # loop stays uniform — XLA overlaps it with the block compute)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, kc, vc

    # initial accumulators must carry the same varying-axes type as the
    # loop body's outputs (jax >= 0.7 shard_map vma check)
    def _vary(x):
        try:
            return lax.pcast(x, axis_name, to="varying")
        except (AttributeError, TypeError):
            return lax.pvary(x, axis_name)

    m0 = _vary(jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, s_local, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((b, s_local, h, d), jnp.float32))
    m, l, acc, _, _ = lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))

    l_safe = jnp.where(l == 0.0, 1.0, l)                     # [B, H, Sl, 1]
    out = acc / l_safe.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)
