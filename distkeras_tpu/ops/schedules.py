"""Learning-rate schedules: pure ``step -> lr`` functions.

No reference equivalent (dist-keras forwards a fixed Keras optimizer config
to every worker). Schedules are jit-traceable scalar functions of the
optimizer's step counter, accepted anywhere a ``learning_rate`` float is
(``get_optimizer('sgd', learning_rate=cosine_decay(0.1, 10_000))``) — the
optimizer keeps the step count in its state, so schedules work unchanged
under vmap/shard_map/pjit and survive checkpoint/resume.
"""

from __future__ import annotations

from typing import Callable, Sequence, Union

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]  # int32 step -> f32 lr


def constant(value: float) -> Schedule:
    v = float(value)
    return lambda step: jnp.float32(v)


def exponential_decay(init_value: float, decay_steps: int,
                      decay_rate: float, staircase: bool = False) -> Schedule:
    v, k, r = float(init_value), int(decay_steps), float(decay_rate)

    def fn(step):
        p = step.astype(jnp.float32) / k
        if staircase:
            p = jnp.floor(p)
        return jnp.float32(v) * jnp.float32(r) ** p

    return fn


def cosine_decay(init_value: float, decay_steps: int,
                 alpha: float = 0.0, warmup_steps: int = 0) -> Schedule:
    """Linear warmup (0 -> init) over ``warmup_steps``, then cosine decay to
    ``alpha * init_value`` over the remaining ``decay_steps``."""
    v, k, a, w = float(init_value), int(decay_steps), float(alpha), \
        int(warmup_steps)

    def fn(step):
        s = step.astype(jnp.float32)
        warm = v * s / max(w, 1)
        t = jnp.clip((s - w) / max(k, 1), 0.0, 1.0)
        cos = v * (a + (1 - a) * 0.5 * (1 + jnp.cos(jnp.pi * t)))
        return jnp.where(s < w, warm, cos).astype(jnp.float32)

    return fn


def piecewise_constant(boundaries: Sequence[int],
                       values: Sequence[float]) -> Schedule:
    """``values[i]`` for steps in ``[boundaries[i-1], boundaries[i])``;
    needs ``len(values) == len(boundaries) + 1``."""
    if len(values) != len(boundaries) + 1:
        raise ValueError(
            f"need len(values) == len(boundaries) + 1, got "
            f"{len(values)} values / {len(boundaries)} boundaries")
    bs = jnp.asarray(list(boundaries), jnp.int32)
    vs = jnp.asarray(list(values), jnp.float32)

    def fn(step):
        idx = jnp.sum(step >= bs)
        return vs[idx]

    return fn


SCHEDULES = {
    "constant": constant,
    "exponential_decay": exponential_decay,
    "cosine_decay": cosine_decay,
    "piecewise_constant": piecewise_constant,
}


def get_schedule(sched: Union[str, Schedule, float], **kwargs) -> Schedule:
    if callable(sched):
        return sched
    if isinstance(sched, (int, float)):
        return constant(sched)
    try:
        factory = SCHEDULES[sched]
    except KeyError:
        raise ValueError(f"Unknown schedule {sched!r}; "
                         f"known: {sorted(SCHEDULES)}")
    return factory(**kwargs)
