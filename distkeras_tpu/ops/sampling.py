"""Fused sampling epilogue for the serving decode step.

The unfused sampler (``models.decoding._sample_vec``) walks the
[S, V] logits several times at full vocab width: rank argsorts for
top-k, a sort + softmax + cumsum for the nucleus cut, then
``jax.random.categorical`` — each an [S, V] HBM round trip at real
vocab sizes. This module folds everything AFTER the one irreducible
sort into a single Pallas pass: the kernel consumes the
temperature-scaled logits, their descending sort, and an externally
drawn gumbel field, and emits the sampled token ids directly — the
masked logits, softmax probabilities, cumulative sums, and perturbed
scores live only in VMEM.

Exactness contract (the reason the pieces factor this way):

  * ``jax.random.categorical(key, lf)`` IS
    ``argmax(lf + gumbel(key, lf.shape))`` — :func:`gumbel_noise`
    draws the SAME per-slot threefry gumbel field ``categorical``
    would, so sampling from externally drawn noise changes no bits of
    any request's token stream.
  * the reference path (off-TPU, or any misaligned shape) reuses
    ``decoding._masked_logits_vec`` — the exact mask program of the
    unfused sampler — so fused-vs-unfused is byte-identical on CPU by
    construction; ``tests/test_sampling_fused.py`` pins the kernel
    against it under ``interpret=True`` (the tier-1 oracle
    convention).
  * in-kernel masks mirror the unfused semantics exactly: rank top-k
    with stable lowest-index-first ties (reconstructed from the
    sorted row: ``count_above + tie_prefix_rank <= k``), the nucleus
    cut's exclusive-cumsum threshold over the top-k-masked sorted
    row (the masked sort is derived from the unmasked sort — the
    rank mask keeps exactly the k largest VALUES, ties only shuffle
    indices), and first-index argmax for both the greedy and the
    gumbel winner.

Alignment: vocab % 128 (lane tiling); slot rows pad to 8. Gate:
``fused_supported`` (same backend convention as every Pallas-vs-XLA
fork — ``compat.backend_is_tpu`` or a test forcing interpreter mode);
``sample_epilogue`` falls back to the reference path silently, so the
engine enables ``fused_sampling`` unconditionally.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from distkeras_tpu.compat import backend_is_tpu, tpu_compiler_params
from distkeras_tpu.ops.attention import NEG_INF

#: slot-row tile (Mosaic second-to-last-dim rule)
BLOCK_S = 8

_FORCE_INTERPRET = False


@contextlib.contextmanager
def force_interpret():
    """Run the epilogue kernel in Pallas interpreter mode regardless
    of backend — the CPU test suite's hook."""
    global _FORCE_INTERPRET
    prev = _FORCE_INTERPRET
    _FORCE_INTERPRET = True
    try:
        yield
    finally:
        _FORCE_INTERPRET = prev


def fused_supported(vocab: int) -> bool:
    """Whether the epilogue kernel runs for this vocab width."""
    if pltpu is None:
        return False
    if not (_FORCE_INTERPRET or backend_is_tpu()):
        return False
    return vocab % 128 == 0


def gumbel_noise(keys, vocab: int) -> jnp.ndarray:
    """The per-slot gumbel field ``jax.random.categorical`` would draw
    internally: one threefry ``gumbel(key, (V,), f32)`` per slot key —
    bit-identical to ``vmap(categorical)(keys, lf)``'s noise, which is
    what makes the fused and unfused streams byte-identical."""
    return jax.vmap(
        lambda k: jax.random.gumbel(k, (vocab,), jnp.float32))(keys)


def _kernel(lf_ref, srt_ref, g_ref, t_ref, k_ref, p_ref, o_ref):
    lf = lf_ref[...]                     # [bs, V] temp-scaled f32
    srt = srt_ref[...]                   # [bs, V] descending sort of lf
    g = g_ref[...]                       # [bs, V] gumbel
    temp = t_ref[...]                    # [bs, 1]
    kk = k_ref[...]                      # [bs, 1] i32
    p = p_ref[...]                       # [bs, 1]
    v = lf.shape[-1]
    iota = lax.broadcasted_iota(jnp.int32, lf.shape, 1)

    # rank top-k, stable lowest-index-first ties: the k-th largest
    # VALUE from the sorted row, then admit everything above it plus
    # the leading tied indices up to the remaining budget
    kc = jnp.clip(kk, 1, v)
    kth = jnp.sum(jnp.where(iota == kc - 1, srt, 0.0), axis=1,
                  keepdims=True)
    n_gt = jnp.sum((lf > kth).astype(jnp.int32), axis=1, keepdims=True)
    eq = lf == kth
    tie_rank = jnp.cumsum(eq.astype(jnp.int32), axis=1)      # inclusive
    keep_k = (kk <= 0) | (lf > kth) | (eq & (n_gt + tie_rank <= kc))
    lfk = jnp.where(keep_k, lf, NEG_INF)

    # the top-k-masked SORTED row derives from the unmasked sort: the
    # rank mask keeps exactly the k largest values (ties only shuffle
    # which INDEX survives, never the value multiset)
    kcount = jnp.where(kk <= 0, v, kc)
    srt_m = jnp.where(iota < kcount, srt, NEG_INF)

    # nucleus: softmax over the masked sorted row, exclusive cumsum,
    # same boundary construction as the unfused path
    mx = jnp.max(srt_m, axis=1, keepdims=True)
    ex = jnp.exp(srt_m - mx)
    probs = ex / jnp.sum(ex, axis=1, keepdims=True)
    excl = jnp.cumsum(probs, axis=1) - probs
    keep_s = excl < p
    thresh = jnp.min(jnp.where(keep_s, srt_m, jnp.inf), axis=1,
                     keepdims=True)
    lfm = jnp.where((p >= 1.0) | (lfk >= thresh), lfk, NEG_INF)

    # fused gumbel-argmax (== categorical) + greedy, first-index ties
    z = lfm + g
    zmax = jnp.max(z, axis=1, keepdims=True)
    samp = jnp.min(jnp.where(z == zmax, iota, v), axis=1)
    gmax = jnp.max(lf, axis=1, keepdims=True)
    greedy = jnp.min(jnp.where(lf == gmax, iota, v), axis=1)
    o_ref[...] = jnp.where(temp[:, 0] > 0.0, samp, greedy)[:, None]


def sample_epilogue(logits, temperature, top_k, top_p, gumbel, *,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Sampled token ids for one decode step: temperature scale,
    rank top-k, nucleus cut, gumbel draw, greedy override — one fused
    pass. ``gumbel`` comes from :func:`gumbel_noise` over the same
    per-slot keys the unfused sampler would consume. Falls back to the
    exact unfused mask program off-TPU or at misaligned vocab widths,
    so the output token stream never depends on which path ran."""
    from distkeras_tpu.models.decoding import _masked_logits_vec

    s, v = logits.shape
    if not fused_supported(v):
        lf = _masked_logits_vec(logits, temperature, top_k, top_p)
        sampled = jnp.argmax(lf + gumbel, axis=-1)
        return jnp.where(temperature > 0.0, sampled,
                         jnp.argmax(logits, axis=-1))
    if interpret is None:
        interpret = not backend_is_tpu()
    lf = logits.astype(jnp.float32)
    safe_t = jnp.where(temperature > 0.0, temperature, 1.0)
    lf = lf / safe_t[:, None]
    srt = jnp.flip(jnp.sort(lf, axis=-1), axis=-1)   # the one XLA sort
    sp = -(-s // BLOCK_S) * BLOCK_S
    pad = sp - s

    def prep(a, fill):
        return jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1),
                       constant_values=fill) if pad else a

    args = (prep(lf, NEG_INF), prep(srt, NEG_INF),
            prep(gumbel.astype(jnp.float32), 0.0),
            prep(temperature.astype(jnp.float32)[:, None], 0.0),
            prep(top_k.astype(jnp.int32)[:, None], 0),
            prep(top_p.astype(jnp.float32)[:, None], 1.0))
    out = pl.pallas_call(
        _kernel,
        grid=(sp // BLOCK_S,),
        in_specs=[
            pl.BlockSpec((BLOCK_S, v), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_S, v), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_S, v), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_S, 1), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_S, 1), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_S, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_S, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((sp, 1), jnp.int32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*args)
    return out[:s, 0]


def sample_tokens(logits, temperature, top_k, top_p, keys):
    """Drop-in replacement for ``decoding._sample_vec`` with per-slot
    keys: external gumbel + the fused epilogue. The serving engine's
    ``fused_sampling=True`` sampler."""
    g = gumbel_noise(keys, logits.shape[-1])
    return sample_epilogue(logits, temperature, top_k, top_p, g)
