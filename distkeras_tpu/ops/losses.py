"""Loss functions (Keras-name-compatible registry).

The reference passes Keras loss names straight through to ``model.compile``
inside each worker (reference: ``distkeras/workers.py :: Worker.prepare_model``
compiles with the trainer's ``loss`` kwarg). Here losses are pure functions
``(y_true, y_pred) -> scalar`` resolved from the same string names, so trainer
constructors keep the reference's ergonomics
(``loss='categorical_crossentropy'``).

All losses reduce with a mean over the batch; elementwise math happens in
float32 regardless of the model's compute dtype for numerical safety.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

EPS = 1e-7

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


# per-sample forms of the classification losses: (y_true, y_pred) ->
# (loss_per_sample, class_index_per_sample); batch dims follow y_true
# ([B] or [B, S] for token-level models). The registry's mean losses
# are defined from these so each formula lives exactly ONCE (the
# class_weight wrapper below reuses the same forms).

def _ps_categorical(y_true, y_pred):
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    ls = -jnp.sum(y_true.astype(jnp.float32) * jnp.log(p), axis=-1)
    return ls, jnp.argmax(y_true, axis=-1)


def _ps_categorical_logits(y_true, y_pred):
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    ls = -jnp.sum(y_true.astype(jnp.float32) * logp, axis=-1)
    return ls, jnp.argmax(y_true, axis=-1)


def _ps_sparse(y_true, y_pred):
    cls = y_true.astype(jnp.int32)
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    ls = -jnp.take_along_axis(jnp.log(p), cls[..., None], axis=-1)[..., 0]
    return ls, cls


def _ps_sparse_logits(y_true, y_pred):
    cls = y_true.astype(jnp.int32)
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    ls = -jnp.take_along_axis(logp, cls[..., None], axis=-1)[..., 0]
    return ls, cls


def _ps_binary(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    p = jnp.clip(y_pred.astype(jnp.float32).reshape(t.shape), EPS, 1.0 - EPS)
    ls = -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))
    return ls, t.astype(jnp.int32)


def _ps_binary_logits(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    x = y_pred.astype(jnp.float32).reshape(t.shape)
    ls = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return ls, t.astype(jnp.int32)


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(y_pred.astype(jnp.float32) -
                               y_true.astype(jnp.float32)))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred.astype(jnp.float32) -
                            y_true.astype(jnp.float32)))


def categorical_crossentropy(y_true, y_pred):
    """One-hot targets vs probability outputs (post-softmax), Keras-style."""
    return jnp.mean(_ps_categorical(y_true, y_pred)[0])


def categorical_crossentropy_from_logits(y_true, y_pred):
    """One-hot targets vs raw logits — the numerically preferred TPU path
    (fuses log_softmax into the loss; avoids a softmax round-trip)."""
    return jnp.mean(_ps_categorical_logits(y_true, y_pred)[0])


def sparse_categorical_crossentropy(y_true, y_pred):
    """Integer targets vs probability outputs."""
    return jnp.mean(_ps_sparse(y_true, y_pred)[0])


def sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    return jnp.mean(_ps_sparse_logits(y_true, y_pred)[0])


def masked_sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    """Sparse CE over logits where labels ``< 0`` are IGNORED — the
    packed/padded-sequence training loss (pair with ``segment_ids``
    attention masking; give padding label -1). The mean is over the
    non-ignored positions only, so padding density does not dilute the
    gradient scale."""
    mask = (y_true >= 0)
    ls, _ = _ps_sparse_logits(jnp.maximum(y_true, 0), y_pred)
    mf = mask.astype(jnp.float32)
    return jnp.sum(ls * mf) / jnp.maximum(jnp.sum(mf), 1.0)


# ---------------------------------------------------------------------------
# fused unembedding-projection + cross-entropy (chunked, recompute-in-VJP)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _fused_linear_xent(num_chunks: int, cdt_name: str,
                       unroll: bool = False):
    """Build the custom-VJP kernel for ``fused_linear_cross_entropy``.

    Cached per (chunk count, compute dtype) so repeated jit traces reuse
    one custom_vjp identity. NEGATIVE labels are always ignored (dropped
    from the sum AND the mean's denominator) — this single rule serves
    both the masked-loss contract (any label < 0 is padding, matching
    ``masked_sparse_categorical_crossentropy_from_logits``) and the
    wrapper's internal chunk-padding rows.
    """
    cdt = jnp.dtype(cdt_name)

    def _chunk_views(h, labels):
        n, d = h.shape
        c = n // num_chunks
        return (h.reshape(num_chunks, c, d),
                labels.reshape(num_chunks, c), c)

    @jax.custom_vjp
    def f(h, w, labels):
        return _fwd(h, w, labels)[0]

    def _fwd(h, w, labels):
        hs, ls, c = _chunk_views(h, labels)
        wc = w.astype(cdt)

        def chunk(carry, inp):
            s, n = carry
            h_c, l_c = inp
            logits = lax.dot(h_c.astype(cdt), wc,
                             preferred_element_type=jnp.float32)
            m = jnp.max(logits, axis=-1)
            lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]),
                                      axis=-1))
            safe = jnp.maximum(l_c, 0)
            tl = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
            mask = (l_c >= 0).astype(jnp.float32)
            return (s + jnp.sum((lse - tl) * mask),
                    n + jnp.sum(mask)), lse

        (s, n), lses = lax.scan(chunk, (jnp.float32(0.0), jnp.float32(0.0)),
                                (hs, ls), unroll=num_chunks if unroll else 1)
        n = jnp.maximum(n, 1.0)
        return s / n, (h, w, labels, lses.reshape(h.shape[0]), n)

    def _bwd(res, gbar):
        h, w, labels, lse, n = res
        hs, ls, c = _chunk_views(h, labels)
        lses = lse.reshape(num_chunks, c)
        wc = w.astype(cdt)
        gscale = (gbar / n).astype(jnp.float32)

        def chunk(dk, inp):
            h_c, l_c, lse_c = inp
            h_c = h_c.astype(cdt)
            logits = lax.dot(h_c, wc, preferred_element_type=jnp.float32)
            p = jnp.exp(logits - lse_c[:, None])
            g_tok = gscale * (l_c >= 0).astype(jnp.float32)
            dlog = p * g_tok[:, None]
            safe = jnp.maximum(l_c, 0)
            dlog = dlog.at[jnp.arange(c), safe].add(-g_tok)
            dlog_c = dlog.astype(cdt)
            d_h = lax.dot(dlog_c, wc.T,
                          preferred_element_type=jnp.float32)
            dk = dk + lax.dot(h_c.T, dlog_c,
                              preferred_element_type=jnp.float32)
            return dk, d_h

        dk0 = jnp.zeros((w.shape[0], w.shape[1]), jnp.float32)
        dk, dhs = lax.scan(chunk, dk0, (hs, ls, lses),
                           unroll=num_chunks if unroll else 1)
        d_h = dhs.reshape(h.shape).astype(h.dtype)
        ct_labels = np.zeros(labels.shape, jax.dtypes.float0)
        return d_h, dk.astype(w.dtype), ct_labels

    f.defvjp(_fwd, _bwd)
    return f


def fused_linear_cross_entropy(hidden, kernel, y_true, *,
                               num_chunks: int = 8,
                               ignore_index: Optional[int] = None,
                               compute_dtype=None,
                               unroll: bool = False):
    """Softmax cross-entropy FUSED with the final vocab projection,
    chunked over tokens with recompute-inside-VJP.

    ``loss = mean_i( logsumexp(h_i @ W) - (h_i @ W)[y_i] )`` without ever
    materializing the full ``[N, V]`` logits tensor: tokens are processed
    in ``num_chunks`` blocks under ``lax.scan`` — forward keeps only the
    per-token logsumexp (``[N]`` f32), backward recomputes each block's
    logits and forms ``dW`` by f32 accumulation across blocks. At the
    bench shape (16K tokens x 32K vocab) the unfused path materializes a
    ~2.1 GB f32 logits/log-softmax tensor forward AND saves it for
    backward; this path's peak extra footprint is one ``[N/num_chunks, V]``
    f32 block (~256 MB at the default), the standard memory/bandwidth
    lever of TPU LM stacks (VERDICT r3 missing #3). Extra cost: one
    recomputed projection matmul in the backward (+~6% step FLOPs at the
    bench shape; measured win in docs/PERF.md).

    ``ignore_index=-1`` (or any negative sentinel) enables the
    packed/padded-sequence contract of
    ``masked_sparse_categorical_crossentropy_from_logits``: every label
    ``< 0`` is dropped from the sum AND the mean's denominator. With
    ``ignore_index=None`` all labels must be valid class ids ``>= 0``
    (matching the plain sparse CE contract; a negative label is then
    undefined input and is dropped rather than silently clamped to class
    0). The matmuls run in ``compute_dtype`` (default: ``hidden``'s
    dtype if floating, else bf16) with f32 accumulation — slightly
    BETTER numerics than the unfused bf16 Dense output.

    When the token count does not divide ``num_chunks`` the inputs are
    zero-PADDED up to the next multiple with label ``-1`` (pads fall out
    of the masked sum exactly), so the peak block size never regresses
    toward the full [N, V] materialization this function exists to
    avoid.

    No reference analogue (the reference has no LM path; SURVEY §5.7).
    Consumed by ``parallel.worker.make_train_step(fused_vocab_head=True)``.
    """
    if ignore_index is not None and ignore_index >= 0:
        raise ValueError(
            f"ignore_index must be a negative sentinel (labels < 0 are "
            f"ignored) or None, got {ignore_index}")
    d = hidden.shape[-1]
    h = hidden.reshape(-1, d)
    labels = y_true.reshape(-1).astype(jnp.int32)
    n = h.shape[0]
    nc = max(1, min(int(num_chunks), n))
    pad = (-n) % nc
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=-1)
    if compute_dtype is None:
        compute_dtype = hidden.dtype if jnp.issubdtype(
            hidden.dtype, jnp.floating) else jnp.bfloat16
    f = _fused_linear_xent(nc, jnp.dtype(compute_dtype).name,
                           bool(unroll))
    return f(h, kernel, labels)


def binary_crossentropy(y_true, y_pred):
    return jnp.mean(_ps_binary(y_true, y_pred)[0])


def binary_crossentropy_from_logits(y_true, y_pred):
    return jnp.mean(_ps_binary_logits(y_true, y_pred)[0])


def hinge(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    # Keras-compatible: 0/1 binary labels are converted to -1/+1 (traced-safe
    # via a scalar select, no Python control flow).
    is_binary = jnp.all((t == 0.0) | (t == 1.0))
    t = jnp.where(is_binary, 2.0 * t - 1.0, t)
    return jnp.mean(jnp.maximum(0.0, 1.0 - t * y_pred.astype(jnp.float32)))


LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "categorical_crossentropy": categorical_crossentropy,
    "categorical_crossentropy_from_logits":
        categorical_crossentropy_from_logits,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_from_logits":
        sparse_categorical_crossentropy_from_logits,
    "masked_sparse_categorical_crossentropy_from_logits":
        masked_sparse_categorical_crossentropy_from_logits,
    "binary_crossentropy": binary_crossentropy,
    "binary_crossentropy_from_logits": binary_crossentropy_from_logits,
    "hinge": hinge,
}


def get_loss(loss: Union[str, LossFn]) -> LossFn:
    if callable(loss):
        return loss
    try:
        return LOSSES[loss]
    except KeyError:
        raise ValueError(f"Unknown loss {loss!r}; known: {sorted(LOSSES)}")


def with_label_smoothing(loss: Union[str, LossFn],
                         label_smoothing: float) -> LossFn:
    """Keras ``label_smoothing`` for the CATEGORICAL crossentropies: the
    target distribution becomes ``y*(1-s) + s/K`` (integer targets are
    one-hot expanded first). Usage:
    ``loss=with_label_smoothing("sparse_categorical_crossentropy_from_logits",
    0.1)`` anywhere a loss is accepted."""
    s = float(label_smoothing)
    if not 0.0 <= s < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {s}")
    smoothable = {
        "categorical_crossentropy": _ps_categorical,
        "categorical_crossentropy_from_logits": _ps_categorical_logits,
        "sparse_categorical_crossentropy": _ps_categorical,
        "sparse_categorical_crossentropy_from_logits":
            _ps_categorical_logits,
    }
    if not isinstance(loss, str) or loss not in smoothable:
        raise ValueError(
            f"label_smoothing needs a categorical crossentropy name, one "
            f"of {sorted(smoothable)}; got {loss!r}")
    per_sample = smoothable[loss]
    sparse = loss.startswith("sparse")

    def fn(y_true, y_pred):
        k = y_pred.shape[-1]
        if sparse:
            y_true = jax.nn.one_hot(y_true.astype(jnp.int32), k)
        y_true = y_true.astype(jnp.float32) * (1.0 - s) + s / k
        return jnp.mean(per_sample(y_true, y_pred)[0])

    fn.__name__ = f"{loss}_smoothed_{s}"
    return fn


# ---------------------------------------------------------------------------
# class weighting (Keras ``class_weight`` semantics)
# ---------------------------------------------------------------------------
_PER_SAMPLE = {
    "categorical_crossentropy": _ps_categorical,
    "categorical_crossentropy_from_logits": _ps_categorical_logits,
    "sparse_categorical_crossentropy": _ps_sparse,
    "sparse_categorical_crossentropy_from_logits": _ps_sparse_logits,
    "binary_crossentropy": _ps_binary,
    "binary_crossentropy_from_logits": _ps_binary_logits,
}


def with_class_weight(loss: Union[str, LossFn], class_weight) -> LossFn:
    """Keras ``class_weight`` semantics: each sample's loss is scaled by
    the weight of its TRUE class, then mean-reduced. Exposed on every
    trainer and ``model.fit`` as ``class_weight={class: weight}`` (or a
    dense weight array indexed by class).

    Classification losses only — the loss must be one of the registry
    NAMES in ``_PER_SAMPLE`` (a custom callable has no per-sample form to
    weight)."""
    if not isinstance(loss, str) or loss not in _PER_SAMPLE:
        raise ValueError(
            f"class_weight needs a classification loss name, one of "
            f"{sorted(_PER_SAMPLE)}; got {loss!r}")
    import numpy as np
    if isinstance(class_weight, dict):
        idx = np.asarray([int(k) for k in class_weight], np.int32)
        vals = np.asarray([float(class_weight[k]) for k in class_weight],
                          np.float32)
        if (idx < 0).any():
            raise ValueError(f"negative class in class_weight: {idx.min()}")
        dense = None
    else:
        dense = np.asarray(class_weight, np.float32)
    per_sample = _PER_SAMPLE[loss]
    binary = loss.startswith("binary")

    def fn(y_true, y_pred):
        ls, cls = per_sample(y_true, y_pred)
        # size the table from the STATIC class count so an out-of-table
        # class can never silently clamp onto a neighbor's weight
        # (unlisted dict classes default to 1.0, Keras-style)
        n = 2 if binary else y_pred.shape[-1]
        if dense is not None:
            if len(dense) != n:
                raise ValueError(
                    f"class_weight array has {len(dense)} entries but the "
                    f"loss sees {n} classes")
            tbl = jnp.asarray(dense)
        else:
            if idx.size and idx.max() >= n:
                raise ValueError(
                    f"class_weight has class {idx.max()} but the loss "
                    f"sees only {n} classes")
            tbl = jnp.ones((n,), jnp.float32).at[idx].set(vals)
        return jnp.mean(ls * tbl[cls])

    fn.__name__ = f"{loss}_class_weighted"
    return fn
