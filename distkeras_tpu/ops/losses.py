"""Loss functions (Keras-name-compatible registry).

The reference passes Keras loss names straight through to ``model.compile``
inside each worker (reference: ``distkeras/workers.py :: Worker.prepare_model``
compiles with the trainer's ``loss`` kwarg). Here losses are pure functions
``(y_true, y_pred) -> scalar`` resolved from the same string names, so trainer
constructors keep the reference's ergonomics
(``loss='categorical_crossentropy'``).

All losses reduce with a mean over the batch; elementwise math happens in
float32 regardless of the model's compute dtype for numerical safety.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

EPS = 1e-7

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(y_pred.astype(jnp.float32) -
                               y_true.astype(jnp.float32)))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred.astype(jnp.float32) -
                            y_true.astype(jnp.float32)))


def categorical_crossentropy(y_true, y_pred):
    """One-hot targets vs probability outputs (post-softmax), Keras-style."""
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    return -jnp.mean(jnp.sum(y_true.astype(jnp.float32) * jnp.log(p),
                             axis=-1))


def categorical_crossentropy_from_logits(y_true, y_pred):
    """One-hot targets vs raw logits — the numerically preferred TPU path
    (fuses log_softmax into the loss; avoids a softmax round-trip)."""
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(y_true.astype(jnp.float32) * logp, axis=-1))


def sparse_categorical_crossentropy(y_true, y_pred):
    """Integer targets vs probability outputs."""
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    logp = jnp.log(p)
    picked = jnp.take_along_axis(
        logp, y_true.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp, y_true.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def binary_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    t = y_true.astype(jnp.float32)
    return -jnp.mean(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))


def binary_crossentropy_from_logits(y_true, y_pred):
    x = y_pred.astype(jnp.float32)
    t = y_true.astype(jnp.float32)
    # stable formulation: max(x,0) - x*t + log(1+exp(-|x|))
    return jnp.mean(jnp.maximum(x, 0) - x * t +
                    jnp.log1p(jnp.exp(-jnp.abs(x))))


def hinge(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    # Keras-compatible: 0/1 binary labels are converted to -1/+1 (traced-safe
    # via a scalar select, no Python control flow).
    is_binary = jnp.all((t == 0.0) | (t == 1.0))
    t = jnp.where(is_binary, 2.0 * t - 1.0, t)
    return jnp.mean(jnp.maximum(0.0, 1.0 - t * y_pred.astype(jnp.float32)))


LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "categorical_crossentropy": categorical_crossentropy,
    "categorical_crossentropy_from_logits":
        categorical_crossentropy_from_logits,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_from_logits":
        sparse_categorical_crossentropy_from_logits,
    "binary_crossentropy": binary_crossentropy,
    "binary_crossentropy_from_logits": binary_crossentropy_from_logits,
    "hinge": hinge,
}


def get_loss(loss: Union[str, LossFn]) -> LossFn:
    if callable(loss):
        return loss
    try:
        return LOSSES[loss]
    except KeyError:
        raise ValueError(f"Unknown loss {loss!r}; known: {sorted(LOSSES)}")
