"""Loss functions (Keras-name-compatible registry).

The reference passes Keras loss names straight through to ``model.compile``
inside each worker (reference: ``distkeras/workers.py :: Worker.prepare_model``
compiles with the trainer's ``loss`` kwarg). Here losses are pure functions
``(y_true, y_pred) -> scalar`` resolved from the same string names, so trainer
constructors keep the reference's ergonomics
(``loss='categorical_crossentropy'``).

All losses reduce with a mean over the batch; elementwise math happens in
float32 regardless of the model's compute dtype for numerical safety.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

EPS = 1e-7

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


# per-sample forms of the classification losses: (y_true, y_pred) ->
# (loss_per_sample, class_index_per_sample); batch dims follow y_true
# ([B] or [B, S] for token-level models). The registry's mean losses
# are defined from these so each formula lives exactly ONCE (the
# class_weight wrapper below reuses the same forms).

def _ps_categorical(y_true, y_pred):
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    ls = -jnp.sum(y_true.astype(jnp.float32) * jnp.log(p), axis=-1)
    return ls, jnp.argmax(y_true, axis=-1)


def _ps_categorical_logits(y_true, y_pred):
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    ls = -jnp.sum(y_true.astype(jnp.float32) * logp, axis=-1)
    return ls, jnp.argmax(y_true, axis=-1)


def _ps_sparse(y_true, y_pred):
    cls = y_true.astype(jnp.int32)
    p = jnp.clip(y_pred.astype(jnp.float32), EPS, 1.0 - EPS)
    ls = -jnp.take_along_axis(jnp.log(p), cls[..., None], axis=-1)[..., 0]
    return ls, cls


def _ps_sparse_logits(y_true, y_pred):
    cls = y_true.astype(jnp.int32)
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    ls = -jnp.take_along_axis(logp, cls[..., None], axis=-1)[..., 0]
    return ls, cls


def _ps_binary(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    p = jnp.clip(y_pred.astype(jnp.float32).reshape(t.shape), EPS, 1.0 - EPS)
    ls = -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))
    return ls, t.astype(jnp.int32)


def _ps_binary_logits(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    x = y_pred.astype(jnp.float32).reshape(t.shape)
    ls = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return ls, t.astype(jnp.int32)


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(y_pred.astype(jnp.float32) -
                               y_true.astype(jnp.float32)))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred.astype(jnp.float32) -
                            y_true.astype(jnp.float32)))


def categorical_crossentropy(y_true, y_pred):
    """One-hot targets vs probability outputs (post-softmax), Keras-style."""
    return jnp.mean(_ps_categorical(y_true, y_pred)[0])


def categorical_crossentropy_from_logits(y_true, y_pred):
    """One-hot targets vs raw logits — the numerically preferred TPU path
    (fuses log_softmax into the loss; avoids a softmax round-trip)."""
    return jnp.mean(_ps_categorical_logits(y_true, y_pred)[0])


def sparse_categorical_crossentropy(y_true, y_pred):
    """Integer targets vs probability outputs."""
    return jnp.mean(_ps_sparse(y_true, y_pred)[0])


def sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    return jnp.mean(_ps_sparse_logits(y_true, y_pred)[0])


def masked_sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    """Sparse CE over logits where labels ``< 0`` are IGNORED — the
    packed/padded-sequence training loss (pair with ``segment_ids``
    attention masking; give padding label -1). The mean is over the
    non-ignored positions only, so padding density does not dilute the
    gradient scale."""
    mask = (y_true >= 0)
    ls, _ = _ps_sparse_logits(jnp.maximum(y_true, 0), y_pred)
    mf = mask.astype(jnp.float32)
    return jnp.sum(ls * mf) / jnp.maximum(jnp.sum(mf), 1.0)


def binary_crossentropy(y_true, y_pred):
    return jnp.mean(_ps_binary(y_true, y_pred)[0])


def binary_crossentropy_from_logits(y_true, y_pred):
    return jnp.mean(_ps_binary_logits(y_true, y_pred)[0])


def hinge(y_true, y_pred):
    t = y_true.astype(jnp.float32)
    # Keras-compatible: 0/1 binary labels are converted to -1/+1 (traced-safe
    # via a scalar select, no Python control flow).
    is_binary = jnp.all((t == 0.0) | (t == 1.0))
    t = jnp.where(is_binary, 2.0 * t - 1.0, t)
    return jnp.mean(jnp.maximum(0.0, 1.0 - t * y_pred.astype(jnp.float32)))


LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "categorical_crossentropy": categorical_crossentropy,
    "categorical_crossentropy_from_logits":
        categorical_crossentropy_from_logits,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_from_logits":
        sparse_categorical_crossentropy_from_logits,
    "masked_sparse_categorical_crossentropy_from_logits":
        masked_sparse_categorical_crossentropy_from_logits,
    "binary_crossentropy": binary_crossentropy,
    "binary_crossentropy_from_logits": binary_crossentropy_from_logits,
    "hinge": hinge,
}


def get_loss(loss: Union[str, LossFn]) -> LossFn:
    if callable(loss):
        return loss
    try:
        return LOSSES[loss]
    except KeyError:
        raise ValueError(f"Unknown loss {loss!r}; known: {sorted(LOSSES)}")


def with_label_smoothing(loss: Union[str, LossFn],
                         label_smoothing: float) -> LossFn:
    """Keras ``label_smoothing`` for the CATEGORICAL crossentropies: the
    target distribution becomes ``y*(1-s) + s/K`` (integer targets are
    one-hot expanded first). Usage:
    ``loss=with_label_smoothing("sparse_categorical_crossentropy_from_logits",
    0.1)`` anywhere a loss is accepted."""
    s = float(label_smoothing)
    if not 0.0 <= s < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {s}")
    smoothable = {
        "categorical_crossentropy": _ps_categorical,
        "categorical_crossentropy_from_logits": _ps_categorical_logits,
        "sparse_categorical_crossentropy": _ps_categorical,
        "sparse_categorical_crossentropy_from_logits":
            _ps_categorical_logits,
    }
    if not isinstance(loss, str) or loss not in smoothable:
        raise ValueError(
            f"label_smoothing needs a categorical crossentropy name, one "
            f"of {sorted(smoothable)}; got {loss!r}")
    per_sample = smoothable[loss]
    sparse = loss.startswith("sparse")

    def fn(y_true, y_pred):
        k = y_pred.shape[-1]
        if sparse:
            y_true = jax.nn.one_hot(y_true.astype(jnp.int32), k)
        y_true = y_true.astype(jnp.float32) * (1.0 - s) + s / k
        return jnp.mean(per_sample(y_true, y_pred)[0])

    fn.__name__ = f"{loss}_smoothed_{s}"
    return fn


# ---------------------------------------------------------------------------
# class weighting (Keras ``class_weight`` semantics)
# ---------------------------------------------------------------------------
_PER_SAMPLE = {
    "categorical_crossentropy": _ps_categorical,
    "categorical_crossentropy_from_logits": _ps_categorical_logits,
    "sparse_categorical_crossentropy": _ps_sparse,
    "sparse_categorical_crossentropy_from_logits": _ps_sparse_logits,
    "binary_crossentropy": _ps_binary,
    "binary_crossentropy_from_logits": _ps_binary_logits,
}


def with_class_weight(loss: Union[str, LossFn], class_weight) -> LossFn:
    """Keras ``class_weight`` semantics: each sample's loss is scaled by
    the weight of its TRUE class, then mean-reduced. Exposed on every
    trainer and ``model.fit`` as ``class_weight={class: weight}`` (or a
    dense weight array indexed by class).

    Classification losses only — the loss must be one of the registry
    NAMES in ``_PER_SAMPLE`` (a custom callable has no per-sample form to
    weight)."""
    if not isinstance(loss, str) or loss not in _PER_SAMPLE:
        raise ValueError(
            f"class_weight needs a classification loss name, one of "
            f"{sorted(_PER_SAMPLE)}; got {loss!r}")
    import numpy as np
    if isinstance(class_weight, dict):
        idx = np.asarray([int(k) for k in class_weight], np.int32)
        vals = np.asarray([float(class_weight[k]) for k in class_weight],
                          np.float32)
        if (idx < 0).any():
            raise ValueError(f"negative class in class_weight: {idx.min()}")
        dense = None
    else:
        dense = np.asarray(class_weight, np.float32)
    per_sample = _PER_SAMPLE[loss]
    binary = loss.startswith("binary")

    def fn(y_true, y_pred):
        ls, cls = per_sample(y_true, y_pred)
        # size the table from the STATIC class count so an out-of-table
        # class can never silently clamp onto a neighbor's weight
        # (unlisted dict classes default to 1.0, Keras-style)
        n = 2 if binary else y_pred.shape[-1]
        if dense is not None:
            if len(dense) != n:
                raise ValueError(
                    f"class_weight array has {len(dense)} entries but the "
                    f"loss sees {n} classes")
            tbl = jnp.asarray(dense)
        else:
            if idx.size and idx.max() >= n:
                raise ValueError(
                    f"class_weight has class {idx.max()} but the loss "
                    f"sees only {n} classes")
            tbl = jnp.ones((n,), jnp.float32).at[idx].set(vals)
        return jnp.mean(ls * tbl[cls])

    fn.__name__ = f"{loss}_class_weighted"
    return fn
