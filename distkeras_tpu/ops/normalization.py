"""Batch-norm training-mode apply with a hand-derived 2-reduction backward.

Why this exists (measured, round 3): autodiff through the naive
``y = (x - mean(x)) * rsqrt(mean(x^2) - mean(x)^2 + eps) * scale + offset``
expression produces ~5 full-tensor f32 multiply+reduce chains per BN in the
backward pass — including algebraically redundant ones of the form
``sum(g * broadcast(c))`` (a per-channel constant times ``sum(g)``) that XLA
does not simplify. On ResNet-50/v5e those chains fuse into the backward
convolutions and make them VPU-bound: backward convs were 60.4 ms of a
98.5 ms step (forward convs: 18 ms) in the round-2 profile.

The standard closed-form BN gradient needs exactly TWO reductions:

    sum_g  = sum(g)            # -> d_offset
    sum_gx = sum(g * xhat)     # -> d_scale
    dx     = scale * rinv * (g - sum_g/n - xhat * sum_gx/n)

which is algebraically identical to the autodiff result (the variance path
through ``E[x^2] - E[x]^2`` is the same function of x) at roughly half the
VPU work. The forward is unchanged — statistics are computed by the caller
(so XLA keeps fusing them into the producing convolution's epilogue) and
passed in; this function's backward folds the full d(mean)/dx and
d(var)/dx chains into ``dx`` and returns symbolic zeros for the stats
arguments (their only external consumers are the running-statistics update,
which is never differentiated).

Cross-replica BN (``axis_name``): the caller computes mean/var with
``lax.pmean``; the backward then needs ``psum`` over the same axis for the
two sums, and ``n`` counts the global batch.

No reference equivalent: the reference's Keras BN ran per-Spark-executor
on CPU (SURVEY §2.1 utils); this file is pure TPU-performance engineering.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def bn_train_apply(x, scale, offset, mean, var, eps: float,
                   axes: Tuple[int, ...], axis_name: Optional[str]):
    """``(x - mean) * rsqrt(var + eps) * scale + offset`` in f32, cast back
    to ``x.dtype``. ``mean``/``var`` must be the batch moments of ``x``
    reduced over ``axes`` (globally over ``axis_name`` if set); the custom
    backward differentiates through them analytically."""
    inv = lax.rsqrt(var + eps) * scale
    y = (x.astype(jnp.float32) - mean) * inv + offset
    return y.astype(x.dtype)


def _bn_fwd(x, scale, offset, mean, var, eps, axes, axis_name):
    rinv = lax.rsqrt(var + eps)
    y = ((x.astype(jnp.float32) - mean) * (rinv * scale) + offset) \
        .astype(x.dtype)
    return y, (x, scale, mean, rinv)


def _bn_bwd(eps, axes, axis_name, res, g):
    x, scale, mean, rinv = res
    gf = g.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean) * rinv
    sum_g = jnp.sum(gf, axis=axes)
    sum_gx = jnp.sum(gf * xhat, axis=axes)
    # d_scale/d_offset are the LOCAL sums (matching autodiff: the trainer's
    # gradient psum handles cross-replica accumulation); the dx statistics
    # terms need the GLOBAL sums because mean/var were global (pmean)
    d_scale = sum_gx
    d_offset = sum_g
    n = 1
    for a in axes:
        n *= x.shape[a]
    if axis_name is not None:
        sum_g = lax.psum(sum_g, axis_name)
        sum_gx = lax.psum(sum_gx, axis_name)
        n = n * lax.psum(1, axis_name)
    dx = ((scale * rinv) * (gf - sum_g / n - xhat * (sum_gx / n))) \
        .astype(x.dtype)
    return (dx, d_scale, d_offset,
            jnp.zeros_like(mean), jnp.zeros_like(rinv))


bn_train_apply.defvjp(_bn_fwd, _bn_bwd)
