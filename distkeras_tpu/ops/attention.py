"""Attention ops: scaled dot-product attention, RoPE, causal masking.

The reference has no attention models at all (SURVEY §5.7 — dist-keras
predates transformers; its examples are MLP/CNN/(Bi)LSTM). This module is
part of the TPU build's first-class long-context story: the functional core
consumed by ``models.attention.MultiHeadAttention``, the Pallas flash kernel
(``ops.flash_attention``) and the sequence-parallel ring variant
(``ops.ring_attention``).

Conventions:
  * Layout is **BSHD**: ``q/k/v`` are ``[batch, seq, heads, head_dim]``.
  * Softmax math is float32 regardless of input dtype (bf16-safe).
  * ``NEG_INF`` is a large finite negative instead of ``-inf`` so fully
    masked rows produce zeros, not NaNs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def causal_mask(q_len: int, k_len: int, q_offset: int = 0,
                k_offset: int = 0) -> jnp.ndarray:
    """Boolean [q_len, k_len] mask, True where attention is allowed.

    Offsets give the global position of the first row/column — used by the
    ring variant where each device holds a sequence shard.
    """
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    k_pos = k_offset + jnp.arange(k_len)[None, :]
    return q_pos >= k_pos


def dot_product_attention(q, k, v, *, causal: bool = False,
                          mask: Optional[jnp.ndarray] = None,
                          scale: Optional[float] = None,
                          window: Optional[int] = None,
                          segment_ids: Optional[jnp.ndarray] = None
                          ) -> jnp.ndarray:
    """Reference (pure-XLA) attention. BSHD in, BSHD out.

    XLA fuses this well for moderate sequence lengths; the Pallas flash
    kernel (``ops.flash_attention``) avoids materializing the [S, S] scores
    for long sequences.

    ``window=W`` (requires ``causal``) restricts each query to the last W
    keys — causal sliding-window attention.

    ``segment_ids``: [B, S] int — packed/variable-length sequences.
    Attention is restricted to positions with EQUAL ids (cross-segment
    scores are masked to NEG_INF), composing with ``causal``/``window``.
    The convention: give padding its own id (e.g. -1); padded rows then
    attend only to each other and the loss masks them out
    (``losses.masked_sparse_categorical_crossentropy_from_logits``).
    """
    head_dim = q.shape[-1]
    if scale is None:
        scale = head_dim ** -0.5
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    # [B, H, Sq, Sk] scores in f32
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        allowed = causal_mask(q.shape[1], k.shape[1])
        if window is not None:
            q_pos = jnp.arange(q.shape[1])[:, None]
            k_pos = jnp.arange(k.shape[1])[None, :]
            allowed = allowed & (k_pos > q_pos - window)
        s = jnp.where(allowed[None, None], s, NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        s = jnp.where(same[:, None], s, NEG_INF)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# rotary position embeddings
# ---------------------------------------------------------------------------

def rope_frequencies(head_dim: int, base: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies for RoPE: [head_dim // 2] float32."""
    return 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))


def apply_rope(x, positions=None, base: float = 10000.0,
               layout: str = "bshd", scale: float = 1.0):
    """Rotary position embedding on a BSHD (default) or BHSD tensor.

    ``positions``: optional [S] or [B, S] int array of global token positions
    (defaults to 0..S-1 — pass explicit positions for sequence-sharded
    shards in ring attention).

    ``scale > 1`` is linear position interpolation (Chen et al. 2023):
    positions are divided by ``scale`` so a model trained to length L
    serves length ``scale * L`` inside its trained rotary range — the
    standard cheap long-context extension.
    """
    if layout == "bhsd":
        b, h, s, d = x.shape
    else:
        b, s, h, d = x.shape
    if positions is None:
        positions = jnp.arange(s)
    positions = jnp.asarray(positions, jnp.float32)
    if scale != 1.0:
        positions = positions / scale
    if positions.ndim == 1:
        positions = positions[None, :]  # [1, S] broadcasts over batch
    freqs = rope_frequencies(d, base)                   # [D/2]
    angles = positions[..., None] * freqs               # [B?, S, D/2]
    if layout == "bhsd":
        cos = jnp.cos(angles)[:, None, :, :]            # [B?, 1, S, D/2]
        sin = jnp.sin(angles)[:, None, :, :]
    else:
        cos = jnp.cos(angles)[:, :, None, :]            # [B?, S, 1, D/2]
        sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., ::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)
