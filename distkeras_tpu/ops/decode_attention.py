"""Fused single-step decode attention as a Pallas TPU kernel.

Round 4 (VERDICT r3 weak #7 / next #7). The XLA lowering of the decode
cache contractions (``einsum("bqhgd,bhkd->bhgqk")`` with q-length 1)
is a ``multiply_reduce`` fusion: it materializes the f32 broadcast
product of the whole [L, D] cache plane in HBM before reducing —
measured 0.37 ms per layer-step at L=2113 on v5e (~3x the cache bytes,
~100 GB/s effective). This kernel fuses scores + masking + softmax +
value mixing into ONE pass over the cache per layer: each K/V tile is
read once at streaming rate, the online-softmax carry lives in VMEM
scratch, and nothing intermediate touches HBM.

Two structural lessons are baked in (both measured on v5e):

* **Program granularity.** A first cut used one program per (batch,
  head) row — 128 tiny programs per layer on the single TensorCore,
  whose per-program overhead (~2 us) swamped the 64 KB of useful DMA
  each (short-cache decode regressed 6.4K -> 2.2K tok/s). Programs now
  cover ``bh_block`` (default 8) rows at once, with the per-row math an
  unrolled loop inside the kernel; per-program DMA is bh_block x
  [block_l, D] x 2.
* **Capacity coupling.** The cache length is rounded by ``generate()``
  to the block size this module picks for the TOTAL length
  (``choose_block``): short caches use small blocks so a 136-position
  decode does not stream a 512-padded buffer.

Layout: head-major caches ``[B*Hkv, L, D]`` (matching
``models.decoding.init_cache``); queries ``[B*Hkv, G, D]`` (G = query
heads per KV head — GQA groups are the matmul M dimension, so grouped
queries make the tile MORE efficient, not less). The current decode
position ``t`` is a scalar-prefetch operand: tile columns past ``t``
skip their compute.

int8 caches pass per-token scales ``[B*Hkv, L]``; dequant happens on
the VPU inside the kernel (scores multiply by k_scale AFTER the D
contraction; probabilities multiply by v_scale BEFORE the V
contraction), so HBM traffic stays int8 + scales.

Off-TPU the caller (``models.decoding._decode_attn``) keeps the einsum
path — this kernel also runs in interpreter mode for the CPU test suite
(``tests/test_decode_kernel.py`` pins it against the einsum oracle).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from distkeras_tpu.compat import backend_is_tpu
from distkeras_tpu.ops.attention import NEG_INF

#: candidate L tile sizes, largest first — `choose_block` picks per length
BLOCK_CANDIDATES = (1024, 512, 256, 128)

#: caches shorter than this stay on the einsum path (measured: the
#: kernel's per-program overhead outweighs its single-pass read below
#: ~1K positions). generate()'s capacity rounding and _decode_attn's
#: dispatch share this one gate.
MIN_KERNEL_LEN = 1024


def choose_block(total_len: int) -> int:
    """The L tile size for a cache serving ``total_len`` positions —
    big enough to amortize per-program overhead at depth, small enough
    that a short cache is not rounded far past its real length."""
    if total_len >= 4096:
        return 1024
    if total_len >= 1024:
        return 512
    return 128


def block_of(cache_len: int) -> Optional[int]:
    """The tile size to use for an existing cache length, or None when
    no candidate divides it (caller falls back to the einsum path)."""
    for bl in BLOCK_CANDIDATES:
        if cache_len % bl == 0 and cache_len >= bl:
            return bl
    return None


def _kernel(t_ref, *refs, scale: float, block_l: int, bh_block: int,
            window, quantized: bool):
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    li = pl.program_id(1)
    nl = pl.num_programs(1)
    t = t_ref[0]

    @pl.when(li == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = li * block_l <= t
    if window is not None:
        run = jnp.logical_and(run,
                              li * block_l + block_l - 1 > t - window)

    @pl.when(run)
    def _compute():
        pos = li * block_l + lax.broadcasted_iota(
            jnp.int32, (1, block_l), 1)
        valid = pos <= t
        if window is not None:
            valid = jnp.logical_and(valid, pos > t - window)
        # unrolled per-(batch, head)-row loop: each j is one independent
        # online-softmax update — static Python unroll, bh_block copies
        for j in range(bh_block):
            q = q_ref[j]                               # [G, D]
            kblk = k_ref[j].astype(q.dtype) if quantized else k_ref[j]
            s = lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
                * scale
            if ks_ref is not None:
                s = s * ks_ref[j][None, :]             # dequant scores
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[j]
            l_prev = l_ref[j]
            acc_prev = acc_ref[j]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)                     # [G, bl] f32
            m_ref[j] = m_new
            l_ref[j] = l_prev * alpha + jnp.sum(p, axis=-1,
                                                keepdims=True)
            if vs_ref is not None:
                p = p * vs_ref[j][None, :]             # dequant values
            vblk = v_ref[j].astype(q.dtype) if quantized else v_ref[j]
            acc_ref[j] = acc_prev * alpha + lax.dot_general(
                p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(li == nl - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def decode_attention(q, k, v, t, *, scale: Optional[float] = None,
                     window: Optional[int] = None,
                     k_scale=None, v_scale=None,
                     block_l: Optional[int] = None,
                     bh_block: int = 8,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """One-step cache attention. q: [BH, G, D]; k/v: [BH, L, D] (L a
    multiple of the chosen ``block_l``; positions > t are masked); t:
    scalar int32 current position. Returns [BH, G, D] f32.
    ``k_scale``/``v_scale`` ([BH, L] f32) mark an int8 cache."""
    bh, g, d = q.shape
    L = k.shape[1]
    if block_l is None:
        block_l = block_of(L)
        if block_l is None:
            raise ValueError(
                f"no supported tile size divides cache length {L}; size "
                "the cache with decode_attention.choose_block")
    if L % block_l:
        raise ValueError(
            f"cache length {L} must be a multiple of block_l {block_l}")
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = not backend_is_tpu()
    quantized = k_scale is not None
    # Mosaic tiling wants block second-to-last dims % 8 == 0: pad the G
    # row axis to 8 (zero rows cost nothing — the kernel is read-bound)
    g_orig = g
    if g % 8:
        q = jnp.pad(q, ((0, 0), (0, 8 - g % 8), (0, 0)))
        g = q.shape[1]
    # rows per program: amortizes per-program overhead; BH must divide.
    # Round 5 (advisor): validate up front (<=0 used to ZeroDivisionError)
    # and round non-divisors to the LARGEST divisor of bh <= bh_block —
    # the old halving loop silently degraded e.g. bh_block=6, bh=8 to 1,
    # losing the amortization the parameter exists for.
    bh_block = int(bh_block)
    if bh_block < 1:
        raise ValueError(f"bh_block must be >= 1, got {bh_block}")
    bh_block = max(d for d in range(1, min(bh, bh_block) + 1)
                   if bh % d == 0)
    grid = (bh // bh_block, L // block_l)
    kernel = functools.partial(_kernel, scale=float(scale),
                               block_l=int(block_l),
                               bh_block=int(bh_block), window=window,
                               quantized=quantized)
    in_specs = [
        pl.BlockSpec((bh_block, g, d), lambda b, li, *_: (b, 0, 0)),
        pl.BlockSpec((bh_block, block_l, d), lambda b, li, *_: (b, li, 0)),
        pl.BlockSpec((bh_block, block_l, d), lambda b, li, *_: (b, li, 0)),
    ]
    operands = [q, k, v]
    if quantized:
        in_specs += [
            pl.BlockSpec((bh_block, block_l), lambda b, li, *_: (b, li)),
            pl.BlockSpec((bh_block, block_l), lambda b, li, *_: (b, li)),
        ]
        operands += [k_scale, v_scale]
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    if pltpu is None:  # pragma: no cover — no Pallas TPU support
        raise RuntimeError("decode_attention requires Pallas TPU support")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bh_block, g, d),
                               lambda b, li, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bh_block, g, 1), jnp.float32),
            pltpu.VMEM((bh_block, g, 1), jnp.float32),
            pltpu.VMEM((bh_block, g, d), jnp.float32),
        ])
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, g, d), jnp.float32),
        interpret=interpret,
        **kwargs,
    )(jnp.asarray(t, jnp.int32).reshape(1), *operands)
    return out[:, :g_orig]
