"""True paged-attention decode as a Pallas TPU kernel: K/V read
THROUGH the page table, no materialized logical view.

The paged serving data plane (rounds 12+) stored every layer's cache
as ``[N, Hkv, page_len, D]`` fixed-size pages with per-slot page
tables, but the decode step still paid one large HBM round trip per
iteration: ``models.decoding._gather_pages`` gathered each slot's
pages back into a logically contiguous ``[S, H, L, D]`` view in HBM
before ``_slot_attn_readout`` ran — writing AND re-reading the whole
resident working set every step, which is why the equal-HBM
paged-vs-slab bench sat at ~1.4x instead of the >= 2x accelerator
target (ROADMAP item 3a).

This kernel removes that copy. The grid is ``(S, P)`` — one program
per (slot, logical page) — and the PAGE TABLE IS THE INDEX MAP: the
k/v BlockSpecs look up ``table[s, p]`` from the scalar-prefetch
operand and DMA the physical page HBM -> VMEM directly. Scores,
masking, online softmax and the value mix all happen on that one
streaming read; nothing intermediate ever touches HBM. Structure
mirrors the proven slab-decode kernel (``ops.decode_attention``):
per-program state in VMEM scratch carried across the ``arbitrary``
page dimension, init at page 0, finalize at the last page, Hkv heads
unrolled inside the program so per-program DMA amortizes.

Feature contract (everything the gather path supports):

  * **GQA** — queries arrive grouped ``[S, W, Hkv, G, D]``; the
    ``W * G`` rows sharing one KV head are the matmul M dimension.
  * **Window-causal [S, W] verify windows** — window query ``j`` of
    slot ``s`` admits cache positions ``<= t[s] + j`` (and
    ``> t[s] + j - window`` for SWA models), exactly
    ``_slot_attn_readout``'s mask, so speculative
    ``verify_step_slots_paged`` rides the same kernel with W > 1.
  * **int8 caches** — per-token scales ``[N, Hkv, page_len]`` ride
    the same page-table index map; dequant happens on the VPU inside
    the kernel (scores * k_scale after the D contraction,
    probabilities * v_scale before the V contraction), so HBM traffic
    stays int8 + scales.
  * **int4 caches** (this PR) — pages arrive nibble-PACKED along the
    position axis (``[N, Hkv, page_len//2, D]`` bytes, two positions
    per byte, ``models.decoding.pack_int4``'s half-split); the kernel
    unpacks each page block on the VPU and dequantizes through the
    same per-token scale planes, halving the payload HBM read again
    vs int8. The packed byte plane must itself satisfy the int8
    sublane rule, hence the ``page_len % 64`` gate.
  * **Sentinels** — a table entry >= N (unallocated logical page)
    clamps in the index map and its program skips compute; pages
    entirely past ``t + W - 1`` (or entirely before a sliding
    window's reach) skip too, so a mostly-empty slot costs its live
    pages only.

Numerics: the page-blocked online softmax is algebraically exact but
reassociates the softmax sums relative to the gather path's one-shot
softmax — the same contract as ``ops.decode_attention`` vs the einsum
oracle (and chunked vs one-pass prefill). Greedy token identity holds
at any realistic argmax margin; ``tests/test_paged_kernel.py`` pins
the kernel against the ``_gather_pages`` reference in interpreter
mode (the off-TPU/CI oracle) across GQA/int8/window/W>1/scrambled
page orders, and end-to-end through the serving engine.

Tiling: the page block's second-to-last dim is ``page_len``, so the
Mosaic sublane rule wants ``page_len % 8 == 0`` for float caches,
``% 32`` for int8, and ``% 64`` for packed int4 (the byte plane is
``page_len // 2`` rows); ``page_aligned`` is the shared gate — callers
fall back to the gather path for unaligned pools (the engine default
``page_len=16`` qualifies for float caches).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from distkeras_tpu.compat import backend_is_tpu
from distkeras_tpu.ops.attention import NEG_INF


def page_alignment(quantized) -> int:
    """The ``page_len`` divisor the kernel's sublane tiling demands for
    a cache quantization mode. ``quantized`` spans the dtype ladder:
    falsy / a float dtype name -> 8 (f32/bf16 sublane rule), ``True`` /
    ``8`` / ``"int8"`` -> 32 (int8 sublane rule), ``4`` / ``"int4"`` ->
    64 (the packed byte plane is ``page_len // 2`` int8 rows, and THAT
    must hit the % 32 int8 rule)."""
    if isinstance(quantized, str):
        name = quantized.lower()
        if name in ("int4", "4"):
            return 64
        if name == "int8":
            return 32
        if name in ("f32", "float32", "bf16", "bfloat16", "float16"):
            return 8
        raise ValueError(f"unknown cache quantization mode {quantized!r}")
    if quantized == 4:
        return 64
    return 32 if quantized else 8


def page_aligned(page_len: int, quantized=False) -> bool:
    """Can the kernel tile this pool? The page block's sublane dim is
    ``page_len``: Mosaic wants multiples of 8 (f32/bf16) / 32 (int8) /
    64 (int4 — see :func:`page_alignment` for the full matrix)."""
    return int(page_len) % page_alignment(quantized) == 0


def _unpack4(b, dt):
    """In-kernel nibble unpack of a ``[page_len//2, D]`` packed int4
    byte block to ``[page_len, D]`` in the compute dtype. Matches
    ``models.decoding.pack_int4``'s half-split layout (byte row r =
    position r low nibble, position r + page_len//2 high nibble), so
    the sublane concat lands positions in order. All nibble math runs
    in int32 (portable two's complement on VPU and in interpret mode)."""
    b32 = b.astype(jnp.int32) & 255
    lo = b32 & 15
    lo = lo - 16 * (lo > 7)
    hi = (b32 >> 4) & 15
    hi = hi - 16 * (hi > 7)
    return jnp.concatenate([lo, hi], axis=0).astype(dt)


def _kernel(t_ref, tb_ref, *refs, scale: float, page_len: int,
            g: int, w_len: int, hkv: int, window, quantized: bool,
            int4: bool, n_pages: int, tree: bool):
    if tree:
        anc_ref, refs = refs[0], refs[1:]
    else:
        anc_ref = None
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    si = pl.program_id(0)
    pi = pl.program_id(1)
    npp = pl.num_programs(1)
    t = t_ref[si]
    rows = q_ref.shape[2]                      # W*G, padded to % 8

    @pl.when(pi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = pi * page_len
    # a page participates iff it holds any position some window query
    # admits: the union of the per-query ranges is (t - window, t+W-1]
    # (tree windows too: every node's column lies in [t, t+W-1])
    run = jnp.logical_and(start <= t + (w_len - 1),
                          tb_ref[si, pi] < n_pages)
    if window is not None:
        run = jnp.logical_and(run, start + page_len - 1 > t - window)

    @pl.when(run)
    def _compute():
        # per-row window index j = row // G (pad rows past W*G read a
        # too-permissive mask — their output is sliced off), per-column
        # global position: the _slot_attn_readout mask, page-local
        j_idx = lax.broadcasted_iota(jnp.int32, (rows, page_len), 0) // g
        pos = start + lax.broadcasted_iota(
            jnp.int32, (rows, page_len), 1)
        if anc_ref is None:
            valid = pos <= t + j_idx
            if window is not None:
                valid = jnp.logical_and(valid, pos > t + j_idx - window)
        else:
            # tree window (tree-speculation PR): the committed prefix
            # (< t) plus, for window column w2 at position t + w2, the
            # per-ROW ancestor bit — the equality-OR form keeps the
            # gather static (W is small and compile-time)
            anc_blk = anc_ref[0]               # [rows, Wpad] int32
            valid = pos < t
            for w2 in range(w_len):
                valid = jnp.logical_or(
                    valid,
                    jnp.logical_and(anc_blk[:, w2:w2 + 1] != 0,
                                    pos == t + w2))
            if window is not None:
                # each query's own position is t + depth; depth = its
                # ancestor count (self included) minus one
                depth = jnp.sum((anc_blk[:, :w_len] != 0)
                                .astype(jnp.int32),
                                axis=1, keepdims=True) - 1
                valid = jnp.logical_and(valid, pos > t + depth - window)
        # unrolled per-KV-head loop: each h is one independent
        # online-softmax update (static Python unroll, hkv copies —
        # the bh_block amortization of ops.decode_attention)
        for h in range(hkv):
            q = q_ref[0, h]                    # [rows, D]
            if int4:
                # packed page: [page_len//2, D] bytes -> [page_len, D];
                # dequant stays the shared q * scale contract below
                kblk = _unpack4(k_ref[0, h], q.dtype)
            elif quantized:
                kblk = k_ref[0, h].astype(q.dtype)
            else:
                kblk = k_ref[0, h]
            s = lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
                * scale
            if ks_ref is not None:
                s = s * ks_ref[0, h][None, :]  # dequant scores
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[h]
            l_prev = l_ref[h]
            acc_prev = acc_ref[h]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)             # [rows, page_len] f32
            m_ref[h] = m_new
            l_ref[h] = l_prev * alpha + jnp.sum(p, axis=-1,
                                                keepdims=True)
            if vs_ref is not None:
                p = p * vs_ref[0, h][None, :]  # dequant values
            if int4:
                vblk = _unpack4(v_ref[0, h], q.dtype)
            elif quantized:
                vblk = v_ref[0, h].astype(q.dtype)
            else:
                vblk = v_ref[0, h]
            acc_ref[h] = acc_prev * alpha + lax.dot_general(
                p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(pi == npp - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, t, table, *,
                           scale: Optional[float] = None,
                           window: Optional[int] = None,
                           k_scale=None, v_scale=None, anc=None,
                           interpret: Optional[bool] = None):
    """Window decode attention straight off the page pool.

    q: ``[S, W, Hkv, G, D]`` (W = 1 for plain decode, k+1 for the
    speculative verify window); k_pages/v_pages: ``[N, Hkv, page_len,
    D]`` (int8 with ``k_scale``/``v_scale`` ``[N, Hkv, page_len]``);
    t: ``[S]`` int32 per-slot window start positions; table:
    ``[S, P]`` int32 page tables (entries >= N are the unallocated
    sentinel — skipped). Returns ``[S, W, Hkv, G, D]`` f32, the
    masked-softmax attention of each window query over its slot's
    cache positions (``window`` adds the SWA band).

    ``anc`` (tree speculation, ``[S, W, W]`` bool): switch the
    window-causal mask to a per-slot token-TREE mask — window query i
    admits the committed prefix (``< t``) plus window column j's
    position ``t + j`` iff ``anc[s, i, j]`` (node j is i or one of its
    ancestors; the engine derives the mask from the draft's
    parent-index vectors). SWA models derive each node's own position
    from its ancestor count (``t + depth``). A lower-triangular ``anc``
    reproduces the plain window-causal mask exactly."""
    s, w_len, hkv, g, d = q.shape
    n_pages, _, payload_rows, _ = k_pages.shape
    n_logical = table.shape[1]
    quantized = k_scale is not None
    # int4 pools arrive nibble-PACKED along the position axis (pack_
    # int4's half-split): the payload block holds page_len // 2 byte
    # rows while the per-position scale plane keeps the true page_len —
    # that shape disagreement IS the int4 signal (no extra flag to
    # thread through jit)
    int4 = quantized and k_scale.shape[2] != payload_rows
    page_len = k_scale.shape[2] if int4 else payload_rows
    if int4 and page_len != 2 * payload_rows:
        raise ValueError(
            f"int4 payload rows {payload_rows} do not match scale "
            f"plane page_len {page_len} (expected page_len // 2)")
    mode = "int4" if int4 else ("int8" if quantized else False)
    if not page_aligned(page_len, mode):
        raise ValueError(
            f"page_len {page_len} is not kernel-tileable "
            f"(% {page_alignment(mode)} for "
            f"{mode or 'float'} pages); "
            "use models.decoding._gather_pages instead")
    if scale is None:
        scale = d ** -0.5
    if anc is not None and w_len > 128:
        raise ValueError(
            f"tree window {w_len} exceeds the kernel's one-tile "
            "ancestor-mask lane budget (128 nodes)")
    if interpret is None:
        interpret = not backend_is_tpu()
    if pltpu is None:  # pragma: no cover — no Pallas TPU support
        raise RuntimeError(
            "paged_decode_attention requires Pallas TPU support")
    # rows = W*G is the per-head matmul M dim; pad to the 8-row
    # sublane rule (zero rows are independent softmaxes, sliced off)
    rows = w_len * g
    qr = q.transpose(0, 2, 1, 3, 4).reshape(s, hkv, rows, d)
    pad = (-rows) % 8
    if pad:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, pad), (0, 0)))
    rows_p = rows + pad

    def q_map(si, pi, *_):
        return (si, 0, 0, 0)

    def kv_map(si, pi, t_ref, tb_ref):
        # THE page-table indirection: the physical page id is the
        # block index (sentinels clamp; their program skips compute)
        return (jnp.minimum(tb_ref[si, pi], n_pages - 1), 0, 0, 0)

    def sc_map(si, pi, t_ref, tb_ref):
        return (jnp.minimum(tb_ref[si, pi], n_pages - 1), 0, 0)

    def anc_map(si, pi, t_ref, tb_ref):
        return (si, 0, 0)

    in_specs = []
    operands = []
    if anc is not None:
        # the ancestor mask as a per-slot [rows, W] int32 plane: each
        # query row repeats its window node's mask (G query heads share
        # one node), rows padded with the q padding, the node axis
        # padded to the 128-lane tile
        anc_rows = jnp.repeat(jnp.asarray(anc, jnp.int32), g, axis=1)
        anc_rows = jnp.pad(anc_rows,
                           ((0, 0), (0, pad), (0, 128 - w_len)))
        in_specs.append(pl.BlockSpec((1, rows_p, 128), anc_map))
        operands.append(anc_rows)
    in_specs += [
        pl.BlockSpec((1, hkv, rows_p, d), q_map),
        pl.BlockSpec((1, hkv, payload_rows, d), kv_map),
        pl.BlockSpec((1, hkv, payload_rows, d), kv_map),
    ]
    operands += [qr, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((1, hkv, page_len), sc_map),
                     pl.BlockSpec((1, hkv, page_len), sc_map)]
        operands += [k_scale, v_scale]
    kernel = functools.partial(
        _kernel, scale=float(scale), page_len=int(page_len), g=int(g),
        w_len=int(w_len), hkv=int(hkv), window=window,
        quantized=quantized, int4=int4, n_pages=int(n_pages),
        tree=anc is not None)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, n_logical),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hkv, rows_p, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((hkv, rows_p, 1), jnp.float32),
            pltpu.VMEM((hkv, rows_p, 1), jnp.float32),
            pltpu.VMEM((hkv, rows_p, d), jnp.float32),
        ])
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hkv, rows_p, d), jnp.float32),
        interpret=interpret,
        **kwargs,
    )(jnp.asarray(t, jnp.int32), jnp.asarray(table, jnp.int32),
      *operands)
    return out[:, :, :rows].reshape(s, hkv, w_len, g, d) \
        .transpose(0, 2, 1, 3, 4)
