"""Pure ops: losses, metrics, optimizers, attention."""

from distkeras_tpu.ops.attention import (  # noqa: F401
    apply_rope, causal_mask, dot_product_attention)
from distkeras_tpu.ops.ring_attention import ring_attention  # noqa: F401
from distkeras_tpu.ops.ulysses import ulysses_attention  # noqa: F401


def __getattr__(name):
    # lazy: keep the Pallas dependency off the common import path (losses/
    # optimizer-only consumers, and jax builds without pallas)
    if name == "flash_attention":
        from distkeras_tpu.ops.flash_attention import flash_attention
        return flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from distkeras_tpu.ops.losses import (  # noqa: F401
    LOSSES, fused_linear_cross_entropy, get_loss, with_class_weight,
    with_label_smoothing)
from distkeras_tpu.ops.metrics import METRICS, get_metric  # noqa: F401
from distkeras_tpu.ops.optimizers import (  # noqa: F401
    OPTIMIZERS, Optimizer, apply_updates, get_optimizer)
from distkeras_tpu.ops.schedules import SCHEDULES, get_schedule  # noqa: F401
