"""Flash attention as a Pallas TPU kernel (blockwise, online softmax).

Absent from the reference (no attention models; SURVEY §5.7) — this is the
TPU build's hot-op kernel for the long-context path. The forward pass never
materializes the ``[S, S]`` score matrix: the grid is
``(batch*heads, q_blocks, k_blocks)`` with the K axis innermost ("arbitrary"
= sequential on TPU), so exactly one ``[block_k, D]`` tile of K and V is
resident in VMEM at a time while the online-softmax carry (running max
``m``, normalizer ``l``, accumulator ``acc``) persists in VMEM scratch
across the K sweep. Causal q/k tiles above the diagonal skip their compute
via ``pl.when``. Sequence lengths that don't divide the block sizes are
zero-padded and the pad keys masked off.

The backward pass is in-kernel too (two Pallas kernels: dq sweeps K blocks
innermost; dk/dv sweeps Q blocks innermost, both recomputing probabilities
from the saved log-sum-exp with f32 VMEM accumulators) — the probability
tile never touches HBM. A blockwise XLA-scan backward is retained for
interpreter/CPU runs and as a cross-check oracle (``bwd="xla"``). Current
record on a v5e (``bench.py --model lm``, 218M LM, B8 H16 S2048 D64
causal bf16, kernel backward + BHSD layer path + tuned blocks):
**64.2K tokens/sec end to end, 2.15x the fused-XLA attention path**
(36% MFU; repeat runs land 64.1-64.2K / 2.13-2.15x through the
tunnel — docs/PERF.md is the authoritative record, with the history
of the intermediate cuts).

On non-TPU backends the kernel runs in Pallas interpreter mode (tests) or
falls back to the fused-XLA reference (``ops.attention``) for speed.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from distkeras_tpu.compat import backend_is_tpu
from distkeras_tpu.ops.attention import (NEG_INF, causal_mask,
                                         dot_product_attention)

# Measured on TPU v5e (causal bf16, fwd+bwd, BHSD, steady state —
# the tunneled backend's FIRST timed loop after compile can pay a one-off
# ~0.5 s lazy-init cost; always discard trial 0 when benchmarking here):
# 512/1024 beats 512/512 by ~10-15% at both S=2048 (14.8 vs 17.5 ms,
# B8 H16) and S=8192 (22.0 vs 24-25 ms, B2 H8). Score tile at 512x1024
# f32 is 2 MB of VMEM, safe through D=256.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _window_kblocks(block_q: int, block_k: int, nk: int,
                    window, nq: int) -> int:
    """Number of k-grid steps per q block under a sliding window: the
    reachable key span per q block is ``block_q + window - 1`` positions,
    so the k-axis grid shrinks from ``nk`` to O(window/block_k) — skipped
    tiles then never pay their K/V DMA (they are not in the grid at all),
    instead of being ``pl.when``-skipped compute with full-cost DMA.
    Computed as the EXACT trace-time maximum over q blocks (one fewer
    step than the closed form when window/block_q align to block_k)."""
    if window is None:
        return nk
    best = 1
    for qi in range(nq):
        last = min(nk - 1, (qi * block_q + block_q - 1) // block_k)
        first = max(0, (qi * block_q - window + 1) // block_k)
        best = max(best, last - first + 1)
    return min(nk, best)


def _k_base(qi, block_q: int, block_k: int, nkw: int):
    """First k block visited for q block ``qi`` (window remap): the last
    ``nkw`` blocks ending at the causal diagonal block, clamped at 0.
    Shared by the BlockSpec index maps and the kernels' position math."""
    end = (qi * block_q + block_q - 1) // block_k
    return jnp.maximum(0, end - (nkw - 1))


def _needs_mask(qi, kb, block_q: int, block_k: int, causal: bool,
                window, k_len: int, has_seg: bool):
    """Does the (qi, kb) tile intersect any mask edge? Returns Python
    ``True`` when masking is unconditionally required (segment ids are
    data-dependent), else a traced bool over the program ids. A causal
    tile is mask-free when every query position >= every key position
    (min q_pos >= max k_pos); a windowed tile when every key is within
    every query's reach; the pad mask only touches the final key block.
    """
    if has_seg:
        return True
    need = None
    if causal:
        need = qi * block_q < kb * block_k + block_k - 1
    if window is not None:
        w_edge = kb * block_k <= qi * block_q + block_q - 1 - window
        need = w_edge if need is None else (need | w_edge)
    if k_len % block_k:
        pad_edge = (kb + 1) * block_k > k_len
        need = pad_edge if need is None else (need | pad_edge)
    if need is None:
        return False        # non-causal, no window, no padding: clear
    return need


def _mask_dispatch(run, need, masked_fn, clear_fn):
    """Emit the masked and/or clear tile bodies under ``pl.when`` guards
    per ``_needs_mask``'s verdict (Python bool = one static body; traced
    bool = both bodies, selected per tile at run time)."""
    if need is True:
        pl.when(run)(masked_fn)
    elif need is False:
        pl.when(run)(clear_fn)
    else:
        pl.when(jnp.logical_and(run, need))(masked_fn)
        pl.when(jnp.logical_and(run, jnp.logical_not(need)))(clear_fn)


def _fwd_kernel(*refs, scale: float, causal: bool, k_len: int,
                window=None, nkw=None, has_seg: bool = False):
    """One (batch*head, q_block, k_block) program.

    Block shapes: q_ref [1, bq, D]; k_ref/v_ref [1, bk, D];
    o_ref [1, bq, D]; lse_ref [1, bq, 1] (the trailing singleton keeps the
    block's last-two dims Mosaic-tileable: (bq, 1) with bq % 8 == 0 and 1
    equal to the full array dim — a [1, bq] block fails TPU lowering).
    Scratch m/l [bq, 1], acc [bq, D] persist across the (sequential,
    innermost) k grid axis. Under a sliding window the k grid axis is
    REMAPPED: grid step ``ki`` addresses actual k block
    ``_k_base(qi) + ki`` (see ``_window_kblocks``). With ``has_seg``
    two extra [1, blk, 1] int32 refs carry packed segment ids; scores
    with unequal ids are masked (packed-sequence support).
    """
    if has_seg:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
         o_ref, lse_ref, m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = refs
        qseg_ref = kseg_ref = None
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    kb = ki if nkw is None else _k_base(qi, block_q, block_k, nkw) + ki

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: tiles strictly above the diagonal contribute nothing;
    # sliding window: tiles entirely OLDER than any query's window start
    # contribute nothing either
    run = (kb * block_k <= qi * block_q + block_q - 1) if causal \
        else (kb >= 0)
    if window is not None:
        run = jnp.logical_and(
            run, kb * block_k + block_k - 1 > qi * block_q - window)

    def _scores():
        # matmul inputs stay in the STORED dtype (bf16 for bf16 models)
        # with f32 accumulation — the MXU's native mode. Upcasting inputs
        # to f32 forces multi-pass f32 matmuls (~3-6x slower); round 4
        # measured the f32-input kernel at ~22% MXU on v5e. Scale is
        # applied to the f32 scores, not the bf16 q, so no precision is
        # lost relative to the old `q.astype(f32) * scale` form.
        return lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32) * scale

    def _mask(s):
        q_pos = (qi * block_q +
                 lax.broadcasted_iota(jnp.int32, s.shape, 0))
        k_pos = (kb * block_k +
                 lax.broadcasted_iota(jnp.int32, s.shape, 1))
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if window is not None:
            s = jnp.where(k_pos > q_pos - window, s, NEG_INF)
        # mask zero-padded keys past the true sequence end
        if k_len % block_k:
            s = jnp.where(k_pos < k_len, s, NEG_INF)
        if qseg_ref is not None:
            same = qseg_ref[0, :, 0][:, None] == kseg_ref[0, :, 0][None, :]
            s = jnp.where(same, s, NEG_INF)
        return s

    def _merge(s):
        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p is cast to the value dtype for the PV matmul (f32 accumulate);
        # p in [0, 1] so bf16's relative precision bounds the elementwise
        # error at ~2^-8 of each probability — the flash-on-TPU standard
        acc_ref[:] = acc_prev * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # tile-static mask specialization (round 4): the kernels are
    # VPU-bound, not MXU-bound (measured — the bf16-input change moved
    # nothing), so interior tiles skip the whole iota/compare/select
    # chain. A tile needs masking only if the causal diagonal, the
    # window's trailing edge, or the key padding actually intersects it
    # — a predicate of the program ids.
    need = _needs_mask(qi, kb, block_q, block_k, causal, window, k_len,
                       has_seg)
    _mask_dispatch(run, need,
                   lambda: _merge(_mask(_scores())),
                   lambda: _merge(_scores()))

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l_safe)


def _pad_seq(x, block: int, axis: int = 1):
    s = x.shape[axis]
    pad = (-s) % block
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    return x


def _seg_blocks(segment_ids, sq_p: int, sk_p: int):
    """[B, S] int segment ids -> padded [B, S_p, 1] int32 q/k variants
    (pads get -1: they never match a real segment, and real ``-1``
    padding tokens only reach k pads when no k_len masking applies —
    harmless, those rows are loss-masked)."""
    seg = jnp.asarray(segment_ids, jnp.int32)
    b, s = seg.shape
    segq = jnp.pad(seg, ((0, 0), (0, sq_p - s)), constant_values=-1)
    segk = jnp.pad(seg, ((0, 0), (0, sk_p - s)), constant_values=-1)
    return segq[..., None], segk[..., None]


def _flash_forward(q, k, v, scale: float, causal: bool, block_q: int,
                   block_k: int, interpret: bool, bhsd: bool = False,
                   window=None, segment_ids=None):
    if bhsd:
        b, h, sq, d = q.shape
        sk = k.shape[2]
        seq_axis = 2
    else:
        b, sq, h, d = q.shape
        sk = k.shape[1]
        seq_axis = 1
    # clamp to the (8-rounded) sequence length: Mosaic requires the block's
    # second-to-last dim % 8 == 0, so a raw min(block, seq) would fail to
    # lower for seq in (block, 8k) that isn't a multiple of 8 — the padder
    # below then pads seq up to the rounded block
    round8 = lambda n: max(8, -(-n // 8) * 8)
    block_q = min(block_q, round8(sq))
    block_k = min(block_k, round8(sk))
    qp = _pad_seq(q, block_q, seq_axis)
    kp = _pad_seq(k, block_k, seq_axis)
    vp = _pad_seq(v, block_k, seq_axis)
    sq_p, sk_p = qp.shape[seq_axis], kp.shape[seq_axis]

    if bhsd:
        # BHSD -> (B*H, S, D) is a FREE reshape (no data movement) — the
        # layout the layer uses when it targets this kernel
        qf = qp.reshape(b * h, sq_p, d)
        kf = kp.reshape(b * h, sk_p, d)
        vf = vp.reshape(b * h, sk_p, d)
    else:
        # BSHD -> (B*H, S, D): one grid row per (batch, head)
        qf = qp.transpose(0, 2, 1, 3).reshape(b * h, sq_p, d)
        kf = kp.transpose(0, 2, 1, 3).reshape(b * h, sk_p, d)
        vf = vp.transpose(0, 2, 1, 3).reshape(b * h, sk_p, d)

    nk = sk_p // block_k
    nkw = _window_kblocks(block_q, block_k, nk, window,
                          sq_p // block_q)
    remap = nkw < nk
    grid = (b * h, sq_p // block_q, nkw)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               k_len=sk, window=window,
                               nkw=nkw if remap else None,
                               has_seg=segment_ids is not None)

    def k_map(bh, qi, ki):
        if remap:
            return (bh, _k_base(qi, block_q, block_k, nkw) + ki, 0)
        return (bh, ki, 0)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), k_map),
        pl.BlockSpec((1, block_k, d), k_map),
    ]
    operands = [qf, kf, vf]
    if segment_ids is not None:
        segq, segk = _seg_blocks(segment_ids, sq_p, sk_p)
        # segment ids are per-BATCH: block index maps divide the b*h grid
        # row back down to the batch row
        in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, ki: (bh // h, qi, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda bh, qi, ki: (bh // h,) + k_map(bh, qi,
                                                               ki)[1:]),
        ]
        operands += [segq, segk]
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(*operands)
    if bhsd:
        out = out.reshape(b, h, sq_p, d)[:, :, :sq]
    else:
        out = out.reshape(b, h, sq_p, d).transpose(0, 2, 1, 3)[:, :sq]
    lse = lse.reshape(b, h, sq_p)[:, :, :sq]
    return out, lse


def _bwd_dq_kernel(*refs, scale: float, causal: bool, k_len: int,
                   window=None, nkw=None, has_seg: bool = False):
    """dq pass: one (batch*head, q_block, k_block) program, K innermost.
    ``dq_acc`` [bq, D] f32 persists across the K sweep. Window remap as
    in ``_fwd_kernel``; ``has_seg`` adds packed-segment masking."""
    if has_seg:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
         dq_acc) = refs
        qseg_ref = kseg_ref = None
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    kb = ki if nkw is None else _k_base(qi, block_q, block_k, nkw) + ki

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (kb * block_k <= qi * block_q + block_q - 1) if causal \
        else (kb >= 0)
    if window is not None:
        run = jnp.logical_and(
            run, kb * block_k + block_k - 1
            > qi * block_q - window)

    def _mask(s):
        q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if window is not None:
            s = jnp.where(k_pos > q_pos - window, s, NEG_INF)
        if k_len % block_k:
            s = jnp.where(k_pos < k_len, s, NEG_INF)
        if qseg_ref is not None:
            same = qseg_ref[0, :, 0][:, None] == kseg_ref[0, :, 0][None, :]
            s = jnp.where(same, s, NEG_INF)
        return s

    def _compute(mask):
        # bf16 matmul inputs + f32 accumulation throughout (see
        # _fwd_kernel); scale folds into the f32 score/grad tensors
        s = lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if mask:
            s = _mask(s)
        p = jnp.exp(s - lse_ref[0])                        # [bq, bk]
        dp = lax.dot_general(g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0])).astype(k_ref.dtype)
        dq_acc[:] += lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    need = _needs_mask(qi, kb, block_q, block_k, causal, window, k_len,
                       qseg_ref is not None)
    _mask_dispatch(run, need,
                   lambda: _compute(True), lambda: _compute(False))

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _window_qblocks(block_q: int, block_k: int, nq: int,
                    window, nk: int) -> int:
    """Mirror of ``_window_kblocks`` for the dk/dv pass: the reachable
    query span per k block is ``block_k + window - 1`` positions. Exact
    trace-time maximum over k blocks."""
    if window is None:
        return nq
    best = 1
    for ki in range(nk):
        first = min(nq - 1, (ki * block_k) // block_q)
        last = min(nq - 1,
                   (ki * block_k + block_k - 1 + window - 1) // block_q)
        best = max(best, last - first + 1)
    return min(nq, best)


def _q_base(ki, block_q: int, block_k: int, nq: int, nqw: int):
    """First q block visited for k block ``ki`` (window remap). Clamped
    from ABOVE to ``nq - nqw`` so every program stays in range without
    any q block appearing twice in one sweep (a double-visit would
    double-count its dk/dv contribution)."""
    return jnp.minimum((ki * block_k) // block_q, nq - nqw)


def _bwd_dkv_kernel(*refs, scale: float, causal: bool, k_len: int,
                    window=None, nq=None, nqw=None, has_seg: bool = False):
    """dk/dv pass: one (batch*head, k_block, q_block) program, Q innermost.
    ``dk_acc``/``dv_acc`` [bk, D] f32 persist across the Q sweep. Window
    remap: grid step ``qi`` addresses actual q block ``_q_base(ki) + qi``."""
    if has_seg:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref,
         dk_acc, dv_acc) = refs
        qseg_ref = kseg_ref = None
    ki, qi = pl.program_id(1), pl.program_id(2)
    block_k, block_q = k_ref.shape[1], q_ref.shape[1]
    qb = qi if nqw is None else _q_base(ki, block_q, block_k, nq, nqw) + qi

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: q tiles entirely above the diagonal see none of this k
    # block; sliding window: q tiles entirely NEWER than every key's
    # window reach see none of it either
    run = (qb * block_q + block_q - 1 >= ki * block_k) if causal \
        else (qb >= 0)
    if window is not None:
        run = jnp.logical_and(
            run, qb * block_q
            < ki * block_k + block_k - 1 + window)

    def _mask(s):
        q_pos = qb * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if window is not None:
            s = jnp.where(k_pos > q_pos - window, s, NEG_INF)
        if k_len % block_k:
            s = jnp.where(k_pos < k_len, s, NEG_INF)
        if qseg_ref is not None:
            same = qseg_ref[0, :, 0][:, None] == kseg_ref[0, :, 0][None, :]
            s = jnp.where(same, s, NEG_INF)
        return s

    def _compute(mask):
        # bf16 matmul inputs + f32 accumulation (see _fwd_kernel); the
        # dk contribution applies scale to the f32 accumulator instead of
        # pre-scaling q (dot(ds, q*scale) == scale * dot(ds, q))
        s = lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if mask:
            s = _mask(s)
        p = jnp.exp(s - lse_ref[0])                        # [bq, bk]
        dv_acc[:] += lax.dot_general(
            p.astype(g_ref.dtype), g_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0])).astype(q_ref.dtype)
        dk_acc[:] += lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    need = _needs_mask(qb, ki, block_q, block_k, causal, window, k_len,
                       qseg_ref is not None)
    _mask_dispatch(run, need,
                   lambda: _compute(True), lambda: _compute(False))

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward_pallas(res, g, scale: float, causal: bool,
                           block_q: int, block_k: int, interpret: bool,
                           bhsd: bool = False, window=None):
    """In-kernel backward: the [bq, bk] probability tile lives only in
    VMEM; f32 accumulators carry across the sequential grid axis."""
    q, k, v, out, lse, segment_ids = res
    if bhsd:
        b, h, sq, d = q.shape
        sk = k.shape[2]
        seq_axis = 2
    else:
        b, sq, h, d = q.shape
        sk = k.shape[1]
        seq_axis = 1
    round8 = lambda n: max(8, -(-n // 8) * 8)
    block_q = min(block_q, round8(sq))
    block_k = min(block_k, round8(sk))
    qp, gp = _pad_seq(q, block_q, seq_axis), _pad_seq(g, block_q, seq_axis)
    kp, vp = _pad_seq(k, block_k, seq_axis), _pad_seq(v, block_k, seq_axis)
    sq_p, sk_p = qp.shape[seq_axis], kp.shape[seq_axis]

    # delta_i = rowsum(dO * O) (flash trick); pad rows contribute zeros
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                   # [B, Sq, H] or [B, H, Sq]
    deltaf = (delta if bhsd else delta.transpose(0, 2, 1)) \
        .reshape(b * h, sq, 1)
    lsef = lse.reshape(b * h, sq, 1)
    pad_q = sq_p - sq
    if pad_q:
        deltaf = jnp.pad(deltaf, ((0, 0), (0, pad_q), (0, 0)))
        # pad lse with zeros: padded q rows have g = 0, so p's garbage
        # rows multiply into zero contributions everywhere
        lsef = jnp.pad(lsef, ((0, 0), (0, pad_q), (0, 0)))

    if bhsd:
        to_flat = lambda x: x.reshape(b * h, x.shape[2], d)  # free
    else:
        to_flat = lambda x: x.transpose(0, 2, 1, 3).reshape(
            b * h, x.shape[1], d)
    qf, kf, vf, gf = to_flat(qp), to_flat(kp), to_flat(vp), to_flat(gp)

    nq, nk = sq_p // block_q, sk_p // block_k
    nkw = _window_kblocks(block_q, block_k, nk, window, nq)
    nqw = _window_qblocks(block_q, block_k, nq, window, nk)
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))

    def k_map(bh, qi, ki):
        if nkw < nk:
            return (bh, _k_base(qi, block_q, block_k, nkw) + ki, 0)
        return (bh, ki, 0)

    k_spec = pl.BlockSpec((1, block_k, d), k_map)
    row_q = pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0))
    in_specs = [q_spec, k_spec, k_spec, q_spec, row_q, row_q]
    operands = [qf, kf, vf, gf, lsef, deltaf]
    if segment_ids is not None:
        segq, segk = _seg_blocks(segment_ids, sq_p, sk_p)
        in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, ki: (bh // h, qi, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda bh, qi, ki: (bh // h,) + k_map(bh, qi,
                                                               ki)[1:]),
        ]
        operands += [segq, segk]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          k_len=sk, window=window,
                          nkw=nkw if nkw < nk else None,
                          has_seg=segment_ids is not None),
        grid=(b * h, nq, nkw),
        in_specs=in_specs,
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret, **kwargs,
    )(*operands)[0]

    # second pass: k blocks parallel, q innermost (window-remapped)
    def q_map2(bh, ki, qi):
        if nqw < nq:
            return (bh, _q_base(ki, block_q, block_k, nq, nqw) + qi, 0)
        return (bh, qi, 0)

    q_spec2 = pl.BlockSpec((1, block_q, d), q_map2)
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    row_q2 = pl.BlockSpec((1, block_q, 1), q_map2)
    in_specs2 = [q_spec2, k_spec2, k_spec2, q_spec2, row_q2, row_q2]
    operands2 = [qf, kf, vf, gf, lsef, deltaf]
    if segment_ids is not None:
        segq, segk = _seg_blocks(segment_ids, sq_p, sk_p)
        in_specs2 += [
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, ki, qi: (bh // h,) + q_map2(bh, ki,
                                                                qi)[1:]),
            pl.BlockSpec((1, block_k, 1),
                         lambda bh, ki, qi: (bh // h, ki, 0)),
        ]
        operands2 += [segq, segk]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          k_len=sk, window=window,
                          nq=nq if nqw < nq else None,
                          nqw=nqw if nqw < nq else None,
                          has_seg=segment_ids is not None),
        grid=(b * h, nk, nqw),
        in_specs=in_specs2,
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b * h, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk_p, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret, **kwargs,
    )(*operands2)

    if bhsd:
        unflat = lambda x, s: x.reshape(b, h, x.shape[1], d)[:, :, :s]
    else:
        unflat = lambda x, s: x.reshape(b, h, x.shape[1], d) \
            .transpose(0, 2, 1, 3)[:, :s]
    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


def _flash_backward(res, g, scale: float, causal: bool, block_k: int,
                    window=None):
    """Blockwise XLA backward: scan over K/V blocks, recompute P from lse."""
    q, k, v, out, lse, segment_ids = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    pad = (-sk) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    seg = None
    if segment_ids is not None:
        seg = jnp.pad(jnp.asarray(segment_ids, jnp.int32),
                      ((0, 0), (0, pad)), constant_values=-1)

    qf = q.astype(jnp.float32) * scale
    g32 = g.astype(jnp.float32)
    # delta_i = sum_j P_ij dP_ij = rowsum(dO * O)  (flash attention trick)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)   # [B, Sq, H]

    nkb = (sk + pad) // block_k

    def body(dq_acc, kb):
        ks = lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=1)
        vs = lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        allowed = causal_mask(sq, block_k, k_offset=kb * block_k) \
            if causal else True
        if window is not None:
            q_pos = jnp.arange(sq)[:, None]
            k_pos = (kb * block_k + jnp.arange(block_k))[None, :]
            allowed = jnp.logical_and(allowed, k_pos > q_pos - window)
        k_valid = (kb * block_k + jnp.arange(block_k)) < sk
        mask = jnp.logical_and(allowed, k_valid[None, :]) if causal \
            else k_valid[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
        if seg is not None:
            ksg = lax.dynamic_slice_in_dim(seg, kb * block_k, block_k,
                                           axis=1)
            same = seg[:, :sq, None] == ksg[:, None, :]     # [B, Sq, bk]
            s = jnp.where(same[:, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                       # [B,H,Sq,bk]
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, g32,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", g32, vs.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta.transpose(0, 2, 1)[..., None])   # [B,H,Sq,bk]
        dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, ks.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf,
                        preferred_element_type=jnp.float32)
        return dq_acc + dq_blk, (dk, dv)

    dq, (dks, dvs) = lax.scan(body, jnp.zeros(q.shape, jnp.float32),
                              jnp.arange(nkb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, sk + pad, h, d)[:, :sk]
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, sk + pad, h, d)[:, :sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, segment_ids, scale, causal, block_q, block_k,
           interpret, bwd, bhsd, window):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                            interpret, bhsd, window, segment_ids)
    return out


def _flash_fwd_rule(q, k, v, segment_ids, scale, causal, block_q, block_k,
                    interpret, bwd, bhsd, window):
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                              interpret, bhsd, window, segment_ids)
    return out, (q, k, v, out, lse, segment_ids)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, bwd, bhsd,
                    window, res, g):
    # segment ids are integer routing data: their cotangent is float0
    seg = res[5]
    dseg = None if seg is None else np.zeros(seg.shape, jax.dtypes.float0)
    if bwd == "pallas":
        dq, dk, dv = _flash_backward_pallas(res, g, scale, causal, block_q,
                                            block_k, interpret, bhsd,
                                            window)
        return dq, dk, dv, dseg
    if bhsd:
        # the scan-backward oracle is written for BSHD; convert around it
        t = lambda x: x.transpose(0, 2, 1, 3)
        q, k, v, out, lse, segment_ids = res
        dq, dk, dv = _flash_backward(
            (t(q), t(k), t(v), t(out), lse, segment_ids),
            t(g), scale, causal, block_k, window)
        return t(dq), t(dk), t(dv), dseg
    dq, dk, dv = _flash_backward(res, g, scale, causal, block_k, window)
    return dq, dk, dv, dseg


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    bwd: Optional[str] = None,
                    layout: str = "bshd",
                    window: Optional[int] = None,
                    segment_ids: Optional[jnp.ndarray] = None
                    ) -> jnp.ndarray:
    """Flash attention, BSHD in/out by default. Differentiable (custom
    VJP). ``layout="bhsd"`` takes/returns [B, H, S, D] — the kernel's
    native flattening is then a free reshape instead of four
    [B,S,H,D]<->[B,H,S,D] transposes per call (the layer's flash path
    produces BHSD directly for exactly this reason).

    ``interpret=None`` auto-selects: real kernel on TPU, interpreter mode
    elsewhere (falling back to the fused-XLA reference for big shapes or
    when ``interpret=False`` is forced off-TPU, where Mosaic can't lower).

    ``bwd``: ``"pallas"`` (in-kernel backward — the TPU default) or
    ``"xla"`` (blockwise-scan recomputation — the interpreter default,
    since interpreted kernels are slow on CPU; also the cross-check
    oracle for the kernel backward's numerics).

    ``block_q``/``block_k`` default adaptively: 512/1024 for full
    attention, except 1024/1024 at exactly d_head 128 causal (both
    measured optima — module header and the round-5 D=128 sweep),
    512/512 under a sliding ``window`` at every d_head — the remapped
    k-grid covers ``~window + block_q + block_k`` keys per q block, so
    the smaller blocks tighten coverage (measured: W=1024 S=8192
    fwd+bwd 1.80x full-causal at 512/512 vs 1.44x at 1024/1024 on
    v5e).

    ``segment_ids``: [B, S] int — packed-sequence masking (attention
    restricted to equal ids) through every path: forward, both Pallas
    backward kernels, the XLA-scan backward, and the fused-XLA fallback.
    See ``ops.attention.dot_product_attention`` for the convention.
    """
    if layout not in ("bshd", "bhsd"):
        raise ValueError(f"layout must be 'bshd' or 'bhsd', got {layout!r}")
    if block_q is None:
        # d_head == 128 prefers the square 1024 tile for FULL causal
        # attention: measured fwd+bwd at B4 H16 S2048 D128 (the lm_big
        # shape, round 5) — 1024/1024 4.58 ms vs the d64-tuned 512/1024
        # default's 6.05 (24% faster; 512/512 5.10, 2048-sized tiles
        # fail to compile). Deliberately NARROW: exactly d_head 128 and
        # causal — D=256 would double the measured VMEM footprint into
        # the range that failed to compile at D=128, and non-causal
        # shapes were not swept; both keep the 512/1024 default
        # (documented safe through D=256). WINDOWED attention keeps
        # 512/512 at every d_head — its remapped k-grid covers
        # ~window + block_q + block_k keys per q block, and the bigger
        # q tile widens exactly the overscan 512/512 was measured to
        # avoid.
        block_q = 1024 if (q.shape[-1] == 128 and causal
                           and window is None
                           and segment_ids is None) else DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = DEFAULT_BLOCK_K if window is None else DEFAULT_BLOCK_Q
    bhsd = layout == "bhsd"
    seq_axis = 2 if bhsd else 1
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None:
        window = int(window)
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if not causal:
            raise ValueError("window requires causal=True")

    def _xla_fallback():
        if bhsd:
            t = lambda x: x.transpose(0, 2, 1, 3)
            return t(dot_product_attention(t(q), t(k), t(v), causal=causal,
                                           scale=scale, window=window,
                                           segment_ids=segment_ids))
        return dot_product_attention(q, k, v, causal=causal, scale=scale,
                                     window=window,
                                     segment_ids=segment_ids)

    if pltpu is None:  # no Pallas TPU support in this jax build
        return _xla_fallback()
    on_tpu = backend_is_tpu()
    if interpret is None:
        interpret = not on_tpu
        if interpret and q.shape[seq_axis] * k.shape[seq_axis] > 256 * 256:
            # interpreter is too slow for big shapes; use the XLA reference
            return _xla_fallback()
    if not on_tpu and not interpret:
        return _xla_fallback()
    if bwd is None:
        bwd = "pallas" if not interpret else "xla"
    if bwd not in ("pallas", "xla"):
        raise ValueError(f"bwd must be 'pallas' or 'xla', got {bwd!r}")
    return _flash(q, k, v, segment_ids, scale, causal, block_q, block_k,
                  interpret, bwd, bhsd, window)
