"""Per-worker optimizers as pure pytree transforms.

The reference hands a Keras optimizer name to every worker's ``model.compile``
(the ``worker_optimizer`` constructor kwarg on every trainer — reference:
``distkeras/trainers.py :: Trainer.__init__``). Here an optimizer is a pure
``(init, update)`` pair over pytrees — stateless functions that jit/shard
transparently, so the same optimizer code runs single-chip, under vmap
(EnsembleTrainer), and under shard_map with a per-worker leading axis
(the distributed trainer family).

API (optax-compatible shape, independent implementation):
    opt = get_optimizer('adam', learning_rate=1e-3)
    state = opt.init(params)
    updates, state = opt.update(grads, state, params)
    params = apply_updates(params, updates)
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Any]  # (grads, state, params) ->
    #                                          (updates, new_state)
    name: str = "optimizer"


def apply_updates(params, updates):
    return jax.tree_util.tree_map(lambda p, u: p + u, params, updates)


def _zeros_like(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def _lr_resolver(learning_rate):
    """``learning_rate`` may be a float or a schedule (``step -> lr``,
    see ``ops.schedules``). Returns ``(scheduled, lr_fn)``: when scheduled,
    the optimizer carries a step counter ``"t"`` in its state and evaluates
    the schedule each update."""
    if callable(learning_rate):
        return True, learning_rate
    v = float(learning_rate)
    return False, lambda t: v


def _with_step(scheduled: bool, state: dict) -> dict:
    if scheduled:
        state["t"] = jnp.zeros((), jnp.int32)
    return state


def _step_lr(scheduled, lr_fn, state):
    """Advance the step counter and evaluate the (possibly scheduled) lr."""
    if not scheduled:
        return lr_fn(None), state
    t = state["t"] + 1
    return lr_fn(t - 1), {**state, "t": t}


def sgd(learning_rate: float = 0.01, momentum: float = 0.0,
        nesterov: bool = False) -> Optimizer:
    scheduled, lrf = _lr_resolver(learning_rate)
    mu = float(momentum)

    def init(params):
        return _with_step(scheduled,
                          {"velocity": _zeros_like(params)} if mu else {})

    def update(grads, state, params=None):
        lr, state = _step_lr(scheduled, lrf, state)
        if not mu:
            return jax.tree_util.tree_map(lambda g: -lr * g, grads), state
        vel = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g,
                                     state["velocity"], grads)
        if nesterov:
            upd = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g,
                                         vel, grads)
        else:
            upd = vel
        return upd, {**state, "velocity": vel}

    return Optimizer(init, update, "sgd")


def adagrad(learning_rate: float = 0.01, epsilon: float = 1e-7) -> Optimizer:
    scheduled, lrf = _lr_resolver(learning_rate)
    eps = float(epsilon)

    def init(params):
        return _with_step(scheduled, {"accum": _zeros_like(params)})

    def update(grads, state, params=None):
        lr, state = _step_lr(scheduled, lrf, state)
        accum = jax.tree_util.tree_map(lambda a, g: a + jnp.square(g),
                                       state["accum"], grads)
        upd = jax.tree_util.tree_map(
            lambda g, a: -lr * g / (jnp.sqrt(a) + eps), grads, accum)
        return upd, {**state, "accum": accum}

    return Optimizer(init, update, "adagrad")


def rmsprop(learning_rate: float = 0.001, rho: float = 0.9,
            epsilon: float = 1e-7) -> Optimizer:
    scheduled, lrf = _lr_resolver(learning_rate)
    r, eps = float(rho), float(epsilon)

    def init(params):
        return _with_step(scheduled, {"ms": _zeros_like(params)})

    def update(grads, state, params=None):
        lr, state = _step_lr(scheduled, lrf, state)
        ms = jax.tree_util.tree_map(
            lambda m, g: r * m + (1 - r) * jnp.square(g), state["ms"], grads)
        upd = jax.tree_util.tree_map(
            lambda g, m: -lr * g / (jnp.sqrt(m) + eps), grads, ms)
        return upd, {**state, "ms": ms}

    return Optimizer(init, update, "rmsprop")


def adam(learning_rate: float = 0.001, beta1: float = 0.9,
         beta2: float = 0.999, epsilon: float = 1e-7) -> Optimizer:
    scheduled, lrf = _lr_resolver(learning_rate)
    b1, b2, eps = float(beta1), float(beta2), float(epsilon)

    def init(params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "t": jnp.zeros((), jnp.int32)}  # adam always counts steps

    def update(grads, state, params=None):
        t = state["t"] + 1
        lr = lrf(t - 1) if scheduled else lrf(None)
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state["v"],
            grads)
        # bias correction folded into the step size (scalar, jit-friendly)
        tf = t.astype(jnp.float32)
        step = lr * jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
        upd = jax.tree_util.tree_map(
            lambda m_, v_: -step * m_ / (jnp.sqrt(v_) + eps), m, v)
        return upd, {"m": m, "v": v, "t": t}

    return Optimizer(init, update, "adam")


def adadelta(learning_rate: float = 1.0, rho: float = 0.95,
             epsilon: float = 1e-7) -> Optimizer:
    scheduled, lrf = _lr_resolver(learning_rate)
    r, eps = float(rho), float(epsilon)

    def init(params):
        return _with_step(scheduled, {"acc_g": _zeros_like(params),
                                      "acc_u": _zeros_like(params)})

    def update(grads, state, params=None):
        lr, state = _step_lr(scheduled, lrf, state)
        acc_g = jax.tree_util.tree_map(
            lambda a, g: r * a + (1 - r) * jnp.square(g), state["acc_g"],
            grads)
        upd = jax.tree_util.tree_map(
            lambda g, ag, au: -lr * g * jnp.sqrt(au + eps) /
            jnp.sqrt(ag + eps), grads, acc_g, state["acc_u"])
        acc_u = jax.tree_util.tree_map(
            lambda a, u: r * a + (1 - r) * jnp.square(u), state["acc_u"], upd)
        return upd, {**state, "acc_g": acc_g, "acc_u": acc_u}

    return Optimizer(init, update, "adadelta")


def adamw(learning_rate: float = 0.001, beta1: float = 0.9,
          beta2: float = 0.999, epsilon: float = 1e-7,
          weight_decay: float = 0.01) -> Optimizer:
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter 2019) — the
    transformer-era default the reference's Keras 1.x never had."""
    scheduled, lrf = _lr_resolver(learning_rate)
    b1, b2, eps, wd = (float(beta1), float(beta2), float(epsilon),
                       float(weight_decay))

    def init(params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "t": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("adamw needs params (decoupled decay); call "
                             "opt.update(grads, state, params)")
        t = state["t"] + 1
        lr = lrf(t - 1) if scheduled else lrf(None)
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state["v"],
            grads)
        tf = t.astype(jnp.float32)
        step = lr * jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
        upd = jax.tree_util.tree_map(
            lambda m_, v_, p: -step * m_ / (jnp.sqrt(v_) + eps)
            - lr * wd * p, m, v, params)
        return upd, {"m": m, "v": v, "t": t}

    return Optimizer(init, update, "adamw")


def _l2(x) -> jnp.ndarray:
    return jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))


def lars(learning_rate: float = 1.0, momentum: float = 0.9,
         weight_decay: float = 0.0, trust_coefficient: float = 1e-3,
         epsilon: float = 1e-8) -> Optimizer:
    """Layer-wise Adaptive Rate Scaling (You et al. 2017) — the classic
    large-batch ResNet optimizer. Per tensor, with the decayed gradient
    ``g' = g + wd·w``, the trust ratio ``tc·‖w‖ / (‖g'‖ + eps)`` scales the
    momentum step so huge global batches (the natural TPU-pod regime) keep
    SGD's convergence. (Folding the decay into the norm is the common
    implementation variant; it differs from the paper's
    ``‖g‖ + wd·‖w‖`` denominator only when decay is large.)"""
    scheduled, lrf = _lr_resolver(learning_rate)
    mu, wd, tc, eps = (float(momentum), float(weight_decay),
                       float(trust_coefficient), float(epsilon))

    def init(params):
        return _with_step(scheduled, {"v": _zeros_like(params)})

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("lars needs params; call "
                             "opt.update(grads, state, params)")
        lr, state = _step_lr(scheduled, lrf, state)

        def leaf(v_, g, p):
            g = g + wd * p
            wn, gn = _l2(p), _l2(g)
            # trust ratio only where both norms are nonzero (biases /
            # fresh layers fall back to the plain lr)
            ratio = jnp.where((wn > 0) & (gn > 0),
                              tc * wn / (gn + eps), 1.0)
            return mu * v_ + (lr * ratio).astype(g.dtype) * g

        v = jax.tree_util.tree_map(leaf, state["v"], grads, params)
        upd = jax.tree_util.tree_map(lambda v_: -v_, v)
        return upd, {**state, "v": v}

    return Optimizer(init, update, "lars")


def lamb(learning_rate: float = 0.001, beta1: float = 0.9,
         beta2: float = 0.999, epsilon: float = 1e-6,
         weight_decay: float = 0.0) -> Optimizer:
    """LAMB (You et al. 2020): Adam direction × per-tensor trust ratio —
    large-batch training for transformers (the BERT-in-76-minutes
    optimizer)."""
    scheduled, lrf = _lr_resolver(learning_rate)
    b1, b2, eps, wd = (float(beta1), float(beta2), float(epsilon),
                       float(weight_decay))

    def init(params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "t": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("lamb needs params; call "
                             "opt.update(grads, state, params)")
        t = state["t"] + 1
        lr = lrf(t - 1) if scheduled else lrf(None)
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state["v"],
            grads)
        tf = t.astype(jnp.float32)
        c1, c2 = 1 - b1 ** tf, 1 - b2 ** tf

        def leaf(m_, v_, p):
            r = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps) + wd * p
            wn, rn = _l2(p), _l2(r)
            ratio = jnp.where((wn > 0) & (rn > 0), wn / rn, 1.0)
            return -(lr * ratio).astype(r.dtype) * r

        upd = jax.tree_util.tree_map(leaf, m, v, params)
        return upd, {"m": m, "v": v, "t": t}

    return Optimizer(init, update, "lamb")


def clip_by_global_norm(optimizer: Optimizer, max_norm: float) -> Optimizer:
    """Wrap an optimizer so gradients are rescaled to a maximum GLOBAL L2
    norm before its update (the standard transformer stabilizer; exposed on
    every trainer as ``clip_grad_norm=``)."""
    mx = float(max_norm)
    if mx <= 0:
        raise ValueError(f"max_norm must be > 0, got {max_norm}")

    def update(grads, state, params=None):
        leaves = jax.tree_util.tree_leaves(grads)
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in leaves))
        scale = mx / jnp.maximum(gn, mx)
        grads = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
            grads)
        return optimizer.update(grads, state, params)

    return Optimizer(optimizer.init, update,
                     f"clip({optimizer.name}, {mx})")


OPTIMIZERS = {
    "sgd": sgd,
    "momentum": lambda **kw: sgd(momentum=kw.pop("momentum", 0.9), **kw),
    "nesterov": lambda **kw: sgd(momentum=kw.pop("momentum", 0.9),
                                 nesterov=True, **kw),
    "adagrad": adagrad,
    "rmsprop": rmsprop,
    "adam": adam,
    "adamw": adamw,
    "adadelta": adadelta,
    "lars": lars,
    "lamb": lamb,
}


def get_optimizer(opt: Union[str, Optimizer], **kwargs) -> Optimizer:
    """Resolve ``'adam'`` / ``('sgd', lr=0.1)`` / Optimizer -> Optimizer,
    matching the reference's string ``worker_optimizer`` ergonomics."""
    if isinstance(opt, Optimizer):
        if kwargs:
            raise ValueError(
                f"got both an Optimizer instance and kwargs {sorted(kwargs)};"
                " configure the instance directly instead (the kwargs would"
                " be silently ignored)")
        return opt
    try:
        factory = OPTIMIZERS[opt]
    except KeyError:
        raise ValueError(f"Unknown optimizer {opt!r}; "
                         f"known: {sorted(OPTIMIZERS)}")
    return factory(**kwargs)
