"""Per-worker optimizers as pure pytree transforms.

The reference hands a Keras optimizer name to every worker's ``model.compile``
(the ``worker_optimizer`` constructor kwarg on every trainer — reference:
``distkeras/trainers.py :: Trainer.__init__``). Here an optimizer is a pure
``(init, update)`` pair over pytrees — stateless functions that jit/shard
transparently, so the same optimizer code runs single-chip, under vmap
(EnsembleTrainer), and under shard_map with a per-worker leading axis
(the distributed trainer family).

API (optax-compatible shape, independent implementation):
    opt = get_optimizer('adam', learning_rate=1e-3)
    state = opt.init(params)
    updates, state = opt.update(grads, state, params)
    params = apply_updates(params, updates)
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Any]  # (grads, state, params) ->
    #                                          (updates, new_state)
    name: str = "optimizer"


def apply_updates(params, updates):
    return jax.tree_util.tree_map(lambda p, u: p + u, params, updates)


def _zeros_like(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd(learning_rate: float = 0.01, momentum: float = 0.0,
        nesterov: bool = False) -> Optimizer:
    lr, mu = float(learning_rate), float(momentum)

    def init(params):
        return {"velocity": _zeros_like(params)} if mu else {}

    def update(grads, state, params=None):
        if not mu:
            return jax.tree_util.tree_map(lambda g: -lr * g, grads), state
        vel = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g,
                                     state["velocity"], grads)
        if nesterov:
            upd = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g,
                                         vel, grads)
        else:
            upd = vel
        return upd, {"velocity": vel}

    return Optimizer(init, update, "sgd")


def adagrad(learning_rate: float = 0.01, epsilon: float = 1e-7) -> Optimizer:
    lr, eps = float(learning_rate), float(epsilon)

    def init(params):
        return {"accum": _zeros_like(params)}

    def update(grads, state, params=None):
        accum = jax.tree_util.tree_map(lambda a, g: a + jnp.square(g),
                                       state["accum"], grads)
        upd = jax.tree_util.tree_map(
            lambda g, a: -lr * g / (jnp.sqrt(a) + eps), grads, accum)
        return upd, {"accum": accum}

    return Optimizer(init, update, "adagrad")


def rmsprop(learning_rate: float = 0.001, rho: float = 0.9,
            epsilon: float = 1e-7) -> Optimizer:
    lr, r, eps = float(learning_rate), float(rho), float(epsilon)

    def init(params):
        return {"ms": _zeros_like(params)}

    def update(grads, state, params=None):
        ms = jax.tree_util.tree_map(
            lambda m, g: r * m + (1 - r) * jnp.square(g), state["ms"], grads)
        upd = jax.tree_util.tree_map(
            lambda g, m: -lr * g / (jnp.sqrt(m) + eps), grads, ms)
        return upd, {"ms": ms}

    return Optimizer(init, update, "rmsprop")


def adam(learning_rate: float = 0.001, beta1: float = 0.9,
         beta2: float = 0.999, epsilon: float = 1e-7) -> Optimizer:
    lr, b1, b2, eps = (float(learning_rate), float(beta1), float(beta2),
                       float(epsilon))

    def init(params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "t": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        t = state["t"] + 1
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state["v"],
            grads)
        # bias correction folded into the step size (scalar, jit-friendly)
        tf = t.astype(jnp.float32)
        step = lr * jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
        upd = jax.tree_util.tree_map(
            lambda m_, v_: -step * m_ / (jnp.sqrt(v_) + eps), m, v)
        return upd, {"m": m, "v": v, "t": t}

    return Optimizer(init, update, "adam")


def adadelta(learning_rate: float = 1.0, rho: float = 0.95,
             epsilon: float = 1e-7) -> Optimizer:
    lr, r, eps = float(learning_rate), float(rho), float(epsilon)

    def init(params):
        return {"acc_g": _zeros_like(params), "acc_u": _zeros_like(params)}

    def update(grads, state, params=None):
        acc_g = jax.tree_util.tree_map(
            lambda a, g: r * a + (1 - r) * jnp.square(g), state["acc_g"],
            grads)
        upd = jax.tree_util.tree_map(
            lambda g, ag, au: -lr * g * jnp.sqrt(au + eps) /
            jnp.sqrt(ag + eps), grads, acc_g, state["acc_u"])
        acc_u = jax.tree_util.tree_map(
            lambda a, u: r * a + (1 - r) * jnp.square(u), state["acc_u"], upd)
        return upd, {"acc_g": acc_g, "acc_u": acc_u}

    return Optimizer(init, update, "adadelta")


OPTIMIZERS = {
    "sgd": sgd,
    "momentum": lambda **kw: sgd(momentum=kw.pop("momentum", 0.9), **kw),
    "nesterov": lambda **kw: sgd(momentum=kw.pop("momentum", 0.9),
                                 nesterov=True, **kw),
    "adagrad": adagrad,
    "rmsprop": rmsprop,
    "adam": adam,
    "adadelta": adadelta,
}


def get_optimizer(opt: Union[str, Optimizer], **kwargs) -> Optimizer:
    """Resolve ``'adam'`` / ``('sgd', lr=0.1)`` / Optimizer -> Optimizer,
    matching the reference's string ``worker_optimizer`` ergonomics."""
    if isinstance(opt, Optimizer):
        if kwargs:
            raise ValueError(
                f"got both an Optimizer instance and kwargs {sorted(kwargs)};"
                " configure the instance directly instead (the kwargs would"
                " be silently ignored)")
        return opt
    try:
        factory = OPTIMIZERS[opt]
    except KeyError:
        raise ValueError(f"Unknown optimizer {opt!r}; "
                         f"known: {sorted(OPTIMIZERS)}")
    return factory(**kwargs)
