"""Ulysses attention: all-to-all sequence/context parallelism.

Absent from the reference (SURVEY §5.7: dist-keras has no sequence
sharding of any kind) — this is the second of the TPU build's two
long-context strategies, complementing ``ops.ring_attention``:

  * **Ring** keeps the sequence sharded end-to-end and rotates K/V shards
    around the mesh axis with ``ppermute`` — N-1 neighbor hops, each
    overlapped with block compute. Communication volume per device scales
    with the FULL K/V (every shard visits every device).
  * **Ulysses** (DeepSpeed-Ulysses style) re-shards with two
    ``all_to_all``s: sequence-sharded → head-sharded before attention and
    back after. Each device then computes EXACT attention over the whole
    sequence for ``H / N`` heads, so any single-device kernel (fused XLA or
    the Pallas flash kernel) is reused unchanged. Communication is two
    all-to-alls of the activations — O(B·S·H·D / N) per device, cheaper
    than the ring's rotating K/V when heads are plentiful, but it requires
    ``num_heads % axis_size == 0`` and peak score memory is that of the
    inner kernel at full sequence length (use ``impl="flash"`` for long S).

Like ``ring_attention`` this must run **inside** a ``shard_map`` whose
``axis_name`` axis shards the sequence dimension of q/k/v
(``MultiHeadAttention(attn_impl="ulysses")`` arranges this).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from distkeras_tpu.ops.attention import dot_product_attention


def _seq_to_heads(x, axis_name):
    """[B, S/N, H, D] sequence-sharded -> [B, S, H/N, D] head-sharded.

    ``tiled`` all-to-all splits the local heads into N chunks and
    concatenates the received sequence shards in device order — device
    order IS global sequence order, so the result holds the full sequence
    contiguously.
    """
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _heads_to_seq(x, axis_name):
    """[B, S, H/N, D] head-sharded -> [B, S/N, H, D] sequence-sharded."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None, impl: str = "xla",
                      block_q: int = 128, block_k: int = 128,
                      segment_ids=None) -> jnp.ndarray:
    """BSHD sequence-sharded exact attention via head-scatter all-to-all.

    q/k/v: local sequence shards ``[B, S/N, H, D]`` with ``H % N == 0``.
    ``impl`` picks the per-device kernel on the gathered sequence:
    ``"xla"`` (fused reference attention) or ``"flash"`` (Pallas kernel;
    ``block_q``/``block_k`` are its tile sizes). Returns the local
    ``[B, S/N, H, D]`` output shard.

    ``segment_ids`` (round 4): the LOCAL [B, S/N] shard of
    packed-sequence ids. After the head-scatter each device holds the
    FULL sequence for its heads, so the ids are ``all_gather``-ed to
    [B, S] (int32 — negligible next to the activation all-to-alls) and
    handed to the inner kernel's own segment masking (VERDICT r3 weak
    #4: packing now composes with both sequence-parallel strategies).
    """
    n = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use attn_impl='ring' when "
            "heads don't split evenly")
    if segment_ids is not None and segment_ids.shape != q.shape[:2]:
        raise ValueError(
            f"segment_ids must be the local [B, S_local] shard "
            f"{q.shape[:2]}, got {segment_ids.shape}")

    qg = _seq_to_heads(q, axis_name)
    kg = _seq_to_heads(k, axis_name)
    vg = _seq_to_heads(v, axis_name)
    seg_full = None
    if segment_ids is not None:
        seg_full = lax.all_gather(
            jnp.asarray(segment_ids, jnp.int32), axis_name,
            axis=1, tiled=True)                              # [B, S]

    if impl == "flash":
        from distkeras_tpu.ops.flash_attention import flash_attention
        out = flash_attention(qg, kg, vg, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              segment_ids=seg_full)
    else:
        out = dot_product_attention(qg, kg, vg, causal=causal, scale=scale,
                                    segment_ids=seg_full)

    return _heads_to_seq(out, axis_name)
