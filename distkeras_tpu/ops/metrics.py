"""Metrics as pure batched functions.

The reference computes accuracy offline on the driver by comparing DataFrame
columns (reference: ``distkeras/evaluators.py :: AccuracyEvaluator``). Here
metrics are vectorized jnp functions usable both inside jitted eval steps and
from the host-side ``Evaluator`` wrappers in ``inference/evaluators.py``.
"""

from __future__ import annotations

from typing import Callable, Union

import jax.numpy as jnp

from distkeras_tpu.ops import losses


def accuracy(y_true, y_pred):
    """Classification accuracy. Handles one-hot or integer ``y_true`` and
    probability/logit vectors, sigmoid scores, or integer predictions in
    ``y_pred``. Binary float scores are thresholded at 0.5 when they look
    like probabilities (all values in [0, 1]) and at 0.0 otherwise (logits);
    the check is a traced scalar select, so it stays jit-compatible."""
    if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
        y_pred = jnp.argmax(y_pred, axis=-1)
    elif jnp.issubdtype(y_pred.dtype, jnp.floating):
        is_prob = jnp.all((y_pred >= 0.0) & (y_pred <= 1.0))
        y_pred = y_pred >= jnp.where(is_prob, 0.5, 0.0)
    if y_true.ndim > 1 and y_true.shape[-1] > 1:
        y_true = jnp.argmax(y_true, axis=-1)
    return jnp.mean((y_pred.reshape(-1).astype(jnp.int32) ==
                     y_true.reshape(-1).astype(jnp.int32))
                    .astype(jnp.float32))


def top_k_accuracy(y_true, y_pred, k: int = 5):
    if y_true.ndim > 1 and y_true.shape[-1] > 1:
        y_true = jnp.argmax(y_true, axis=-1)
    topk = jnp.argsort(y_pred, axis=-1)[..., -k:]
    hit = jnp.any(topk == y_true[..., None].astype(jnp.int32), axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


METRICS = {
    "accuracy": accuracy,
    "top_5_accuracy": lambda t, p: top_k_accuracy(t, p, 5),
    "mse": losses.mean_squared_error,
}


def get_metric(metric: Union[str, Callable]):
    if callable(metric):
        return metric
    try:
        return METRICS[metric]
    except KeyError:
        raise ValueError(f"Unknown metric {metric!r}; known: {sorted(METRICS)}")
