"""Metrics as pure batched functions.

The reference computes accuracy offline on the driver by comparing DataFrame
columns (reference: ``distkeras/evaluators.py :: AccuracyEvaluator``). Here
metrics are vectorized jnp functions usable both inside jitted eval steps and
from the host-side ``Evaluator`` wrappers in ``inference/evaluators.py``.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

from distkeras_tpu.ops import losses


def _class_vectors(y_true, y_pred):
    """Normalize (labels, predictions) to flat integer class vectors.

    Handles one-hot or integer ``y_true`` and probability/logit vectors,
    sigmoid scores, or integer predictions in ``y_pred``. One-hot label
    encodings must be FLOATING-point (what ``to_categorical`` produces):
    an integer ``[B, C]`` label array is always read as per-position
    class ids, never argmaxed — integer one-hot labels would be silently
    misread, so cast them to float (or ``argmax`` them) first (ADVICE
    r3). Binary float
    scores are thresholded at 0.5 when they look like probabilities (all
    values in [0, 1]) and at 0.0 otherwise (logits); the check is a traced
    scalar select, so it stays jit-compatible. Returns ``(t, p, k)`` where
    ``k`` is the class count implied by a vector width, or None when both
    inputs are plain class vectors.
    """
    k = None
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
        k = y_pred.shape[-1]
        y_pred = jnp.argmax(y_pred, axis=-1)
    elif jnp.issubdtype(y_pred.dtype, jnp.floating):
        k = 2
        is_prob = jnp.all((y_pred >= 0.0) & (y_pred <= 1.0))
        y_pred = y_pred >= jnp.where(is_prob, 0.5, 0.0)
    # one-hot label encodings are FLOATING-point (what to_categorical and
    # softmax targets produce); integer multi-dim labels are always class
    # ids — in particular [B, S] per-token LM targets, which must not be
    # argmaxed even when S coincidentally equals the class count
    if y_true.ndim > 1 and y_true.shape[-1] > 1 and \
            jnp.issubdtype(y_true.dtype, jnp.floating):
        k = max(k or 0, y_true.shape[-1])
        y_true = jnp.argmax(y_true, axis=-1)
    return (y_true.reshape(-1).astype(jnp.int32),
            y_pred.reshape(-1).astype(jnp.int32), k)


def accuracy(y_true, y_pred):
    """Classification accuracy (see ``_class_vectors`` for accepted
    shapes/encodings)."""
    t, p, _ = _class_vectors(y_true, y_pred)
    return jnp.mean((p == t).astype(jnp.float32))


def top_k_accuracy(y_true, y_pred, k: int = 5):
    # same one-hot rule as _class_vectors: floating labels only — integer
    # [B, S] per-token targets are class ids, never argmaxed
    if y_true.ndim > 1 and y_true.shape[-1] > 1 and \
            jnp.issubdtype(jnp.asarray(y_true).dtype, jnp.floating):
        y_true = jnp.argmax(y_true, axis=-1)
    topk = jnp.argsort(y_pred, axis=-1)[..., -k:]
    hit = jnp.any(topk == y_true[..., None].astype(jnp.int32), axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


def _concrete_max(x):
    """max(x)+1 when x is a concrete array; None under jit tracing (class
    count must then come from a vector dimension)."""
    import numpy as np
    try:
        return int(np.max(np.asarray(x))) + 1
    except Exception:  # tracer — no concrete value available
        return None


def _prf(y_true, y_pred):
    """Per-class (precision, recall) via a confusion count, jit-friendly.

    The class count k comes from the prediction/label VECTOR width when one
    is present (always the case for in-training metrics on logits — static
    under jit); for plain integer class vectors it is inferred from the
    concrete data, which requires host-side (non-traced) inputs. Concrete
    labels OUTSIDE the k implied by the scores raise rather than silently
    dropping out of the macro average.
    """
    t, p, k = _class_vectors(y_true, y_pred)
    kt, kp = _concrete_max(t), _concrete_max(p)
    if k is None:  # both plain int vectors: infer from the data
        if kt is None or kp is None:
            raise ValueError(
                "precision/recall/f1 on two integer class VECTORS under "
                "jit cannot infer the class count; pass logits/one-hot, or "
                "call on concrete (host) arrays")
        k = max(kt, kp, 2)
    else:
        # concrete classes OUTSIDE k would one-hot to all-zero rows and
        # silently vanish from the confusion counts
        for nm, kk in (("labels", kt), ("predictions", kp)):
            if kk is not None and kk > k:
                raise ValueError(
                    f"{nm} contain class {kk - 1} but the vector-encoded "
                    f"side only covers {k} classes")
    t1 = jax.nn.one_hot(t, k, dtype=jnp.float32)
    p1 = jax.nn.one_hot(p, k, dtype=jnp.float32)
    tp = jnp.sum(t1 * p1, axis=0)
    pred_k = jnp.sum(p1, axis=0)
    true_k = jnp.sum(t1, axis=0)
    prec = tp / jnp.maximum(pred_k, 1.0)
    rec = tp / jnp.maximum(true_k, 1.0)
    # macro-average over classes PRESENT in y_true (absent classes would
    # drag the mean down with zeros)
    present = (true_k > 0).astype(jnp.float32)
    denom = jnp.maximum(present.sum(), 1.0)
    return (prec * present).sum() / denom, (rec * present).sum() / denom


def precision(y_true, y_pred):
    """Macro-averaged precision over the classes present in ``y_true``."""
    return _prf(y_true, y_pred)[0]


def recall(y_true, y_pred):
    """Macro-averaged recall over the classes present in ``y_true``."""
    return _prf(y_true, y_pred)[1]


def f1(y_true, y_pred):
    """Macro F1 (harmonic mean of the macro precision/recall)."""
    p, r = _prf(y_true, y_pred)
    return 2.0 * p * r / jnp.maximum(p + r, 1e-12)


def auc(y_true, y_pred):
    """Binary ROC-AUC via the rank statistic (Mann–Whitney U): the
    probability a random positive scores above a random negative, with
    ties counted half. Keras-parity metric for imbalanced problems (the
    Criteo config) where accuracy is uninformative.

    ``y_pred``: scores — a [N] vector (probability OR logit; AUC is
    rank-based so monotone transforms don't matter) or an [N, 2] softmax/
    logit pair (the class-1 margin is used). ``y_true``: 0/1 labels.

    FULL-DATASET evaluator metric: as a per-batch training metric
    (``metrics=["auc"]``) the history records batch-wise AUCs whose mean
    is biased toward 0.5 on imbalanced data (single-class batches score
    exactly 0.5) — use ``inference.Evaluator("auc")`` or
    ``model.evaluate`` over the whole set for the real number.
    """
    y_true = jnp.asarray(y_true).reshape(-1).astype(jnp.float32)
    if y_true.shape[0] >= 2 ** 24:
        # f32 rank arithmetic loses integer precision beyond 2^24
        raise ValueError(
            f"auc supports up to 2^24 rows (got {y_true.shape[0]}); "
            "evaluate on a subsample")
    s = jnp.asarray(y_pred)
    if s.ndim > 1 and s.shape[-1] == 2:
        # the DIFFERENCE is monotone in softmax p1 for logits AND for
        # probability pairs; column 1 alone is not rank-equivalent for
        # logits (p1 depends on s1 - s0)
        s = s[..., 1] - s[..., 0]
    s = s.reshape(-1).astype(jnp.float32)
    # average ranks via sort + searchsorted (O(N log N), no [N, N]
    # pairwise matrix): a value whose equal-group occupies sorted
    # positions lo+1..hi gets the midpoint rank (lo + hi + 1) / 2
    sorted_s = jnp.sort(s)
    lo = jnp.searchsorted(sorted_s, s, side="left").astype(jnp.float32)
    hi = jnp.searchsorted(sorted_s, s, side="right").astype(jnp.float32)
    ranks = (lo + hi + 1.0) / 2.0
    npos = jnp.sum(y_true)
    nneg = y_true.shape[0] - npos
    u = jnp.sum(ranks * y_true) - npos * (npos + 1.0) / 2.0
    return jnp.where((npos > 0) & (nneg > 0), u / (npos * nneg), 0.5)


METRICS = {
    "accuracy": accuracy,
    "top_5_accuracy": lambda t, p: top_k_accuracy(t, p, 5),
    "mse": losses.mean_squared_error,
    "precision": precision,
    "recall": recall,
    "f1": f1,
    "auc": auc,
}


def metric_name(metric: Union[str, Callable]) -> str:
    """Display/history key for a metric spec (shared by trainer histories
    and ``Model.evaluate`` so the two report under the same names)."""
    if isinstance(metric, str):
        return metric
    return getattr(metric, "__name__", "metric")


def get_metric(metric: Union[str, Callable]):
    if callable(metric):
        return metric
    try:
        return METRICS[metric]
    except KeyError:
        raise ValueError(f"Unknown metric {metric!r}; known: {sorted(METRICS)}")
