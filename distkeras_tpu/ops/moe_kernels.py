"""Fused Pallas MoE dispatch + grouped expert GEMM (round 6).

The round-5 restructure took the capacity dispatch to XLA's primitive
floor: one [K*N, d] drop/unique scatter builds the [E*C, d] HBM buffer,
one gather reads the combine — both measured at the chip's row-granular
permute rate (~85-110 GB/s, ~8x under streaming; docs/PERF.md SSMoE).
That floor exists because XLA has no primitive that CONSUMES a gather:
the dispatch buffer must round-trip HBM before the expert matmul reads
it. This module is the Pallas lever the round-5 VERDICT asked for
(MegaBlocks-style dropless grouping as prior art): fuse the gather INTO
the expert GEMM so the buffer never exists.

Structure (one ``custom_vjp`` op, ``moe_fused_experts``):

  * forward — ``_gather_gemm1``: grid ``(E, C/block_c)``; each program
    row-DMAs its capacity tile's tokens straight from the [N, d]
    residual stream in HBM into a contiguous VMEM tile (indices come
    from the SAME ``_dispatch_plan`` arrays the XLA path scatters with,
    inverted by one cheap int32 [E*C] scatter), then runs the expert's
    up-projection matmul + bias + activation on the MXU while the next
    rows stream in. Only the [E, C, H] activations touch HBM — the
    [K*N, d] broadcast source and [E*C, d] dispatch buffer of the XLA
    path never materialize. The down-projection stays the stacked
    einsum (measured round 5: the batched-dot emitter beats ragged_dot
    and unrolling there) and the combine stays the structured
    gather + reshape-sum.
  * backward — the combine's transpose is ALSO a gather: the cotangent
    row a buffer slot needs is ``g[src_tok[row]] * gate[row]``, the
    exact mirror of the forward's token gather. ``_bwd_dx`` re-gathers
    x and g per tile, recomputes the pre-activation (MegaBlocks-style
    recompute: FLOPs are cheaper than an [E, C, H] f32 residual),
    and emits ``dx``-rows, ``dz``, the per-row ``<y, g>`` dot the
    router gradient needs, and the gated cotangent ``gy`` — all
    row-granular traffic is a GATHER in both passes; the only scatters
    left anywhere are the two int32/f32 [E*C] plan inversions.
    ``_bwd_dw1`` accumulates ``dw1[e] += x_tile^T @ dz_tile`` across
    the capacity grid in a VMEM-resident f32 block.

Numerics contract: identical routing, drop, tie-break, and NaN-masking
semantics to ``dispatch="tokens"`` — both consume one ``_dispatch_plan``
and mask gathered rows with ``where(keep, ..., 0)`` BEFORE the gate
multiply. ``tests/test_moe_fused.py`` pins forward AND backward against
the ``dispatch="dense"`` oracle under ``interpret=True`` (the tier-1
CPU gate), including capacity drops and top-k ties.

Backend selection follows the repo-wide convention
(``compat.backend_is_tpu``, trace-time default backend — the documented
contract of ``models.decoding.generate``): on TPU the kernels compile;
elsewhere ``MoE`` falls back to the XLA-floor ``tokens`` path unless a
test forces interpreter mode via ``force_interpret()``.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from distkeras_tpu.compat import backend_is_tpu, tpu_compiler_params
from distkeras_tpu.models.layers import get_activation

#: upper bound on the capacity-tile row count. 128 keeps the worst
#: kernel (``_bwd_dx``: w1 + w2 + h + dz + dxr + two gather tiles)
#: inside VMEM at the bench shape (d=1024, H=2048, bf16).
MAX_BLOCK_C = 128

_FORCE_INTERPRET = False


@contextlib.contextmanager
def force_interpret():
    """Run the fused kernels in Pallas interpreter mode regardless of
    backend — the CPU test suite's hook (tier-1 runs JAX_PLATFORMS=cpu,
    where the production path would fall back to ``tokens``)."""
    global _FORCE_INTERPRET
    prev = _FORCE_INTERPRET
    _FORCE_INTERPRET = True
    try:
        yield
    finally:
        _FORCE_INTERPRET = prev


def fused_supported() -> bool:
    """Whether ``dispatch="fused"`` should take the kernel path — the
    single gate ``MoE.apply`` consults (same trace-time convention as
    every Pallas-vs-XLA fork in this repo: ``compat.backend_is_tpu``)."""
    if pltpu is None:
        return False
    return _FORCE_INTERPRET or backend_is_tpu()


def kernel_capacity(capacity: int) -> int:
    """Per-expert row count as the KERNELS tile it: ``capacity`` rounded
    up to a multiple of 8 (Mosaic wants block second-to-last dims % 8 ==
    0 — the same rule ``decode_attention`` pads its G row axis for). The
    pad rows are real kernel rows but win no dispatch slot: their
    ``src_tok`` stays -1 (zeroed gather) and their gate 0, so they
    contribute exact zeros everywhere. Plan/combine indices stay in the
    UNPADDED ``e * capacity + pos`` space and are remapped at the op
    boundary (``_pad_slots``)."""
    return -(-int(capacity) // 8) * 8


def choose_block_c(capacity: int, cap: int = MAX_BLOCK_C) -> int:
    """Largest divisor of ``capacity`` <= cap, preferring multiples of 8
    (Mosaic's second-to-last-dim tiling rule; always satisfiable for the
    padded ``kernel_capacity`` row counts the fused op tiles). Divisor
    (not cdiv) tiling keeps every block fully in-bounds, so the
    row-gather loop needs no partial-tile masking (mirrors
    ``decode_attention``'s bh_block rounding)."""
    divs = [b for b in range(1, min(capacity, cap) + 1)
            if capacity % b == 0]
    mult8 = [b for b in divs if b % 8 == 0]
    return max(mult8 or divs)


def _slot_tokens(kn: int, k: int):
    """Choice-major slot->token map: ``tile(arange(N), K)`` (slot
    s = k*N + n), the same structure round 5's combine exploits."""
    return jnp.tile(jnp.arange(kn // k, dtype=jnp.int32), k)


# ---------------------------------------------------------------------------
# row gather: HBM -> contiguous VMEM tile, by prefetched plan indices
# ---------------------------------------------------------------------------

def _gather_tile(idx_ref, src_hbm, dst_vmem, sem, base, rows: int):
    """DMA ``rows`` arbitrary rows of ``src_hbm`` into the contiguous
    VMEM tile ``dst_vmem``, indices ``idx_ref[base + r]`` (SMEM scalar
    prefetch). Start-all-then-wait-all: every row's DMA is in flight
    before the first wait, so the gather runs at the DMA engines' row
    rate rather than serial round-trip latency. Rows with index < 0
    (capacity rows no slot won) are zeroed — their downstream garbage
    is masked by ``keep`` exactly as in the tokens path, but zeroing
    keeps the matmul operands finite."""

    def _start(r, carry):
        tok = idx_ref[base + r]

        @pl.when(tok >= 0)
        def _():
            pltpu.make_async_copy(src_hbm.at[tok], dst_vmem.at[r],
                                  sem).start()

        @pl.when(tok < 0)
        def _():
            dst_vmem[r, :] = jnp.zeros_like(dst_vmem[r, :])
        return carry

    def _wait(r, carry):
        tok = idx_ref[base + r]

        @pl.when(tok >= 0)
        def _():
            pltpu.make_async_copy(src_hbm.at[tok], dst_vmem.at[r],
                                  sem).wait()
        return carry

    lax.fori_loop(0, rows, _start, 0)
    lax.fori_loop(0, rows, _wait, 0)


# ---------------------------------------------------------------------------
# forward: gather + up-projection GEMM (+ bias + activation)
# ---------------------------------------------------------------------------

def _fwd_kernel(src_ref, x_ref, w1_ref, b1_ref, h_ref, xg, sem, *,
                block_c: int, capacity: int, act_name):
    e, c = pl.program_id(0), pl.program_id(1)
    _gather_tile(src_ref, x_ref, xg, sem, e * capacity + c * block_c,
                 block_c)
    z = jnp.dot(xg[:], w1_ref[0], preferred_element_type=jnp.float32) \
        + b1_ref[0].astype(jnp.float32)
    h_ref[0] = get_activation(act_name)(z).astype(h_ref.dtype)


def _gather_gemm1(xt, src_tok, w1, b1, *, capacity: int, block_c: int,
                  act_name: str, interpret: bool):
    """[N, d] tokens + plan indices -> [E, C, H] activated hidden tiles,
    no intermediate HBM buffer."""
    e, d, hid = w1.shape
    grid = (e, capacity // block_c)
    kwargs = {}
    if not interpret:  # pragma: no cover — compiled path needs a TPU
        kwargs["compiler_params"] = tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),               # x [N, d]
            pl.BlockSpec((1, d, hid), lambda e_, c_, *_: (e_, 0, 0)),
            pl.BlockSpec((1, 1, hid), lambda e_, c_, *_: (e_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_c, hid),
                               lambda e_, c_, *_: (e_, c_, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_c, d), xt.dtype),
            pltpu.SemaphoreType.DMA,
        ])
    kernel = functools.partial(_fwd_kernel, block_c=block_c,
                               capacity=capacity, act_name=act_name)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e, capacity, hid), xt.dtype),
        interpret=interpret, **kwargs,
    )(src_tok, xt, w1, b1.reshape(e, 1, hid))


# ---------------------------------------------------------------------------
# backward: the gather's transpose is another gather
# ---------------------------------------------------------------------------

def _bwd_dx_kernel(src_ref, x_ref, g_ref, w1_ref, w2_ref, b1_ref, b2_ref,
                   h_ref, rowg_ref, dxr_ref, dz_ref, gy_ref, rowdot_ref,
                   xg, gg, sem, *, block_c: int, capacity: int, act_name):
    """Per capacity tile: gather the OUTPUT cotangent rows its tokens
    received (the combine's transpose — a gather, because
    ``gy[row] = g[src_tok[row]] * gate[row]``), push them back through
    the expert MLP, and re-gather x to recompute the pre-activation."""
    e, c = pl.program_id(0), pl.program_id(1)
    base = e * capacity + c * block_c
    _gather_tile(src_ref, g_ref, gg, sem, base, block_c)
    _gather_tile(src_ref, x_ref, xg, sem, base, block_c)
    ggf = gg[:].astype(jnp.float32)
    gy = ggf * rowg_ref[0]                                   # [BC, d] f32
    # router cotangent ingredient: per-row <y, g> (y recomputed from the
    # saved h tile — one extra MXU pass instead of an [E, C, d] residual)
    y = jnp.dot(h_ref[0], w2_ref[0], preferred_element_type=jnp.float32) \
        + b2_ref[0].astype(jnp.float32)
    rowdot_ref[0] = jnp.sum(y * ggf, axis=1, keepdims=True)
    # dh = gy @ w2^T (contract the d axes — no transpose materialized)
    dh = lax.dot_general(gy, w2_ref[0], (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    z = jnp.dot(xg[:], w1_ref[0], preferred_element_type=jnp.float32) \
        + b1_ref[0].astype(jnp.float32)
    _, dz = jax.jvp(get_activation(act_name), (z,), (dh,))
    dz_ref[0] = dz.astype(dz_ref.dtype)
    gy_ref[0] = gy.astype(gy_ref.dtype)
    dxr_ref[0] = lax.dot_general(
        dz, w1_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dxr_ref.dtype)


def _bwd_dx(xt, g, src_tok, row_gate, w1, b1, w2, b2, h, *,
            capacity: int, block_c: int, act_name: str, interpret: bool):
    e, d, hid = w1.shape
    grid = (e, capacity // block_c)
    kwargs = {}
    if not interpret:  # pragma: no cover — compiled path needs a TPU
        kwargs["compiler_params"] = tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),               # x [N, d]
            pl.BlockSpec(memory_space=pltpu.ANY),               # g [N, d]
            pl.BlockSpec((1, d, hid), lambda e_, c_, *_: (e_, 0, 0)),
            pl.BlockSpec((1, hid, d), lambda e_, c_, *_: (e_, 0, 0)),
            pl.BlockSpec((1, 1, hid), lambda e_, c_, *_: (e_, 0, 0)),
            pl.BlockSpec((1, 1, d), lambda e_, c_, *_: (e_, 0, 0)),
            pl.BlockSpec((1, block_c, hid),
                         lambda e_, c_, *_: (e_, c_, 0)),        # h
            pl.BlockSpec((1, block_c, 1),
                         lambda e_, c_, *_: (e_, c_, 0)),        # row gate
        ],
        out_specs=(
            pl.BlockSpec((1, block_c, d),
                         lambda e_, c_, *_: (e_, c_, 0)),        # dx rows
            pl.BlockSpec((1, block_c, hid),
                         lambda e_, c_, *_: (e_, c_, 0)),        # dz
            pl.BlockSpec((1, block_c, d),
                         lambda e_, c_, *_: (e_, c_, 0)),        # gy
            pl.BlockSpec((1, block_c, 1),
                         lambda e_, c_, *_: (e_, c_, 0)),        # <y, g>
        ),
        scratch_shapes=[
            pltpu.VMEM((block_c, d), xt.dtype),
            pltpu.VMEM((block_c, d), g.dtype),
            pltpu.SemaphoreType.DMA,
        ])
    kernel = functools.partial(_bwd_dx_kernel, block_c=block_c,
                               capacity=capacity, act_name=act_name)
    dt = xt.dtype
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((e, capacity, d), dt),
            jax.ShapeDtypeStruct((e, capacity, hid), dt),
            jax.ShapeDtypeStruct((e, capacity, d), dt),
            jax.ShapeDtypeStruct((e, capacity, 1), jnp.float32),
        ),
        interpret=interpret, **kwargs,
    )(src_tok, xt, g, w1, w2, b1.reshape(e, 1, hid), b2.reshape(e, 1, d),
      h, row_gate.reshape(e, capacity, 1))


def _bwd_dw1_kernel(src_ref, x_ref, dz_ref, dw1_ref, xg, sem, *,
                    block_c: int, capacity: int):
    e, c = pl.program_id(0), pl.program_id(1)
    _gather_tile(src_ref, x_ref, xg, sem, e * capacity + c * block_c,
                 block_c)

    @pl.when(c == 0)
    def _():
        dw1_ref[0] = jnp.zeros_like(dw1_ref[0])

    # dw1[e] += x_tile^T @ dz_tile (contract the capacity axes); the
    # [d, H] f32 accumulator stays VMEM-resident across the c grid
    dw1_ref[0] += lax.dot_general(
        xg[:], dz_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_dw1(xt, dz, src_tok, *, capacity: int, block_c: int,
             interpret: bool):
    e = dz.shape[0]
    d = xt.shape[1]
    hid = dz.shape[2]
    grid = (e, capacity // block_c)
    kwargs = {}
    if not interpret:  # pragma: no cover — compiled path needs a TPU
        kwargs["compiler_params"] = tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),               # x [N, d]
            pl.BlockSpec((1, block_c, hid),
                         lambda e_, c_, *_: (e_, c_, 0)),        # dz
        ],
        out_specs=pl.BlockSpec((1, d, hid),
                               lambda e_, c_, *_: (e_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_c, d), xt.dtype),
            pltpu.SemaphoreType.DMA,
        ])
    kernel = functools.partial(_bwd_dw1_kernel, block_c=block_c,
                               capacity=capacity)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e, d, hid), jnp.float32),
        interpret=interpret, **kwargs,
    )(src_tok, xt, dz)


# ---------------------------------------------------------------------------
# the op: custom VJP over the whole dispatched expert block
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def moe_fused_experts(act_name, capacity, block_c, interpret,
                      xt, w1, b1, w2, b2, sg, dest, keep):
    """Dispatch + expert MLP + combine with the fused-gather kernels.

    Positional statics (``nondiff_argnums``): activation name, expert
    capacity C, capacity tile rows, interpreter flag. Tensors: ``xt``
    [N, d] tokens (compute dtype), stacked expert weights
    ``w1`` [E, d, H] / ``b1`` [E, H] / ``w2`` [E, H, d] / ``b2`` [E, d],
    and the ``_dispatch_plan`` arrays ``sg``/``dest``/``keep`` [K*N]
    (choice-major slot order). Returns the combined [N, d] output; use
    ``fused_moe_apply`` for the keyword-friendly wrapper.
    """
    out, _ = _fused_fwd(act_name, capacity, block_c, interpret,
                        xt, w1, b1, w2, b2, sg, dest, keep)
    return out


def _pad_slots(dest, capacity: int, cap_k: int):
    """Remap plan slot ids ``e * capacity + pos`` into the padded kernel
    row space ``e * cap_k + pos``. Out-of-range sentinels (the dropped
    slot ``E * capacity`` and the EP-localization sentinels, both >=
    E * capacity) land >= E * cap_k and keep dropping/clamping exactly
    as before."""
    if cap_k == capacity:
        return dest
    return (dest // capacity) * cap_k + dest % capacity


def _fused_fwd(act_name, capacity, block_c, interpret,
               xt, w1, b1, w2, b2, sg, dest, keep):
    e = w1.shape[0]
    d = xt.shape[1]
    dt = xt.dtype
    cap_k = kernel_capacity(capacity)
    dest_k = _pad_slots(dest, capacity, cap_k)
    src_tok = jnp.full((e * cap_k,), -1, jnp.int32).at[dest_k].set(
        _slot_tokens(dest.shape[0], dest.shape[0] // xt.shape[0]),
        mode="drop", unique_indices=True)
    sgk = jnp.where(keep, sg, 0.0).astype(jnp.float32)
    row_gate = jnp.zeros((e * cap_k,), jnp.float32).at[dest_k].set(
        sgk, mode="drop", unique_indices=True)
    h = _gather_gemm1(xt, src_tok, w1, b1, capacity=cap_k,
                      block_c=block_c, act_name=act_name,
                      interpret=interpret)
    # down-projection: the stacked batched dot (measured round 5: beats
    # ragged_dot and static unrolling on this chip/XLA) ...
    y = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    # ... and the round-5 structured combine: gather is the CHEAP
    # direction; where-mask BEFORE the gate multiply (NaN contract, see
    # models/moe.py)
    ye_flat = y.reshape(e * cap_k, d)
    safe = jnp.where(keep[:, None], ye_flat[dest_k], jnp.zeros((), dt))
    contrib = safe * sg[:, None].astype(dt)
    kk = dest.shape[0] // xt.shape[0]
    out = contrib.reshape(kk, xt.shape[0], d).sum(axis=0)
    return out, (xt, w1, b1, w2, b2, sg, dest, keep, src_tok, row_gate, h)


def _fused_bwd(act_name, capacity, block_c, interpret, res, g):
    xt, w1, b1, w2, b2, sg, dest, keep, src_tok, row_gate, h = res
    e = w1.shape[0]
    n, d = xt.shape
    kk = dest.shape[0] // n
    cap_k = kernel_capacity(capacity)
    dest_k = _pad_slots(dest, capacity, cap_k)
    gt = g.astype(xt.dtype)
    dxr, dz, gy, rowdot = _bwd_dx(
        xt, gt, src_tok, row_gate, w1, b1, w2, b2, h,
        capacity=cap_k, block_c=block_c, act_name=act_name,
        interpret=interpret)
    # slot cotangents: both transposes are gathers of the per-row kernel
    # outputs (clamped OOB rows masked by keep, as in forward)
    dxr_flat = dxr.reshape(e * cap_k, d)
    dx_slots = jnp.where(keep[:, None], dxr_flat[dest_k],
                         jnp.zeros((), dxr.dtype))
    dx = dx_slots.reshape(kk, n, d).sum(axis=0)
    dsg = jnp.where(keep, rowdot.reshape(e * cap_k)[dest_k], 0.0)
    # weight cotangents: dw1 in-kernel (needs the gathered x tiles);
    # dw2/db2/db1 are plain stacked contractions of kernel outputs
    dw1 = _bwd_dw1(xt, dz, src_tok, capacity=cap_k, block_c=block_c,
                   interpret=interpret)
    db1 = dz.astype(jnp.float32).sum(axis=1)
    dw2 = jnp.einsum("ech,ecd->ehd", h.astype(jnp.float32),
                     gy.astype(jnp.float32))
    db2 = gy.astype(jnp.float32).sum(axis=1)
    return (dx.astype(xt.dtype), dw1.astype(w1.dtype),
            db1.astype(b1.dtype), dw2.astype(w2.dtype),
            db2.astype(b2.dtype), dsg.astype(sg.dtype), None, None)


moe_fused_experts.defvjp(_fused_fwd, _fused_bwd)


def fused_moe_apply(xt, w1, b1, w2, b2, sg, dest, keep, *,
                    capacity: int, activation: str = "gelu",
                    block_c: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Keyword-friendly entry: resolve the static knobs, then call the
    custom-VJP op. ``interpret=None`` resolves by the repo backend
    convention (interpreter anywhere that is not a TPU — callers that
    want the XLA fallback instead must gate on ``fused_supported()``,
    which is what ``MoE.apply`` does)."""
    if pltpu is None:  # pragma: no cover — no Pallas TPU support
        raise RuntimeError("fused MoE requires Pallas TPU support")
    if interpret is None:
        interpret = _FORCE_INTERPRET or not backend_is_tpu()
    if block_c is None:
        # tile the PADDED row count (multiple of 8): any capacity —
        # odd, prime, 1 — gets a Mosaic-legal %8 tile
        block_c = choose_block_c(kernel_capacity(capacity))
    if not callable(activation) and activation is not None:
        get_activation(activation)    # fail early on unknown names
    return moe_fused_experts(activation, int(capacity), int(block_c),
                             bool(interpret), xt, w1, b1, w2, b2,
                             sg, dest, keep)
