"""Dataset adapters: build the columnar ``Dataset`` from external sources.

Reference parity: dist-keras ingests whatever Spark can read (CSV through a
DataFrame, with examples also covering Kafka streams). The columnar core
here already reads CSV natively (``Dataset.from_csv``); these adapters
cover the other ingestion routes a reference user expects:

  * ``from_iterable`` — any iterable of (features, label) pairs or dicts;
  * ``from_torch`` — a ``torch.utils.data.Dataset`` or ``DataLoader``
    (torch stays a host-side feeder; tensors are converted to numpy
    columns, never touching the TPU path).

All adapters MATERIALIZE to contiguous columns — the trainers' jitted epoch
scans want ``[steps, batch, ...]`` stacks, not per-row iterators (the
reference's per-row marshalling is the bottleneck SURVEY §3.1 flags).
For unbounded streams use ``inference.StreamingPredictor`` (inference) or
feed epoch-sized slices.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def _to_numpy(x) -> np.ndarray:
    if hasattr(x, "detach"):      # torch tensor
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def from_iterable(rows: Iterable[Any], features_col: str = "features",
                  label_col: str = "label") -> Dataset:
    """Iterable of rows -> columnar Dataset. Row forms (must be uniform):

      * TUPLE ``(features, label)`` — a labeled example;
      * ``{col: value}`` dict — arbitrary named columns
        (``Dataset.from_records`` semantics);
      * anything else (ndarray, list, torch tensor, scalar) — one feature
        row. A 2-element LIST is a 2-feature row, not a pair — only tuples
        are treated as (features, label), so feature vectors are never
        silently split into a bogus label column.
    """
    feats, labels, records = [], [], []
    for row in rows:
        if isinstance(row, dict):
            records.append({k: _to_numpy(v) for k, v in row.items()})
        elif isinstance(row, tuple):
            if len(row) != 2:
                raise ValueError(
                    f"tuple rows must be (features, label) pairs, got a "
                    f"{len(row)}-tuple")
            feats.append(_to_numpy(row[0]))
            labels.append(_to_numpy(row[1]))
        else:
            feats.append(_to_numpy(row))
        if records and (feats or labels):
            raise ValueError(
                "mixed dict and non-dict rows — use one row form for the "
                "whole iterable")
    if records:
        return Dataset.from_records(records)
    if not feats:
        raise ValueError("empty iterable")
    cols = {features_col: np.stack(feats)}
    if labels:
        if len(labels) != len(feats):
            raise ValueError(
                "mixed (features, label) pairs and bare feature rows")
        cols[label_col] = np.stack(labels)
    return Dataset(cols)


def from_torch(source, features_col: str = "features",
               label_col: str = "label",
               limit: Optional[int] = None) -> Dataset:
    """``torch.utils.data.Dataset`` / ``DataLoader`` -> columnar Dataset.

    DataLoader batches are concatenated back into flat columns (so the
    loader's own batch size is irrelevant — trainers re-batch). ``limit``
    caps the number of EXAMPLES taken (useful for huge map-style datasets).
    """
    feats, labels, n = [], [], 0
    batched = _looks_batched(source)

    def push(f, l=None):
        nonlocal n
        f = _to_numpy(f)
        if batched:
            feats.append(f)
            n += len(f)
        else:
            feats.append(f[None])
            n += 1
        if l is not None:
            l = _to_numpy(l)
            labels.append(l if batched else l[None])

    for item in source:
        if isinstance(item, (tuple, list)) and len(item) == 2:
            push(item[0], item[1])
        else:
            push(item)
        if limit is not None and n >= limit:
            break

    if not feats:
        raise ValueError("empty torch source")
    cols = {features_col: np.concatenate(feats)[:limit]}
    if labels:
        cols[label_col] = np.concatenate(labels)[:limit]
    return Dataset(cols)


def _looks_batched(source) -> bool:
    """DataLoaders yield batches — unless constructed with
    ``batch_size=None`` (sample mode); map-style Datasets yield rows.
    The check is on ``batch_sampler``: PyTorch creates one for any batched
    loader (including explicit ``batch_sampler=...``, whose ``.batch_size``
    attribute is None) and leaves it None only in sample mode."""
    if any(c.__name__ == "DataLoader" for c in type(source).__mro__):
        return getattr(source, "batch_sampler", None) is not None
    return False
