"""Real-dataset loaders for golden convergence tests.

The reference's de-facto integration tests are real-MNIST notebooks
(``examples/workflow.ipynb``, SURVEY §2.2/§4) and BASELINE config 1 is
"MLP on MNIST". Synthetic separable blobs are a weak convergence oracle —
an optimizer bug that costs a few points of accuracy still clears a
synthetic acc>0.8 bar. This module anchors the golden tests to real
handwritten-digit data with zero network access:

  1. a local MNIST npz (``DKT_MNIST_NPZ`` env var or ``data/mnist.npz``
     under the repo root) when present — keys ``x_train, y_train, x_test,
     y_test`` in the standard Keras layout;
  2. otherwise the UCI Optical Recognition of Handwritten Digits dataset
     bundled inside scikit-learn (1,797 real scanned digits, 8x8) —
     real data that ships on disk;
  3. otherwise (no sklearn either) a deterministic synthetic fallback,
     clearly flagged so tests can skip golden thresholds.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import numpy as np


class RealDataset(NamedTuple):
    x_train: np.ndarray  # [N, d] float32 in [0, 1]
    y_train: np.ndarray  # [N] int64
    x_test: np.ndarray
    y_test: np.ndarray
    name: str            # "mnist" | "sklearn-digits" | "synthetic"
    num_classes: int

    @property
    def is_real(self) -> bool:
        return self.name != "synthetic"


def _local_mnist_path() -> str:
    env = os.environ.get("DKT_MNIST_NPZ")
    if env:
        return env
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo_root, "data", "mnist.npz")


def load_real_digits(test_fraction: float = 0.2,
                     seed: int = 0) -> RealDataset:
    """Best available REAL digit-classification data (see module doc)."""
    path = _local_mnist_path()
    if os.path.exists(path):
        with np.load(path) as d:
            xtr = (d["x_train"].reshape(len(d["x_train"]), -1)
                   / 255.0).astype(np.float32)
            xte = (d["x_test"].reshape(len(d["x_test"]), -1)
                   / 255.0).astype(np.float32)
            return RealDataset(xtr, d["y_train"].astype(np.int64),
                               xte, d["y_test"].astype(np.int64),
                               "mnist", 10)
    try:
        from sklearn.datasets import load_digits
    except ImportError:
        rs = np.random.RandomState(seed)
        X = rs.rand(2000, 64).astype(np.float32)
        y = (X.sum(axis=1) * 10 / 64).astype(np.int64) % 10
        n = int(len(X) * (1 - test_fraction))
        return RealDataset(X[:n], y[:n], X[n:], y[n:], "synthetic", 10)

    d = load_digits()
    rs = np.random.RandomState(seed)
    perm = rs.permutation(len(d.data))
    X = (d.data[perm] / 16.0).astype(np.float32)
    y = d.target[perm].astype(np.int64)
    n = int(len(X) * (1 - test_fraction))
    return RealDataset(X[:n], y[:n], X[n:], y[n:], "sklearn-digits", 10)
