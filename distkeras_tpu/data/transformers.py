"""Feature/label transformers — vectorized columnar ops.

Reference parity: ``distkeras/transformers.py`` implements each transformer
as a Spark map/udf over rows (OneHotTransformer, LabelIndexTransformer,
MinMaxTransformer, ReshapeTransformer, DenseTransformer — SURVEY §2.1).
Here each is a single vectorized numpy op over a whole column — same API
shape (``Transformer.transform(dataset) -> dataset``), columnar execution.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Transformer:
    """Base: pure ``Dataset -> Dataset`` map (reference:
    ``transformers.py :: Transformer.transform(df)``)."""

    def transform(self, dataset: Dataset) -> Dataset:
        raise NotImplementedError

    def __call__(self, dataset: Dataset) -> Dataset:
        return self.transform(dataset)


class OneHotTransformer(Transformer):
    """Integer label column -> one-hot float vector column.

    Reference parity: ``transformers.py :: OneHotTransformer`` /
    ``utils.to_dense_vector``.
    """

    def __init__(self, output_dim: int, input_col: str = "label",
                 output_col: str = "label_encoded"):
        self.output_dim = int(output_dim)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        from distkeras_tpu.data import native
        labels = dataset[self.input_col].astype(np.int64).reshape(-1)
        if labels.size and (labels.min() < 0 or
                            labels.max() >= self.output_dim):
            raise ValueError(
                f"labels out of range [0, {self.output_dim}): "
                f"min={labels.min()}, max={labels.max()}")
        return dataset.with_column(
            self.output_col, native.one_hot(labels, self.output_dim))


class LabelIndexTransformer(Transformer):
    """Probability/score vector column -> argmax class index column.

    Reference parity: ``transformers.py :: LabelIndexTransformer`` (the step
    between ``ModelPredictor`` output and ``AccuracyEvaluator`` in every
    example pipeline).
    """

    def __init__(self, output_dim: Optional[int] = None,
                 input_col: str = "prediction",
                 output_col: str = "predicted_index"):
        self.output_dim = output_dim  # kept for API parity; argmax needs none
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        preds = np.asarray(dataset[self.input_col])
        if preds.ndim == 1 or preds.shape[-1] == 1:
            idx = (preds.reshape(len(preds), -1)[:, 0] >= 0.5).astype(np.int64)
        else:
            idx = np.argmax(preds, axis=-1).astype(np.int64)
        return dataset.with_column(self.output_col, idx)


class MinMaxTransformer(Transformer):
    """Rescale a numeric column into ``[o_min, o_max]``.

    Reference parity: ``transformers.py :: MinMaxTransformer`` (used to scale
    pixel values in the MNIST workflow). Ranges may be given (``i_min`` /
    ``i_max``) as in the reference, or inferred from the data.
    """

    def __init__(self, o_min: float = 0.0, o_max: float = 1.0,
                 i_min: Optional[float] = None, i_max: Optional[float] = None,
                 input_col: str = "features",
                 output_col: str = "features_normalized"):
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.i_min, self.i_max = i_min, i_max
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        from distkeras_tpu.data import native
        x = dataset[self.input_col].astype(np.float32)
        x2d = np.ascontiguousarray(x.reshape(len(x), -1))
        if self.i_min is None or self.i_max is None:
            mins, maxs = native.minmax_fit(x2d)
        i_min = np.float32(self.i_min if self.i_min is not None
                           else mins.min())
        i_max = np.float32(self.i_max if self.i_max is not None
                           else maxs.max())
        # global-scalar range (reference semantics): broadcast the scalar
        # over the per-column native rescale kernel
        d = x2d.shape[1]
        out = native.minmax_scale(
            x2d, np.full((d,), i_min, np.float32),
            np.full((d,), i_max, np.float32), self.o_min, self.o_max)
        return dataset.with_column(self.output_col, out.reshape(x.shape))


class ReshapeTransformer(Transformer):
    """Reshape each row of a column (flat pixel vector -> image tensor).

    Reference parity: ``transformers.py :: ReshapeTransformer`` (MNIST 784
    -> 28x28x1 before the CNN examples).
    """

    def __init__(self, input_col: str, output_col: str,
                 shape: Sequence[int]):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(int(d) for d in shape)

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col]
        return dataset.with_column(self.output_col,
                                   x.reshape((len(x),) + self.shape))


class DenseTransformer(Transformer):
    """Ensure a column is a dense, contiguous float array.

    Reference parity: ``transformers.py :: DenseTransformer`` (Spark sparse
    vector -> dense vector). Accepts scipy-style sparse matrices or object
    arrays of per-row sparse/list values.
    """

    def __init__(self, input_col: str = "features",
                 output_col: str = "features_dense"):
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col]
        if hasattr(x, "toarray"):  # scipy sparse matrix column
            dense = np.asarray(x.toarray(), dtype=np.float32)
        elif x.dtype == object:
            dense = np.stack([
                np.asarray(r.toarray()).reshape(-1)
                if hasattr(r, "toarray") else np.asarray(r, dtype=np.float32)
                for r in x]).astype(np.float32)
        else:
            dense = np.ascontiguousarray(x, dtype=np.float32)
        return dataset.with_column(self.output_col, dense)


class StandardScaleTransformer(Transformer):
    """Zero-mean/unit-variance scaling (capability add beyond the reference's
    MinMax; common preprocessing for the physics examples).

    Spark's StandardScaler is an Estimator: ``fit(train)`` freezes the
    training split's mean/std, and every later call applies THOSE stats —
    so eval data never leaks its own statistics into the transform.
    Unfitted use keeps the old per-dataset behavior."""

    def __init__(self, input_col: str = "features",
                 output_col: str = "features_scaled", epsilon: float = 1e-8):
        self.input_col = input_col
        self.output_col = output_col
        self.epsilon = float(epsilon)
        self.mean_ = None
        self.std_ = None

    def fit(self, dataset: Dataset) -> "StandardScaleTransformer":
        x = dataset[self.input_col].astype(np.float32)
        self.mean_ = x.mean(axis=0, keepdims=True)
        self.std_ = x.std(axis=0, keepdims=True)
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col].astype(np.float32)
        if self.mean_ is not None:
            mean, std = self.mean_, self.std_
        else:
            mean = x.mean(axis=0, keepdims=True)
            std = x.std(axis=0, keepdims=True)
        return dataset.with_column(self.output_col,
                                   (x - mean) / (std + self.epsilon))


class HashingTransformer(Transformer):
    """Categorical column(s) -> multi-hot hashed indicator vector.

    The hashing trick for Criteo-style high-cardinality categoricals
    (BASELINE config 4's wide features): each (column, value) pair maps to
    ``crc32(f"{col}={value}") % num_buckets`` — a STABLE hash (unlike
    Python's salted ``hash``), so train- and serve-time encodings agree
    across processes. Works on string or integer columns; the output is a
    float32 ``[n, num_buckets]`` multi-hot matrix suitable as the wide half
    of ``models.blocks.WideAndDeep``.
    """

    def __init__(self, num_buckets: int, input_cols: Sequence[str],
                 output_col: str = "features_hashed"):
        if num_buckets < 1:
            raise ValueError(f"num_buckets must be >= 1, got {num_buckets}")
        self.num_buckets = int(num_buckets)
        self.input_cols = list(input_cols)
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        import zlib

        n = len(dataset)
        out = np.zeros((n, self.num_buckets), np.float32)
        rows = np.arange(n)
        for col in self.input_cols:
            values = np.asarray(dataset[col])
            prefix = f"{col}=".encode()

            def _hash(v):
                # array-valued rows hash their canonical bytes — str() of an
                # ndarray elides the middle of wide rows ("[0. ... 0.]"), so
                # distinct rows would collide and buckets would depend on
                # numpy print options. Widen to f64/i64 first so the bucket
                # depends on VALUES, not on the column's storage width
                # (train-f32 vs serve-f64 must agree — the class contract).
                if isinstance(v, np.ndarray):
                    if v.dtype.kind == "f":
                        v = v.astype(np.float64)
                    elif v.dtype.kind in "iub":
                        v = v.astype(np.int64)
                    data = np.ascontiguousarray(v).tobytes()
                else:
                    data = str(v).encode()
                return zlib.crc32(prefix + data) % self.num_buckets

            # hash each DISTINCT value once; categorical columns repeat
            # heavily, so this turns O(n) crc32 calls into O(n_unique).
            # Multi-dim columns dedupe whole rows (axis=0); unsortable
            # mixed-type object columns can't go through np.unique at all,
            # so they fall back to the plain per-row loop.
            try:
                uniq, inverse = np.unique(
                    values, return_inverse=True,
                    axis=0 if values.ndim > 1 else None)
            except TypeError:
                buckets = np.fromiter((_hash(v) for v in values),
                                      dtype=np.int64, count=n)
            else:
                uh = np.fromiter((_hash(v) for v in uniq),
                                 dtype=np.int64, count=len(uniq))
                buckets = uh[inverse.reshape(-1)]
            out[rows, buckets] = 1.0
        return dataset.with_column(self.output_col, out)


class StringIndexerTransformer(Transformer):
    """String/categorical column -> integer index column.

    Reference parity: the examples' Spark-ML ``StringIndexer`` stage
    (SURVEY §2.2 — the MNIST/ATLAS workflows run StringIndexer before
    training). Spark semantics kept: indices are assigned by DESCENDING
    frequency (ties broken lexically), so index 0 is the most common
    value. Fit on the training data via ``fit`` (or lazily on first
    transform), then reuse on serve data; unseen values raise by default
    (``handle_invalid="error"``) or get index ``len(labels_)``
    (``"keep"``) — two of Spark's three modes (``"skip"``, which DROPS
    rows, is deliberately unsupported: silent row loss).
    """

    def __init__(self, input_col: str, output_col: Optional[str] = None,
                 handle_invalid: str = "error"):
        if handle_invalid not in ("error", "keep"):
            raise ValueError(
                f"handle_invalid must be 'error' or 'keep', "
                f"got {handle_invalid!r}")
        self.input_col = input_col
        self.output_col = output_col or f"{input_col}_index"
        self.handle_invalid = handle_invalid
        self.labels_ = None  # fitted vocabulary, most-frequent first

    def fit(self, dataset: Dataset) -> "StringIndexerTransformer":
        values = np.asarray(dataset[self.input_col])
        if values.ndim != 1:
            raise ValueError(
                f"StringIndexer expects a 1-D categorical column; "
                f"{self.input_col!r} has shape {values.shape} (index each "
                "sub-column separately)")
        uniq, counts = np.unique(values, return_counts=True)
        # descending count, ascending value on ties (np.unique pre-sorts
        # values, and stable argsort on -counts preserves that order)
        order = np.argsort(-counts, kind="stable")
        self.labels_ = uniq[order]
        self._index = {v: i for i, v in enumerate(self.labels_)}
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        if self.labels_ is None:
            self.fit(dataset)
        values = np.asarray(dataset[self.input_col])
        if values.ndim != 1:
            raise ValueError(
                f"StringIndexer expects a 1-D categorical column; "
                f"{self.input_col!r} has shape {values.shape}")
        unseen = len(self.labels_)
        # map each DISTINCT value once (categoricals repeat heavily), then
        # spread via the inverse — same O(n_unique) pattern as Hashing
        uniq, inverse = np.unique(values, return_inverse=True)
        lut = np.fromiter((self._index.get(v, unseen) for v in uniq),
                          dtype=np.int64, count=len(uniq))
        out = lut[inverse.reshape(-1)]
        if self.handle_invalid == "error" and (out == unseen).any():
            bad = sorted({str(v) for v in values[out == unseen]})[:5]
            raise ValueError(
                f"StringIndexer({self.input_col!r}) saw unseen values "
                f"{bad}; fit on data covering them or use "
                "handle_invalid='keep'")
        return dataset.with_column(self.output_col, out)


class VectorAssemblerTransformer(Transformer):
    """Concatenate feature columns into one flat feature matrix.

    Reference parity: the examples' Spark-ML ``VectorAssembler`` stage
    (SURVEY §2.2) — the step that builds the ``features_col`` every
    trainer consumes. Scalars become width-1 columns; multi-dim columns
    are flattened per row; all inputs are cast to float32.
    """

    def __init__(self, input_cols: Sequence[str],
                 output_col: str = "features"):
        if not input_cols:
            raise ValueError("VectorAssembler needs at least one input_col")
        self.input_cols = list(input_cols)
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        n = len(dataset)
        parts = []
        for col in self.input_cols:
            v = np.asarray(dataset[col], dtype=np.float32)
            parts.append(v.reshape(n, -1))
        return dataset.with_column(self.output_col,
                                   np.concatenate(parts, axis=1))
