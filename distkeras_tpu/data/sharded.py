"""Out-of-core datasets: train on data larger than host memory.

Reference parity: dist-keras inherits Spark's ability to train on a
DataFrame that never fits on one machine — executors stream their
partitions from HDFS (``workers.py :: Worker.train`` consumes a partition
iterator). The columnar ``Dataset`` here is deliberately in-memory (the
jitted epoch scan wants contiguous ``[steps, batch, ...]`` stacks); this
module restores the bigger-than-RAM story the TPU-native way: the dataset
is a SEQUENCE OF SHARDS (files or loader thunks), and the trainers run
their compiled epoch scan per shard while the NEXT shard is loaded and
stacked on a background thread (``utils.prefetch``). Peak host memory is
~2 shards regardless of total size, and the device never waits on IO.

Shard sizing: every full shard compiles ONE scan shape; keep shards
equal-sized (the last, smaller shard adds one extra compile). Each shard
drops its sub-batch remainder exactly like the in-memory path does.

Shuffling = shard-order shuffle per epoch + row permutation within each
shard (the classic two-level approximation of a global shuffle — Spark's
``utils.shuffle`` did a full sort-by-random-column, which is exactly what
out-of-core training cannot afford).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from distkeras_tpu.data.dataset import Dataset


class ShardedDataset:
    """A lazily-loaded sequence of ``Dataset`` shards.

    ``sources`` entries may be:
      * a ``Dataset`` (kept as-is, already in memory);
      * a path string — ``.npz`` (columns as arrays) or ``.csv``;
      * a zero-arg callable returning a ``Dataset`` (custom loaders —
        parquet readers, databases, object stores).
    """

    def __init__(self, sources: Sequence[Union[Dataset, str, Callable]],
                 csv_kwargs: Optional[dict] = None):
        if not sources:
            raise ValueError("ShardedDataset needs at least one shard")
        self.sources = list(sources)
        self.csv_kwargs = dict(csv_kwargs or {})

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_files(cls, paths: Sequence[str], **csv_kwargs):
        """npz/csv shard files (e.g. ``sorted(glob.glob("train-*.npz"))``)."""
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(f"shard files not found: {missing[:3]}")
        return cls(list(paths), csv_kwargs=csv_kwargs)

    @classmethod
    def from_datasets(cls, datasets: Sequence[Dataset]):
        return cls(list(datasets))

    @classmethod
    def write(cls, dataset: Dataset, directory: str, num_shards: int,
              prefix: str = "shard") -> "ShardedDataset":
        """Split an in-memory ``Dataset`` into ``num_shards`` npz files
        under ``directory`` and return the ShardedDataset over them —
        the round-trip utility for preparing out-of-core training data."""
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        n = len(dataset)
        if n < num_shards:
            raise ValueError(
                f"cannot split {n} rows into {num_shards} shards")
        os.makedirs(directory, exist_ok=True)
        bounds = np.linspace(0, n, num_shards + 1).astype(int)
        paths = []
        for i in range(num_shards):
            sl = slice(bounds[i], bounds[i + 1])
            path = os.path.join(directory,
                                f"{prefix}-{i:05d}-of-{num_shards:05d}.npz")
            np.savez(path, **{c: dataset[c][sl] for c in dataset.columns})
            paths.append(path)
        return cls.from_files(paths)

    # -- access -------------------------------------------------------------
    @property
    def num_shards(self) -> int:
        return len(self.sources)

    def load_shard(self, i: int) -> Dataset:
        src = self.sources[i]
        if isinstance(src, Dataset):
            return src
        if callable(src):
            out = src()
            if not isinstance(out, Dataset):
                raise TypeError(
                    f"shard loader {i} returned {type(out).__name__}, "
                    "expected Dataset")
            return out
        path = str(src)
        if path.endswith(".npz"):
            with np.load(path) as z:
                return Dataset({k: z[k] for k in z.files})
        if path.endswith(".csv"):
            return Dataset.from_csv(path, **self.csv_kwargs)
        raise ValueError(
            f"unrecognized shard source {path!r} (expected .npz, .csv, "
            "Dataset, or callable)")

    def shard_order(self, epoch: int, seed: int,
                    shuffle: bool) -> List[int]:
        """Deterministic per-epoch shard visit order."""
        if not shuffle or self.num_shards == 1:
            return list(range(self.num_shards))
        rs = np.random.RandomState(seed + 7919 * (epoch + 1))
        return list(rs.permutation(self.num_shards))

    def epoch_items(self, start_epoch: int, num_epoch: int, seed: int,
                    shuffle: bool) -> List[tuple]:
        """The flattened ``(epoch, shard_idx, is_epoch_last)`` visit
        sequence for epochs ``[start_epoch, num_epoch)`` — the work list
        a single flat ``Prefetcher`` stream iterates (overlap PR:
        one stream spanning epoch boundaries keeps the loader AND the
        device-staging ``place`` hook busy across epochs; a per-epoch
        stream would stall one shard load + one H2D copy at every
        boundary). The order derives only from ``shard_order``, so every
        consumer shares the same shuffle-determinism formula."""
        items = []
        for e in range(start_epoch, num_epoch):
            order = self.shard_order(e, seed, shuffle)
            items += [(e, si, i == len(order) - 1)
                      for i, si in enumerate(order)]
        return items

    # NOTE deliberately no __len__: shards load lazily, so there is no
    # cheap global length (len() raising the standard TypeError also keeps
    # bool(sds) truthy — a __len__ that raises would break `if sds:`)

    def __repr__(self):
        return f"ShardedDataset(num_shards={self.num_shards})"
