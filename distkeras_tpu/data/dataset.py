"""Columnar dataset — the Spark-DataFrame replacement.

The reference's data plane is a Spark DataFrame: named columns, row-oriented
iteration inside executors, `features_col`/`label_col` selection by every
trainer/predictor (reference: ``distkeras/trainers.py`` constructor kwargs;
``distkeras/workers.py`` assembles minibatches from Row iterators —
per-row marshalling that SURVEY §3.1 flags as a real bottleneck).

TPU-first redesign: a ``Dataset`` is a dict of named **columnar numpy
arrays**. Batches are zero-copy slices of contiguous columns, already shaped
``[batch, ...]`` for direct device transfer — no per-row materialization
anywhere. The API keeps the DataFrame ergonomics the reference's users have
(named columns, select/with_column/shuffle, features/label selection).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


def coerce_column(X: np.ndarray) -> np.ndarray:
    """Contiguous host array with the framework dtype policy: integer
    columns (token ids / class labels) keep exact integers — a float32 cast
    would corrupt ids above 2^24 — everything else becomes float32. The ONE
    coercion rule shared by training (``Dataset.arrays``) and inference
    (``inference.predictors``)."""
    X = np.asarray(X)
    if np.issubdtype(X.dtype, np.integer):
        return np.ascontiguousarray(X)
    return np.ascontiguousarray(X, dtype=np.float32)


class Dataset:
    """Immutable columnar dataset: named numpy columns of equal length."""

    def __init__(self, columns: Dict[str, np.ndarray]):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"Column length mismatch: {lengths}")
        self._columns = {k: np.asarray(v) for k, v in columns.items()}

    # -- construction -----------------------------------------------------
    @classmethod
    def from_arrays(cls, features, labels=None, features_col: str = "features",
                    label_col: str = "label") -> "Dataset":
        cols = {features_col: np.asarray(features)}
        if labels is not None:
            cols[label_col] = np.asarray(labels)
        return cls(cols)

    @classmethod
    def from_records(cls, records: Sequence[Dict]) -> "Dataset":
        """List-of-dicts (row) input -> columnar storage."""
        if not records:
            raise ValueError("empty records")
        keys = records[0].keys()
        return cls({k: np.asarray([r[k] for r in records]) for k in keys})

    @classmethod
    def from_csv(cls, path, *, label_col_index: Optional[int] = None,
                 sep: str = ",", skip_header: bool = False,
                 features_col: str = "features",
                 label_col: str = "label") -> "Dataset":
        """Numeric CSV ingest (native strtof parser when available) — the
        reference examples' ``spark.read.csv`` equivalent. When
        ``label_col_index`` is given, that column becomes an integer label
        column and the rest become the features matrix."""
        from distkeras_tpu.data import native
        data = native.read_csv(path, sep=sep, skip_header=skip_header)
        if label_col_index is None:
            return cls({features_col: data})
        y = data[:, label_col_index].astype(np.int64)
        X = np.ascontiguousarray(
            np.delete(data, label_col_index, axis=1), dtype=np.float32)
        return cls({features_col: X, label_col: y})

    @classmethod
    def from_pandas(cls, df) -> "Dataset":
        """pandas DataFrame -> Dataset: one column per frame column
        (object/string columns kept as numpy object arrays for the
        StringIndexer/Hashing transformers). The Spark-DataFrame-handoff
        analogue for the common pandas interchange case."""
        return cls({str(c): np.asarray(df[c].to_numpy())
                    for c in df.columns})

    @classmethod
    def from_parquet(cls, path, columns: Optional[Sequence[str]] = None
                     ) -> "Dataset":
        """Parquet ingest via pyarrow (the reference's de-facto Spark
        storage format). List-valued columns become 2-D feature
        matrices."""
        import pyarrow.parquet as pq

        table = pq.read_table(path, columns=list(columns) if columns
                              else None)
        out = {}
        for name in table.column_names:
            col = table.column(name)
            arr = col.to_numpy(zero_copy_only=False)
            if arr.dtype == object and len(arr) and isinstance(
                    arr[0], np.ndarray):
                arr = np.stack(arr)  # fixed-size list column -> matrix
            out[name] = arr
        return cls(out)

    # -- introspection ----------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return len(next(iter(self._columns.values())))

    def __getitem__(self, col: str) -> np.ndarray:
        try:
            return self._columns[col]
        except KeyError:
            raise KeyError(
                f"No column {col!r}; available: {self.columns}")

    def __contains__(self, col: str) -> bool:
        return col in self._columns

    def __repr__(self):
        spec = ", ".join(f"{k}:{v.dtype}{list(v.shape[1:])}"
                         for k, v in self._columns.items())
        return f"Dataset(rows={len(self)}, {spec})"

    # -- transformations (all return new Datasets) ------------------------
    def select(self, cols: Sequence[str]) -> "Dataset":
        return Dataset({c: self[c] for c in cols})

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        """Reference parity: ``utils.new_dataframe_row`` appended a column to
        every row; columnar equivalent is one array assignment."""
        cols = dict(self._columns)
        cols[name] = np.asarray(values)
        return Dataset(cols)

    def drop(self, name: str) -> "Dataset":
        cols = {k: v for k, v in self._columns.items() if k != name}
        return Dataset(cols)

    def shuffle(self, seed: int = 0) -> "Dataset":
        """Reference parity: ``utils.shuffle(df)`` (rand column + sort).
        Columnar equivalent: one permutation applied to every column
        (multithreaded native gather on large columns)."""
        from distkeras_tpu.data import native
        perm = np.random.RandomState(seed).permutation(len(self))
        return Dataset({k: native.gather(v, perm)
                        for k, v in self._columns.items()})

    def filter(self, mask) -> "Dataset":
        """Row subset by boolean mask — ``mask`` is a length-N bool array
        or a callable ``Dataset -> bool array`` (the DataFrame-ish
        ``df.filter(df.label == 1)`` idiom):
        ``ds.filter(lambda d: d["label"] == 1)``."""
        if callable(mask):
            mask = mask(self)
        mask = np.asarray(mask)
        if mask.dtype != np.bool_ or mask.shape != (len(self),):
            raise ValueError(
                f"filter mask must be bool[{len(self)}], got "
                f"{mask.dtype}{list(mask.shape)}")
        from distkeras_tpu.data import native
        idx = np.flatnonzero(mask)  # multithreaded gather, as shuffle does
        return Dataset({k: native.gather(v, idx)
                        for k, v in self._columns.items()})

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._columns.items()})

    def skip(self, n: int) -> "Dataset":
        return Dataset({k: v[n:] for k, v in self._columns.items()})

    def split(self, fraction: float) -> Tuple["Dataset", "Dataset"]:
        n = int(len(self) * fraction)
        return self.take(n), self.skip(n)

    def map_column(self, col: str, fn: Callable[[np.ndarray], np.ndarray],
                   output_col: Optional[str] = None) -> "Dataset":
        """Vectorized column map — the engine under every feature
        transformer (fn sees the WHOLE column at once, never rows)."""
        return self.with_column(output_col or col, fn(self[col]))

    def concat(self, other: "Dataset") -> "Dataset":
        if set(self.columns) != set(other.columns):
            raise ValueError("column sets differ")
        return Dataset({k: np.concatenate([self[k], other[k]])
                        for k in self.columns})

    # -- training views ---------------------------------------------------
    def arrays(self, features_col: str = "features",
               label_col: Optional[str] = "label"):
        X = coerce_column(self[features_col])
        if label_col is None or label_col not in self:
            return X, None
        return X, coerce_column(self[label_col])

    def batches(self, batch_size: int, features_col: str = "features",
                label_col: Optional[str] = "label",
                drop_remainder: bool = True
                ) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """Contiguous columnar minibatches (replaces the reference's per-row
        Row-iterator minibatch assembly in ``workers.py``)."""
        X, y = self.arrays(features_col, label_col)
        n = len(X)
        end = (n // batch_size) * batch_size if drop_remainder else n
        for i in range(0, end, batch_size):
            xb = X[i:i + batch_size]
            yb = y[i:i + batch_size] if y is not None else None
            yield xb, yb
