"""ctypes binding for the native data kernels (``native/dkt_data.cc``).

Role: the reference delegates its data plane to Spark's JVM machinery;
the TPU framework's host data path is native C++ instead — multithreaded
permutation gather (the per-epoch shuffle), one-hot/min-max transforms,
and CSV parsing. Every entry point has a numpy fallback, selected when

  * the shared library is missing and cannot be built (no compiler), or
  * ``DKT_DISABLE_NATIVE=1`` is set (CI / debugging), or
  * the input is too small for threading to pay for itself.

The library is compiled on first use from the repo's ``native/`` directory
with the same one-liner as ``native/Makefile`` and cached next to the
source; rebuilds happen only when the source is newer than the binary.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).resolve().parent.parent.parent / "native" / "dkt_data.cc"
_SO = _SRC.with_name("libdkt_data.so")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None

# below this many bytes the ctypes/threading overhead beats the win
_MIN_NATIVE_BYTES = 1 << 22  # 4 MiB


def _build() -> Optional[str]:
    """Compile the shared library; returns an error string or None."""
    if not _SRC.exists():
        return f"source not found: {_SRC}"
    # build to a per-process temp name, then atomically rename: an
    # interrupted or concurrent build must never leave a truncated .so
    # that poisons every future load
    tmp = _SO.with_name(f".{_SO.name}.{os.getpid()}.tmp")
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-shared",
           "-o", str(tmp), str(_SRC)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        tmp.unlink(missing_ok=True)
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        return f"build failed: {proc.stderr[-500:]}"
    try:
        os.replace(tmp, _SO)
    except OSError as e:
        tmp.unlink(missing_ok=True)
        return f"rename failed: {e}"
    return None


def _load():
    """Load (building if needed) the native library, or None on failure."""
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    if os.environ.get("DKT_DISABLE_NATIVE") == "1":
        _build_error = "disabled via DKT_DISABLE_NATIVE"
        return None
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if (not _SO.exists()
                or _SO.stat().st_mtime < _SRC.stat().st_mtime):
            err = _build()
            if err is not None:
                _build_error = err
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            _build_error = f"load failed: {e}"
            return None
        c = ctypes
        lib.dkt_gather.argtypes = [c.c_char_p, c.POINTER(c.c_int64),
                                   c.c_char_p, c.c_int64, c.c_int64, c.c_int]
        lib.dkt_one_hot.argtypes = [c.POINTER(c.c_int64), c.POINTER(c.c_float),
                                    c.c_int64, c.c_int64, c.c_int]
        lib.dkt_one_hot.restype = c.c_int64
        lib.dkt_col_minmax.argtypes = [
            c.POINTER(c.c_float), c.c_int64, c.c_int64,
            c.POINTER(c.c_float), c.POINTER(c.c_float), c.c_int]
        lib.dkt_minmax_scale.argtypes = [
            c.POINTER(c.c_float), c.c_int64, c.c_int64,
            c.POINTER(c.c_float), c.POINTER(c.c_float),
            c.c_float, c.c_float, c.POINTER(c.c_float), c.c_int]
        lib.dkt_csv_parse_f32.argtypes = [c.c_char_p, c.c_int64, c.c_char,
                                          c.POINTER(c.c_float), c.c_int64]
        lib.dkt_csv_parse_f32.restype = c.c_int64
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_status() -> str:
    if _load() is not None:
        return f"native: {_SO}"
    return f"fallback: {_build_error}"


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def gather(src: np.ndarray, perm: np.ndarray, *, threads: int = 0
           ) -> np.ndarray:
    """``src[perm]`` for row-major arrays — multithreaded in native mode.

    This is the per-epoch shuffle of every trainer (``_epoch_perm`` →
    ``shard_epoch_data``); numpy's fancy indexing is single-threaded, so
    the native path wins on big datasets.
    """
    src = np.ascontiguousarray(src)
    lib = _load()
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    n = len(perm)
    if lib is None or n * row_bytes < _MIN_NATIVE_BYTES:
        return src[perm]
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    if n and (perm.min() < 0 or perm.max() >= len(src)):
        raise IndexError("perm out of range")
    out = np.empty((n,) + src.shape[1:], src.dtype)
    lib.dkt_gather(src.ctypes.data_as(ctypes.c_char_p), _i64p(perm),
                   out.ctypes.data_as(ctypes.c_char_p),
                   n, row_bytes, threads)
    return out


def one_hot(labels: np.ndarray, num_classes: int, *, threads: int = 0
            ) -> np.ndarray:
    """Labels ``[n]`` -> one-hot ``[n, num_classes]`` float32. Out-of-range
    labels produce all-zero rows (both paths)."""
    labels = np.ascontiguousarray(labels, dtype=np.int64).reshape(-1)
    n = len(labels)
    lib = _load()
    if lib is None or n * num_classes * 4 < _MIN_NATIVE_BYTES:
        out = np.zeros((n, num_classes), np.float32)
        ok = (labels >= 0) & (labels < num_classes)
        out[np.arange(n)[ok], labels[ok]] = 1.0
        return out
    out = np.zeros((n, num_classes), np.float32)
    lib.dkt_one_hot(_i64p(labels), _f32p(out), n, num_classes, threads)
    return out


def minmax_fit(x: np.ndarray, *, threads: int = 0):
    """Column-wise (min, max) of ``[n, d]`` float32."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    n, d = x.shape
    lib = _load()
    if lib is None or x.nbytes < _MIN_NATIVE_BYTES:
        return x.min(axis=0), x.max(axis=0)
    mins = np.empty((d,), np.float32)
    maxs = np.empty((d,), np.float32)
    lib.dkt_col_minmax(_f32p(x), n, d, _f32p(mins), _f32p(maxs), threads)
    return mins, maxs


def minmax_scale(x: np.ndarray, mins, maxs, lo: float = 0.0, hi: float = 1.0,
                 *, threads: int = 0) -> np.ndarray:
    """Affine rescale to [lo, hi] per column; degenerate columns -> lo."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    n, d = x.shape
    mins = np.ascontiguousarray(mins, dtype=np.float32)
    maxs = np.ascontiguousarray(maxs, dtype=np.float32)
    lib = _load()
    if lib is None or x.nbytes < _MIN_NATIVE_BYTES:
        rng = maxs - mins
        scale = np.where(rng > 0, (hi - lo) / np.where(rng > 0, rng, 1), 0.0)
        return (x * scale + (lo - mins * scale)).astype(np.float32)
    out = np.empty_like(x)
    lib.dkt_minmax_scale(_f32p(x), n, d, _f32p(mins), _f32p(maxs),
                         lo, hi, _f32p(out), threads)
    return out


def read_csv(path, *, sep: str = ",", skip_header: bool = False,
             dtype=np.float32) -> np.ndarray:
    """Numeric CSV -> ``[rows, cols]`` array (native strtof parser when
    available). Column count is taken from the first data line."""
    with open(path, "rb") as f:
        buf = f.read()
    if skip_header:
        nl = buf.find(b"\n")
        buf = buf[nl + 1:] if nl >= 0 else b""
    first = buf.split(b"\n", 1)[0].strip()
    if not first:
        return np.empty((0, 0), dtype)
    cols = len([t for t in first.replace(b"\t", sep.encode())
                .split(sep.encode()) if t.strip()])
    lib = _load()
    if lib is None:
        rows = [
            [float(t) for t in line.replace(b"\t", sep.encode())
             .split(sep.encode()) if t.strip()]
            for line in buf.split(b"\n") if line.strip()]
        return np.asarray(rows, dtype)
    max_vals = buf.count(b"\n") * cols + cols + 1
    out = np.empty((max_vals,), np.float32)
    n = lib.dkt_csv_parse_f32(buf, len(buf), sep.encode()[0] if sep else b",",
                              _f32p(out), max_vals)
    if n < 0:
        raise ValueError(f"malformed numeric CSV: {path}")
    if cols == 0 or n % cols != 0:
        raise ValueError(
            f"ragged CSV: {n} values not divisible by {cols} columns")
    return out[:n].reshape(-1, cols).astype(dtype, copy=False)
