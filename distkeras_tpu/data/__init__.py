"""Data plane: columnar Dataset + feature transformers (Spark-DataFrame
ingest replacement)."""

from distkeras_tpu.data.dataset import Dataset, coerce_column  # noqa: F401
from distkeras_tpu.data.adapters import from_iterable, from_torch  # noqa: F401,E501
from distkeras_tpu.data.sharded import ShardedDataset  # noqa: F401
from distkeras_tpu.data.transformers import (  # noqa: F401
    DenseTransformer, LabelIndexTransformer, MinMaxTransformer,
    HashingTransformer, OneHotTransformer, ReshapeTransformer,
    StandardScaleTransformer, StringIndexerTransformer,
    Transformer, VectorAssemblerTransformer)
from distkeras_tpu.data import native  # noqa: F401
