"""Host-side control-plane networking: framed messages over TCP.

Reference parity: ``distkeras/networking.py`` (SURVEY §2.1) —
``determine_host_address``, ``connect``, ``send_data``/``recv_data`` with
length-prefixed pickle framing. In the reference this carried ALL gradient
traffic (worker↔parameter-server pull/commit); here it is strictly a
**control plane**: job submission (``deploy``), the socket parameter-server
fallback for DCN-scale experiments, and daemon RPC. The data plane — every
per-step gradient/weight exchange of the SPMD trainers — rides XLA
collectives over ICI/DCN (``parallel/engine.py``), never these sockets
(SURVEY §5.8 north star: zero socket-PS traffic).

Differences from the reference, by design:
  * an explicit magic + length + format header instead of bare pickled
    frames, so a stray connection can't crash the server mid-unpickle;
  * numpy arrays ship as raw buffers (zero pickle memo overhead) under
    format tag ``NPY``; everything else is pickled (trusted-cluster
    assumption, as in the reference);
  * ``serve_forever`` helper with a clean shutdown path — the reference
    unblocked its ``accept()`` loop with a self-connect trick
    (``parameter_servers.py :: SocketParameterServer.stop`` [verify]); here
    the listener socket is simply closed and the error swallowed.
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import threading
from typing import Any, Callable, Optional, Tuple

import numpy as np

MAGIC = b"DKT1"
_FMT_PICKLE = 0
_FMT_NPY = 1
_HEADER = struct.Struct("!4sBQ")  # magic, format, payload length


def determine_host_address() -> str:
    """Best-effort routable address of this host (reference:
    ``networking.py :: determine_host_address``). Opens a UDP socket to a
    public address (no traffic is sent) and reads the chosen source addr;
    falls back to localhost on isolated machines."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def connect(host: str, port: int, timeout: Optional[float] = None
            ) -> socket.socket:
    """TCP connect with Nagle disabled — control messages are small and
    latency-bound (reference: ``networking.py :: connect``)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _encode(obj: Any) -> Tuple[int, bytes]:
    if isinstance(obj, np.ndarray) and obj.dtype != object:
        buf = io.BytesIO()
        np.save(buf, obj, allow_pickle=False)
        return _FMT_NPY, buf.getvalue()
    return _FMT_PICKLE, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def send_data(sock: socket.socket, obj: Any) -> None:
    """Write one framed message (reference: ``networking.py :: send_data``)."""
    fmt, payload = _encode(obj)
    sock.sendall(_HEADER.pack(MAGIC, fmt, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_data(sock: socket.socket) -> Any:
    """Read one framed message (reference: ``networking.py :: recv_data``)."""
    magic, fmt, length = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    payload = _recv_exact(sock, length)
    if fmt == _FMT_NPY:
        return np.load(io.BytesIO(payload), allow_pickle=False)
    return pickle.loads(payload)


class MessageServer:
    """Threaded request/response server over framed messages.

    The skeleton of both the socket parameter server and the punchcard-style
    job daemon (reference: ``parameter_servers.py :: SocketParameterServer``'s
    accept loop + per-connection handler threads). ``handler(msg) -> reply``
    runs under no lock — handlers do their own synchronization; a handler
    exception becomes an ``{"error": ...}`` reply instead of killing the
    connection.

    SECURITY: the payload format includes pickle, so a connected peer can
    execute code in this process. The default bind is therefore localhost;
    pass an explicit ``host`` (e.g. ``"0.0.0.0"``) only on a trusted-cluster
    network — the same trust model as the reference's pickled-TCP protocol.
    """

    def __init__(self, handler: Callable[[Any], Any],
                 host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._host, self._port = host, port
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._running = False

    @property
    def port(self) -> int:
        if self._listener is None:
            raise RuntimeError("server not started")
        return self._listener.getsockname()[1]

    def start(self) -> "MessageServer":
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self._host, self._port))
        self._listener.listen(128)
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        import time
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                if not self._running:
                    return  # listener closed by stop()
                # transient accept failure (ECONNABORTED, EMFILE under fd
                # pressure, ...): keep serving — exiting here would leave a
                # bound-but-unserved port and hang every future client
                time.sleep(0.05)
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # per-connection threads are daemonized and self-terminating;
            # holding references would only accumulate dead Thread objects
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            with conn:
                while True:
                    try:
                        msg = recv_data(conn)
                    except (ConnectionError, ValueError, OSError):
                        return
                    try:
                        reply = self._handler(msg)
                    except Exception as e:  # noqa: BLE001 — reply, don't die
                        reply = {"error": f"{type(e).__name__}: {e}"}
                    send_data(conn, reply)
        except (BrokenPipeError, OSError):
            return

    def stop(self):
        self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


def request(sock: socket.socket, msg: Any) -> Any:
    """One round-trip on an open connection."""
    send_data(sock, msg)
    return recv_data(sock)
