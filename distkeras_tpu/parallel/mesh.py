"""Device-mesh abstraction over ICI/DCN.

This is the scheduling substrate that replaces Apache Spark in the reference
(SURVEY §1: "The scheduler is Spark" — dist-keras submits one Spark job whose
partitions become training workers). Here "workers" are positions along an
axis of a ``jax.sharding.Mesh``; placing work is a sharding annotation, and
worker↔center communication compiles to XLA collectives over ICI instead of
pickled TCP to a driver thread (reference: ``distkeras/networking.py``).

Axis conventions used across the framework:
  * ``workers`` — data-parallel worker axis (the reference's num_workers)
  * ``tp``      — tensor-parallel axis (no reference equivalent)
  * ``sp``      — sequence-parallel axis for ring attention (no reference
                  equivalent)
Multi-host: build the mesh over ``jax.devices()`` after
``jax.distributed.initialize()`` — the same code then spans hosts over DCN.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_workers: Optional[int] = None,
              axis_name: str = "workers",
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D worker mesh: the data-parallel Spark-executor-pool equivalent."""
    devices = list(devices if devices is not None else jax.devices())
    n = num_workers or len(devices)
    if n > len(devices):
        raise ValueError(
            f"num_workers={n} exceeds available devices ({len(devices)}). "
            "The reference oversubscribed Spark executors via "
            "parallelism_factor; on a TPU mesh workers map 1:1 onto chips.")
    return Mesh(np.array(devices[:n]), (axis_name,))


def make_mesh_2d(shape: Dict[str, int],
                 devices: Optional[Sequence] = None) -> Mesh:
    """N-D mesh, e.g. ``{"workers": 4, "tp": 2}``. Axis order follows dict
    order; the innermost axis should be the highest-bandwidth one (tp/sp over
    ICI neighbors)."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = list(shape.values())
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {shape} needs {total} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def worker_sharded(mesh: Mesh, axis_name: str = "workers") -> NamedSharding:
    """Sharding for arrays with a leading per-worker axis."""
    return NamedSharding(mesh, P(axis_name))
