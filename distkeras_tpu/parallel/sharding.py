"""Tensor/expert-parallel sharding rules: params pytree -> PartitionSpec tree.

The reference has no tensor parallelism of any kind (SURVEY §2.3: TP is
"absent in the reference" — dist-keras workers each hold a FULL model
replica). This module is the TPU-native capability ADD that makes models
larger than one chip's HBM trainable: it walks a ``models.core.Layer`` tree
and produces a ``PartitionSpec`` pytree mirroring the params/opt-state
pytrees, which the ``SPMDTrainer`` (``parallel/spmd.py``) turns into
``NamedSharding``s for ``jax.jit`` — XLA's GSPMD partitioner then inserts
the all-gathers/reduce-scatters over ICI automatically (scaling-book recipe:
pick a mesh, annotate shardings, let XLA place collectives).

Rules follow the Megatron-LM column→row convention so that, within one
transformer block, GSPMD needs exactly two collectives per residual branch:

  * attention: wq/wk/wv shard the HEADS axis (column-parallel), wo shards
    its heads INPUT axis (row-parallel) → one psum after wo;
  * MLP: w1 column-parallel [d, hidden/tp], w2 row-parallel [hidden/tp, d]
    → one psum after w2;
  * MoE: experts shard the EXPERT axis (expert parallelism); gate stays
    replicated. w1/w2 may additionally shard hidden on tp;
  * Embedding / final Dense head: shard the model/vocab dim.

A dimension is only sharded when the mesh axis divides it; otherwise the
rule degrades to replicated for that dim (never an error — small models on
big meshes just replicate).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


def _axis_size(mesh: Mesh, axis) -> int:
    """Total size of a (possibly tuple) mesh-axis spec entry."""
    if axis is None:
        return 1
    if isinstance(axis, (tuple, list)):
        size = 1
        for a in axis:
            size *= mesh.shape[a]
        return size
    return mesh.shape[axis]


class ShardingRules:
    """Produces a PartitionSpec pytree for a module's params/state.

    ``tp_axis``/``ep_axis`` name mesh axes (or None to disable). ``fsdp_axis``
    optionally ZeRO-shards otherwise-replicated large kernels along their
    biggest divisible dim (fully-sharded data parallelism over the data
    axis — params are all-gathered by GSPMD just-in-time per layer).
    """

    def __init__(self, mesh: Mesh, tp_axis: Optional[str] = "tp",
                 ep_axis: Optional[str] = None,
                 fsdp_axis: Optional[str] = None,
                 min_fsdp_size: int = 2 ** 16):
        def present(a):
            return a if a is not None and a in mesh.shape else None
        self.mesh = mesh
        self.tp = present(tp_axis)
        self.ep = present(ep_axis)
        self.fsdp = present(fsdp_axis)
        self.min_fsdp_size = int(min_fsdp_size)

    # -- helpers -----------------------------------------------------------
    def _fits(self, axis, dim: int) -> bool:
        return axis is not None and dim % _axis_size(self.mesh, axis) == 0

    def _tp(self, dim: int):
        return self.tp if self._fits(self.tp, dim) else None

    def _ep(self, dim: int):
        return self.ep if self._fits(self.ep, dim) else None

    def _maybe_fsdp(self, spec: P, shape: Tuple[int, ...]) -> P:
        """Shard the largest still-replicated dim over the fsdp axis."""
        if self.fsdp is None or not shape:
            return spec
        import numpy as np
        if int(np.prod(shape)) < self.min_fsdp_size:
            return spec
        entries = list(spec) + [None] * (len(shape) - len(spec))
        cands = [(shape[i], i) for i, e in enumerate(entries)
                 if e is None and self._fits(self.fsdp, shape[i])]
        if not cands:
            return spec
        _, i = max(cands)
        entries[i] = self.fsdp
        return P(*entries)

    # -- per-layer rules ---------------------------------------------------
    def specs_for(self, layer, params: Pytree) -> Pytree:
        """PartitionSpec tree mirroring ``params`` of ``layer``."""
        name = type(layer).__name__
        rule = getattr(self, f"_rule_{name}", None)
        if rule is not None:
            return rule(layer, params)
        return self._generic(layer, params)

    def _generic(self, layer, params):
        """Containers: recurse by matching param keys to child-layer attrs.
        Leaves with no rule: replicated (+ optional fsdp)."""
        from distkeras_tpu.models.core import Layer, Sequential

        if isinstance(layer, Sequential) and isinstance(params, (list, tuple)):
            return [self.specs_for(l, p)
                    for l, p in zip(layer.layers, params)]
        if isinstance(params, dict) and layer is not None:
            out = {}
            for key, sub in params.items():
                child = getattr(layer, key, None)
                if isinstance(child, Layer):
                    out[key] = self.specs_for(child, sub)
                else:
                    out[key] = self._replicated(sub)
            return out
        return self._replicated(params)

    def _replicated(self, tree):
        return jax.tree_util.tree_map(
            lambda x: self._maybe_fsdp(P(), x.shape), tree)

    # Dense [in, units]: column-parallel on units (head matmuls / generic
    # projections). GSPMD reshards activations between mismatched layers.
    def _rule_Dense(self, layer, params):
        out = {}
        if "kernel" in params:
            units = params["kernel"].shape[-1]
            tp = self._tp(units)
            out["kernel"] = self._maybe_fsdp(P(None, tp),
                                             params["kernel"].shape)
        if "bias" in params:
            out["bias"] = P(self._tp(params["bias"].shape[-1]))
        return out

    # Conv2D [kh, kw, cin, cout]: shard output channels.
    def _rule_Conv2D(self, layer, params):
        out = {}
        if "kernel" in params:
            cout = params["kernel"].shape[-1]
            tp = self._tp(cout)
            out["kernel"] = self._maybe_fsdp(P(None, None, None, tp),
                                             params["kernel"].shape)
        if "bias" in params:
            out["bias"] = P(self._tp(params["bias"].shape[-1]))
        return out

    # Embedding [vocab, d]: shard the model dim (keeps the token gather
    # local; the d-shards concatenate for free downstream).
    def _rule_Embedding(self, layer, params):
        d = params["embeddings"].shape[-1]
        return {"embeddings": self._maybe_fsdp(
            P(None, self._tp(d)), params["embeddings"].shape)}

    def _rule_PositionalEmbedding(self, layer, params):
        d = params["embeddings"].shape[-1]
        return {"embeddings": P(None, self._tp(d))}

    # MHA: wq/wk/wv [d, H, Dh] column-parallel on heads; wo [H, Dh, d]
    # row-parallel on heads (Megatron split — one psum per attention).
    # GQA: wk/wv carry only kv_heads heads, so their shard decision uses
    # THEIR head count — tp > kv_heads degrades those two to replicated
    # (never an error), while wq/wo still shard on the full head axis.
    def _rule_MultiHeadAttention(self, layer, params):
        tp_q = self._tp(params["wq"].shape[1])
        tp_kv = self._tp(params["wk"].shape[1])
        return {
            "wq": self._maybe_fsdp(P(None, tp_q, None), params["wq"].shape),
            "wk": self._maybe_fsdp(P(None, tp_kv, None),
                                   params["wk"].shape),
            "wv": self._maybe_fsdp(P(None, tp_kv, None),
                                   params["wv"].shape),
            "wo": self._maybe_fsdp(P(tp_q, None, None), params["wo"].shape),
        }

    # Transformer MLP: w1 [d, hidden] column, w2 [hidden, d] row.
    def _rule_TransformerMLP(self, layer, params):
        hidden = params["w1"].shape[-1]
        tp = self._tp(hidden)
        return {
            "w1": self._maybe_fsdp(P(None, tp), params["w1"].shape),
            "b1": P(tp),
            "w2": self._maybe_fsdp(P(tp, None), params["w2"].shape),
            "b2": P(),
        }

    # MoE: expert-parallel on the expert axis; hidden additionally tp-sharded
    # (the column→row split inside each expert).
    def _rule_MoE(self, layer, params):
        e = params["w1"].shape[0]
        hidden = params["w1"].shape[-1]
        ep, tp = self._ep(e), self._tp(hidden)
        if ep is not None and getattr(layer, "expert_unroll", False):
            # Warn HERE, at spec-derivation time (trainer setup), because
            # this is where layer config and expert-axis sharding meet on
            # concrete values: inside the jitted train step the layer's
            # own guard sees only tracers (no .sharding) and cannot fire,
            # so the unroll WILL run there and pay per-expert cross-shard
            # resharding collectives every step.
            import warnings
            warnings.warn(
                "MoE(expert_unroll=True) with GSPMD expert-axis sharding "
                f"(axis {self.ep!r}): per-expert slices of the "
                "expert-sharded stacked weights force cross-shard "
                "resharding collectives every step. Set "
                "expert_unroll=False for GSPMD expert parallelism, or "
                "use shard_map EP (expert_axis_name) where the unroll "
                "is safe.", stacklevel=2)
        return {
            "gate": P(),
            "w1": P(ep, None, tp),
            "b1": P(ep, tp),
            "w2": P(ep, tp, None),
            "b2": P(ep, None),
        }

    # Remat is a transparent wrapper: its params ARE the inner layer's
    def _rule_Remat(self, layer, params):
        return self.specs_for(layer.inner, params)

    # LSTM/GRU: wx [in, G*units], wh [units, G*units] — gate blocks make
    # naive column sharding wrong across the gate boundary UNLESS units is
    # divisible: [*, G*units] with units % tp == 0 shards each gate block
    # identically, which is exactly the valid column-parallel split.
    def _rule_LSTM(self, layer, params):
        units = params["wh"].shape[0]
        tp = self._tp(units)
        return {"wx": P(None, tp), "wh": P(None, tp), "b": P(tp)}

    _rule_GRU = _rule_LSTM


def param_specs(module, params: Pytree, mesh: Mesh,
                tp_axis: Optional[str] = "tp",
                ep_axis: Optional[str] = None,
                fsdp_axis: Optional[str] = None) -> Pytree:
    """PartitionSpec pytree for ``params`` of ``module`` (see ShardingRules)."""
    rules = ShardingRules(mesh, tp_axis=tp_axis, ep_axis=ep_axis,
                          fsdp_axis=fsdp_axis)
    return rules.specs_for(module, params)


def named_shardings(spec_tree: Pytree, mesh: Mesh) -> Pytree:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: Pytree, spec_tree: Pytree, mesh: Mesh) -> Pytree:
    """device_put the params according to the spec tree."""
    sh = named_shardings(spec_tree, mesh)
    return jax.tree_util.tree_map(jax.device_put, params, sh)
