"""Worker compute: the jitted local training loop.

Reference parity: ``distkeras/workers.py`` — a Worker deserializes the model
in its executor, assembles minibatches from a row iterator and calls Keras
``train_on_batch`` per batch (SURVEY §3.1 hot loop). The TPU-native redesign
collapses that entire per-worker loop into a ``lax.scan`` over a stacked
``[steps, batch, ...]`` array inside ONE jitted call: no per-batch Python
dispatch, no per-row marshalling, static shapes throughout so XLA keeps the
MXU busy.

The same ``train_step`` body is reused by every trainer:
  * SingleTrainer scans it directly,
  * EnsembleTrainer vmaps it over a stacked model axis,
  * the distributed trainers run it under ``shard_map`` with a collective
    exchange spliced between windows (see ``parallel/engine.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.ops.optimizers import Optimizer, apply_updates


class TrainCarry(NamedTuple):
    """Scan carry for a local training loop (a pure-pytree 'worker')."""
    params: any
    state: any
    opt_state: any
    rng: jax.Array


def make_train_step(module, loss_fn: Callable, optimizer: Optimizer,
                    metric_fns: Optional[dict] = None) -> Callable:
    """Build the per-minibatch step: grad -> optimizer update -> new carry.

    Equivalent role to one ``model.train_on_batch`` call in the reference
    worker loop, as a pure function usable under scan/vmap/shard_map.

    With ``metric_fns`` ({name: fn(y_true, y_pred)}), the step returns
    ``(carry, (loss, {name: value}))`` — the reference's per-batch Keras
    metrics, computed on-device from the training forward's outputs at
    negligible cost (XLA fuses them into the existing graph).
    """

    def train_step(carry: TrainCarry, batch) -> Tuple[TrainCarry, jax.Array]:
        xb, yb = batch
        rng, sub = jax.random.split(carry.rng)

        def objective(params):
            out, new_state = module.apply(params, carry.state, xb,
                                          training=True, rng=sub)
            return loss_fn(yb, out), (new_state, out)

        (loss, (new_state, out)), grads = jax.value_and_grad(
            objective, has_aux=True)(carry.params)
        updates, new_opt_state = optimizer.update(grads, carry.opt_state,
                                                  carry.params)
        new_params = apply_updates(carry.params, updates)
        new_carry = TrainCarry(new_params, new_state, new_opt_state, rng)
        if metric_fns:
            return new_carry, (loss, {name: fn(yb, out)
                                      for name, fn in metric_fns.items()})
        return new_carry, loss

    return train_step


def make_epoch_runner(train_step: Callable) -> Callable:
    """Jitted scan of ``train_step`` over ``[steps, batch, ...]`` data."""

    @jax.jit
    def run(carry: TrainCarry, X: jax.Array, Y: jax.Array):
        carry, losses = lax.scan(train_step, carry, (X, Y))
        return carry, losses

    return run


def shard_epoch_data(X, Y, num_workers: int, batch_size: int, perm=None):
    """Host-side: shape one epoch into ``[S, num_workers, batch, ...]``.

    Plays the role of the reference's ``df.rdd.repartition(num_workers *
    parallelism_factor)`` — but as a zero-copy reshape of the columnar
    arrays, not a cluster shuffle. Drops the remainder (drop_remainder
    batching). The single-device path is the same contract with
    ``num_workers=1`` (see ``stack_batches``).
    """
    if perm is not None:
        from distkeras_tpu.data import native
        X, Y = native.gather(X, perm), native.gather(Y, perm)
    per_step = num_workers * batch_size
    S = len(X) // per_step
    n = S * per_step
    if S == 0:
        raise ValueError(
            f"dataset ({len(X)} rows) smaller than one global step "
            f"({num_workers} workers x batch_size {batch_size})")
    Xs = X[:n].reshape((S, num_workers, batch_size) + X.shape[1:])
    Ys = Y[:n].reshape((S, num_workers, batch_size) + Y.shape[1:])
    return Xs, Ys, S


def stack_batches(X, Y, batch_size: int, perm=None):
    """Single-worker epoch stacking: ``[n_steps, batch_size, ...]``."""
    Xs, Ys, S = shard_epoch_data(X, Y, 1, batch_size, perm)
    return Xs[:, 0], Ys[:, 0], S
