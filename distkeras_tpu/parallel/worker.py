"""Worker compute: the jitted local training loop.

Reference parity: ``distkeras/workers.py`` — a Worker deserializes the model
in its executor, assembles minibatches from a row iterator and calls Keras
``train_on_batch`` per batch (SURVEY §3.1 hot loop). The TPU-native redesign
collapses that entire per-worker loop into a ``lax.scan`` over a stacked
``[steps, batch, ...]`` array inside ONE jitted call: no per-batch Python
dispatch, no per-row marshalling, static shapes throughout so XLA keeps the
MXU busy.

The same ``train_step`` body is reused by every trainer:
  * SingleTrainer scans it directly,
  * EnsembleTrainer vmaps it over a stacked model axis,
  * the distributed trainers run it under ``shard_map`` with a collective
    exchange spliced between windows (see ``parallel/engine.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.core import collect_aux_losses
from distkeras_tpu.ops.optimizers import Optimizer, apply_updates


class TrainCarry(NamedTuple):
    """Scan carry for a local training loop (a pure-pytree 'worker')."""
    params: any
    state: any
    opt_state: any
    rng: jax.Array


def _fused_head_parts(module, loss_fn, metric_fns):
    """Validate + split a model for ``fused_vocab_head`` training.

    Returns ``(trunk, ignore_index, compute_dtype)`` where ``trunk`` is
    the model minus its final vocab projection (whose kernel,
    ``params[-1]["kernel"]``, feeds the fused loss directly).
    """
    from distkeras_tpu.models.core import Sequential
    from distkeras_tpu.models.layers import Dense
    from distkeras_tpu.ops import losses as L

    if metric_fns:
        raise ValueError(
            "fused_vocab_head=True cannot compute per-batch metric_fns: "
            "the logits tensor is never materialized. Evaluate metrics "
            "separately (inference.evaluators) or disable the fusion.")
    if not isinstance(module, Sequential) or not module.layers:
        raise ValueError("fused_vocab_head needs a Sequential model")
    head = module.layers[-1]
    if not (isinstance(head, Dense) and not head.use_bias
            and head.activation is None):
        raise ValueError(
            "fused_vocab_head needs the final layer to be "
            "Dense(use_bias=False, activation=None); got "
            f"{head!r}")
    if loss_fn is L.sparse_categorical_crossentropy_from_logits:
        ignore_index = None
    elif loss_fn is L.masked_sparse_categorical_crossentropy_from_logits:
        ignore_index = -1
    else:
        raise ValueError(
            "fused_vocab_head supports loss="
            "'sparse_categorical_crossentropy_from_logits' or its "
            "masked_ variant; got "
            f"{getattr(loss_fn, '__name__', loss_fn)!r}")
    return Sequential(module.layers[:-1]), ignore_index, head.dtype


def make_train_step(module, loss_fn: Callable, optimizer: Optimizer,
                    metric_fns: Optional[dict] = None,
                    accum_steps: int = 1,
                    param_mask=None, state_mask=None,
                    fused_vocab_head=False) -> Callable:
    """Build the per-minibatch step: grad -> optimizer update -> new carry.

    Equivalent role to one ``model.train_on_batch`` call in the reference
    worker loop, as a pure function usable under scan/vmap/shard_map.

    With ``metric_fns`` ({name: fn(y_true, y_pred)}), the step returns
    ``(carry, (loss, {name: value}))`` — the reference's per-batch Keras
    metrics, computed on-device from the training forward's outputs at
    negligible cost (XLA fuses them into the existing graph).

    ``param_mask`` (a boolean pytree matching params, from
    ``models.core.trainable_mask``) freezes params Keras-style: gradients
    are masked (so optimizer moments stay zero) AND the optimizer's
    updates are masked (so param-coupled terms like adamw/lars/lamb
    weight decay cannot move frozen leaves either) — frozen params are
    bitwise-unchanged through any number of steps. ``state_mask`` (same
    builder over the STATE tree) additionally freezes layer state, the
    Keras inference-mode semantics for frozen BatchNorm: its running
    stats must not drift toward the new data while its frozen
    scale/offset stay matched to the old ones.

    ``accum_steps > 1`` splits the batch into that many microbatches and
    accumulates gradients over an inner ``lax.scan`` before ONE optimizer
    update — the standard memory lever for batches whose activations do
    not fit HBM. Identical math to the full-batch step (the mean of equal
    microbatch means is the batch mean); model state (BN stats) threads
    through the microbatches in order.

    ``fused_vocab_head=True`` (or an int = explicit token-chunk count)
    fuses the model's FINAL bias-free ``Dense``
    projection into a chunked cross-entropy
    (``ops.losses.fused_linear_cross_entropy``) so the ``[B*S, vocab]``
    logits tensor is never materialized — the memory/bandwidth lever for
    large-vocab LMs. Requires a ``Sequential`` ending in
    ``Dense(use_bias=False, activation=None)`` and a sparse-from-logits
    loss (plain or masked); per-batch ``metric_fns`` are unavailable in
    this mode (there are no logits to evaluate them on).
    """
    accum_steps = int(accum_steps)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    fused = None
    if fused_vocab_head:
        fused = _fused_head_parts(module, loss_fn, metric_fns)
        # fused_vocab_head=True -> default chunking; an int picks the
        # token-chunk count explicitly (perf knob, see docs/PERF.md)
        fused_chunks = (8 if fused_vocab_head is True
                        else int(fused_vocab_head))

    def grad_of(params, state, xb, yb, sub):
        def objective(params):
            if fused is not None:
                trunk, ignore_index, cdt = fused
                hidden, t_state = trunk.apply(
                    params[:-1], state[:-1], xb, training=True, rng=sub)
                from distkeras_tpu.ops.losses import \
                    fused_linear_cross_entropy
                loss = fused_linear_cross_entropy(
                    hidden, params[-1]["kernel"], yb,
                    num_chunks=fused_chunks,
                    ignore_index=ignore_index, compute_dtype=cdt)
                new_state = list(t_state) + [state[-1]]
                return loss + collect_aux_losses(new_state), \
                    (new_state, None)
            out, new_state = module.apply(params, state, xb,
                                          training=True, rng=sub)
            # layer-published auxiliary losses (models.core.AUX_LOSS_KEY,
            # e.g. MoE router balance) join the optimized loss here
            return loss_fn(yb, out) + collect_aux_losses(new_state), \
                (new_state, out)

        (loss, (new_state, out)), grads = jax.value_and_grad(
            objective, has_aux=True)(params)
        if param_mask is not None:
            grads = jax.tree_util.tree_map(
                lambda m, g: jnp.where(m, g, 0.0), param_mask, grads)
        mets = ({name: fn(yb, out) for name, fn in metric_fns.items()}
                if metric_fns else {})
        return loss, grads, new_state, mets

    def train_step(carry: TrainCarry, batch) -> Tuple[TrainCarry, jax.Array]:
        xb, yb = batch
        rng, sub = jax.random.split(carry.rng)

        if accum_steps == 1:
            loss, grads, new_state, mets = grad_of(
                carry.params, carry.state, xb, yb, sub)
        else:
            if xb.shape[0] % accum_steps:
                raise ValueError(
                    f"batch of {xb.shape[0]} must divide into "
                    f"accum_steps={accum_steps} microbatches")
            micro = xb.shape[0] // accum_steps
            # STRIDED split (microbatch j = rows j, j+accum, ...): under a
            # data-parallel batch sharding each microbatch then still spans
            # every dp shard — a contiguous split would concentrate each
            # microbatch on a shard subset and serialize the dp axis
            xs = xb.reshape((micro, accum_steps) + xb.shape[1:]) \
                .swapaxes(0, 1)
            ys = yb.reshape((micro, accum_steps) + yb.shape[1:]) \
                .swapaxes(0, 1)
            subs = jax.random.split(sub, accum_steps)

            def body(c, inp):
                state, gacc = c
                x_, y_, r_ = inp
                loss, grads, state, mets = grad_of(carry.params, state,
                                                   x_, y_, r_)
                gacc = jax.tree_util.tree_map(jnp.add, gacc, grads)
                return (state, gacc), (loss, mets)

            zeros = jax.tree_util.tree_map(jnp.zeros_like, carry.params)
            (new_state, gsum), (losses, mets_s) = lax.scan(
                body, (carry.state, zeros), (xs, ys, subs))
            grads = jax.tree_util.tree_map(lambda g: g / accum_steps, gsum)
            loss = losses.mean()
            mets = jax.tree_util.tree_map(lambda m: m.mean(), mets_s)

        updates, new_opt_state = optimizer.update(grads, carry.opt_state,
                                                  carry.params)
        if param_mask is not None:
            updates = jax.tree_util.tree_map(
                lambda m, u: jnp.where(m, u, 0.0), param_mask, updates)
        if state_mask is not None:
            # mask leaves are static Python bools: frozen state keeps the
            # carried value with zero compute
            new_state = jax.tree_util.tree_map(
                lambda m, old, new: new if m else old,
                state_mask, carry.state, new_state)
        new_params = apply_updates(carry.params, updates)
        new_carry = TrainCarry(new_params, new_state, new_opt_state, rng)
        if metric_fns:
            return new_carry, (loss, mets)
        return new_carry, loss

    return train_step


def make_epoch_runner(train_step: Callable) -> Callable:
    """Jitted scan of ``train_step`` over ``[steps, batch, ...]`` data."""

    @jax.jit
    def run(carry: TrainCarry, X: jax.Array, Y: jax.Array):
        carry, losses = lax.scan(train_step, carry, (X, Y))
        return carry, losses

    return run


def shard_epoch_data(X, Y, num_workers: int, batch_size: int, perm=None):
    """Host-side: shape one epoch into ``[S, num_workers, batch, ...]``.

    Plays the role of the reference's ``df.rdd.repartition(num_workers *
    parallelism_factor)`` — but as a zero-copy reshape of the columnar
    arrays, not a cluster shuffle. Drops the remainder (drop_remainder
    batching). The single-device path is the same contract with
    ``num_workers=1`` (see ``stack_batches``).
    """
    if perm is not None:
        from distkeras_tpu.data import native
        X, Y = native.gather(X, perm), native.gather(Y, perm)
    per_step = num_workers * batch_size
    S = len(X) // per_step
    n = S * per_step
    if S == 0:
        raise ValueError(
            f"dataset ({len(X)} rows) smaller than one global step "
            f"({num_workers} workers x batch_size {batch_size})")
    Xs = X[:n].reshape((S, num_workers, batch_size) + X.shape[1:])
    Ys = Y[:n].reshape((S, num_workers, batch_size) + Y.shape[1:])
    return Xs, Ys, S


def stack_batches(X, Y, batch_size: int, perm=None):
    """Single-worker epoch stacking: ``[n_steps, batch_size, ...]``."""
    Xs, Ys, S = shard_epoch_data(X, Y, 1, batch_size, perm)
    return Xs[:, 0], Ys[:, 0], S
