"""True-async training: thread-per-worker against a parameter server.

Reference parity: this IS the reference's concurrency model —
``distkeras/workers.py :: NetworkWorker`` subclasses racing against the
driver-side PS, with staleness arising from wall-clock scheduling rather
than the SPMD engine's deterministic staggering (``parallel/engine.py``
docstring). Use the engine for production throughput (one compiled program,
ICI collectives); use this family to reproduce the reference's genuine
async dynamics, to train across processes/hosts over DCN via the socket
PS, or to exercise heterogeneous worker cadences for real.

One worker = one Python thread driving its own model replica:

    pull center -> K local jitted steps -> algorithm commit -> repeat

On a multi-device host each worker's replica lives on its own device
(``jax.device_put`` pins the carry; jit follows placement), so threads
genuinely overlap device compute. The PS applies commits under its mutex,
exactly serializing concurrent arrivals like the reference
(``parameter_servers.py :: SocketParameterServer`` handler threads).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.core import Model
from distkeras_tpu.parallel.parameter_servers import (
    ADAGParameterServer, DeltaParameterServer, DynSGDParameterServer,
    EASGDParameterServer, ParameterServer, PSClient)
from distkeras_tpu.parallel.trainers import Trainer
from distkeras_tpu.parallel.worker import TrainCarry, make_train_step
from distkeras_tpu.parallel.worker import shard_epoch_data

_ALGORITHMS = ("downpour", "easgd", "dynsgd", "adag")


class HostAsyncTrainer(Trainer):
    """Asynchronous PS training with real thread-level concurrency.

    ``algorithm`` selects the worker/server commit protocol (reference
    worker classes in brackets):

      * ``"downpour"`` — commit accumulated delta, pull fresh center
        [``DOWNPOURWorker`` + ``DeltaParameterServer``]
      * ``"easgd"``    — elastic difference exchange at own cadence
        [``AEASGDWorker`` + EASGD server]
      * ``"dynsgd"``   — delta commit tagged with last-pull clock; server
        scales by 1/staleness [``DynSGDWorker`` + ``DynSGDParameterServer``]
      * ``"adag"``     — delta commit; adaptive per-parameter server rule
        [``ADAGWorker`` + ``ADAGParameterServer``]

    ``transport="inprocess"`` calls the PS directly (one process, the
    default); ``"socket"`` starts the PS on a TCP port and routes every
    pull/commit through the framed wire protocol — the reference's exact
    data path, useful as the DCN fallback and for protocol tests.

    ``communication_window`` may be per-worker (list of K_i) to create REAL
    heterogeneous cadences — the scenario DynSGD exists for.
    """

    def __init__(self, keras_model: Model, algorithm: str = "downpour",
                 num_workers: Optional[int] = None,
                 communication_window: Union[int, Sequence[int]] = 5,
                 rho: float = 5.0, elastic_lr: float = 0.01,
                 adag_learning_rate: float = 0.05,
                 transport: str = "inprocess", **kwargs):
        super().__init__(keras_model, **kwargs)
        if algorithm not in _ALGORITHMS:
            raise ValueError(f"algorithm must be one of {_ALGORITHMS}, "
                             f"got {algorithm!r}")
        if transport not in ("inprocess", "socket"):
            raise ValueError(f"transport must be 'inprocess' or 'socket', "
                             f"got {transport!r}")
        self.algorithm = algorithm
        self.num_workers = int(num_workers or len(jax.devices()))
        self.communication_window = communication_window
        self.alpha = float(rho) * float(elastic_lr)
        self.adag_learning_rate = float(adag_learning_rate)
        self.transport = transport
        self.parameter_server: Optional[ParameterServer] = None

    # -- PS allocation (reference: allocate_parameter_server) --------------
    def allocate_parameter_server(self, params) -> ParameterServer:
        if self.algorithm == "dynsgd":
            return DynSGDParameterServer(params)
        if self.algorithm == "adag":
            return ADAGParameterServer(
                params, learning_rate=self.adag_learning_rate)
        if self.algorithm == "easgd":
            return EASGDParameterServer(params)
        return DeltaParameterServer(params)

    def _windows(self) -> np.ndarray:
        K = self.communication_window
        if np.isscalar(K):
            return np.full((self.num_workers,), int(K), np.int64)
        Ks = np.asarray(K, np.int64)
        if Ks.shape != (self.num_workers,):
            raise ValueError(
                f"communication_window must be scalar or length-"
                f"{self.num_workers}, got shape {Ks.shape}")
        return Ks

    # -- the worker thread body (reference: *Worker.train) ------------------
    def _worker_loop(self, widx: int, client: PSClient, device,
                     step_fn, model: Model, Xw, Yw, K: int,
                     out: Dict[int, Any], errors: List):
        try:
            leaves0, clock = client.pull()
            treedef = jax.tree_util.tree_structure(model.params)
            unflat = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
            params = jax.device_put(unflat(leaves0), device)
            carry = TrainCarry(
                params,
                jax.device_put(model.state, device),
                jax.device_put(self.worker_optimizer.init(params), device),
                jax.device_put(
                    jax.random.PRNGKey(self.seed + 7919 * (widx + 1)),
                    device))
            pull_leaves = leaves0
            step_outs = []
            for s in range(Xw.shape[0]):
                xb = jax.device_put(Xw[s], device)
                yb = jax.device_put(Yw[s], device)
                carry, sout = step_fn(carry, (xb, yb))
                step_outs.append(sout)
                if (s + 1) % K != 0:
                    continue
                w_leaves = [np.asarray(l)
                            for l in jax.tree_util.tree_leaves(carry.params)]
                if self.algorithm == "easgd":
                    center, clock = client.pull()
                    elastic = [self.alpha * (w - c)
                               for w, c in zip(w_leaves, center)]
                    new_w = [w - e for w, e in zip(w_leaves, elastic)]
                    carry = carry._replace(
                        params=jax.device_put(unflat(new_w), device))
                    client.commit(elastic)
                else:
                    delta = [w - p for w, p in zip(w_leaves, pull_leaves)]
                    client.commit(delta, clock=clock)
                    pull_leaves, clock = client.pull()
                    carry = carry._replace(
                        params=jax.device_put(unflat(pull_leaves), device))
            fetched = jax.device_get(step_outs)
            if fetched and isinstance(fetched[0], tuple):  # (loss, metrics)
                losses = np.asarray([f[0] for f in fetched])
                metrics = {nm: np.asarray([f[1][nm] for f in fetched])
                           for nm in fetched[0][1]}
            else:
                losses, metrics = np.asarray(fetched), {}
            out[widx] = {
                "losses": losses,
                "metrics": metrics,
                "state": jax.device_get(carry.state),
                # uncommitted residual, flushed into the center post-join
                "params": [np.asarray(l) for l in
                           jax.tree_util.tree_leaves(carry.params)],
                "pull": pull_leaves,
            }
        except Exception as e:  # surface thread failures to the caller
            errors.append((widx, e))
        finally:
            client.close()

    def _mean_state(self, out, n):
        """Average non-differentiated model state over workers (float leaves
        only; integer counters keep worker 0's value)."""
        return jax.tree_util.tree_map(
            lambda *xs: np.mean(np.stack(xs), axis=0)
            if np.asarray(xs[0]).dtype.kind == "f" else xs[0],
            *[out[i]["state"] for i in range(n)])

    def train(self, dataset: Dataset) -> Model:
        self._reject_step_options()
        model = self.master_model
        X, y = self._training_arrays(dataset)
        n = self.num_workers
        Ks = self._windows()
        devices = jax.devices()

        # resume restores the CENTER; workers restart from it (same
        # semantics as DistributedTrainer / the reference's PS retry)
        manager = self._checkpoint_manager()
        tree, start_epoch = self._maybe_resume(
            manager, {"params": model.params, "state": model.state})
        model = model.replace(params=tree["params"], state=tree["state"])

        self.parameter_server = self.allocate_parameter_server(model.params)
        self.parameter_server.initialize()
        port = None
        if self.transport == "socket":
            port = self.parameter_server.start(host="127.0.0.1")

        step_fn = jax.jit(make_train_step(
            model.module, self.loss, self.worker_optimizer,
            self._metric_fns(), param_mask=self._param_mask(model),
            state_mask=self._state_mask(model)))

        validator = self._make_validator(model.module)
        out: Dict[int, Any] = {}  # latest epoch's worker outputs
        cbs = self._cb_list(
            lambda: (self.parameter_server.get_model(),
                     self._mean_state(out, n) if out else model.state))
        self.record_training_start()
        profile = self._profile_ctx()  # enter/exit by hand: the epoch loop
        profile.__enter__()            # already sits inside a try/finally
        try:
            for epoch in range(start_epoch, self.num_epoch):
                perm = self._epoch_perm(epoch, len(X))
                Xs, Ys, S = shard_epoch_data(X, y, n, self.batch_size, perm)
                out: Dict[int, Any] = {}
                errors: List = []
                threads = []
                for i in range(n):
                    client = (PSClient(host="127.0.0.1", port=port)
                              if port is not None
                              else PSClient(ps=self.parameter_server))
                    t = threading.Thread(
                        target=self._worker_loop,
                        args=(i, client, devices[i % len(devices)], step_fn,
                              model, Xs[:, i], Ys[:, i], int(Ks[i]), out,
                              errors),
                        daemon=True)
                    t.start()
                    threads.append(t)
                for t in threads:
                    t.join()
                if errors:
                    raise errors[0][1]
                losses = np.stack([out[i]["losses"] for i in range(n)],
                                  axis=1)
                self.history.append_epoch(
                    loss=losses,
                    **{nm: np.stack([out[i]["metrics"][nm]
                                     for i in range(n)], axis=1)
                       for nm in out[0]["metrics"]})

                # flush uncommitted partial-window residuals EVERY epoch —
                # workers re-pull the center at the next epoch start, which
                # would silently discard this progress otherwise (reference
                # workers never reset mid-job, so they lose nothing)
                if self.algorithm != "easgd":
                    for i in range(n):
                        delta = [w - p for w, p in zip(out[i]["params"],
                                                       out[i]["pull"])]
                        if any(np.any(d) for d in delta):
                            self.parameter_server.handle_commit(
                                {"delta": delta,
                                 "clock": self.parameter_server.num_updates})
                if validator is not None:
                    vres = {k: np.asarray([float(v)]) for k, v in
                            jax.device_get(validator(
                                self.parameter_server.get_model(),
                                self._mean_state(out, n))).items()}
                    # merge into the epoch just recorded
                    self.history.epochs[-1].update(vres)
                if manager is not None and self._should_checkpoint(epoch):
                    manager.save(
                        epoch,
                        {"params": self.parameter_server.get_model(),
                         "state": self._mean_state(out, n)},
                        metadata={"epoch": epoch})
                epoch_rec = self.history.epochs[-1]
                cbs.epoch_end(epoch, self._epoch_logs(
                    epoch_rec["loss"],
                    {k: v for k, v in epoch_rec.items() if k != "loss"}, {}))
                if self.stop_training:
                    break
        finally:
            import sys
            profile.__exit__(*sys.exc_info())
            self.record_training_stop()
            cbs.train_end()  # closes callback resources on exceptions too
            self.parameter_server.stop()
            if manager is not None:
                manager.wait()  # async snapshots durable before return

        center = self.parameter_server.get_model()
        trained = model.replace(params=center, state=self._mean_state(out, n))
        trained = self._apply_pending_weights(trained)
        self.master_model = trained
        return trained
