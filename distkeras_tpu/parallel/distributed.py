"""Distributed trainer family: DOWNPOUR, EASGD, AEASGD, ADAG, DynSGD,
AveragingTrainer.

Reference parity: ``distkeras/trainers.py`` concrete classes (SURVEY §2.1).
Constructor surfaces mirror the reference (``num_workers``, ``batch_size``,
``communication_window``, ``num_epoch``, ``features_col``, ``label_col``,
algorithm hyper-parameters), but training runs on a ``jax.sharding.Mesh``
via the SPMD engine in ``parallel/engine.py`` instead of Spark executors +
a socket parameter server — see that module's docstring for the mapping.

Notable surface differences from the reference, by design:
  * no ``master_host``/``master_port`` — there is no socket PS;
  * ``parallelism_factor`` (round 3) keeps the reference's PARTITION
    semantics rather than oversubscribing devices: the epoch splits into
    ``num_workers x factor`` partitions and each worker consumes
    ``factor`` of them sequentially, re-initialized from the center at
    every partition start (fresh-Spark-task dynamics: more, smaller
    commit windows + a center re-sync per partition);
  * ``trainer.parameter_server`` is replaced by the replicated center state
    inside the engine.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.core import Model
from distkeras_tpu.parallel.engine import (
    AdagAlgo, AveragingAlgo, DistAlgorithm, DistributedEngine, DownpourAlgo,
    DynSGDAlgo, ElasticAlgo, EngineConfig, host_fetch, shard_epoch_data)
from distkeras_tpu.parallel.mesh import make_mesh
from distkeras_tpu.parallel.trainers import Trainer, val_logs
from distkeras_tpu.resilience import faults


class DistributedTrainer(Trainer):
    """Base for all mesh-distributed trainers.

    Reference: ``trainers.py :: DistributedTrainer`` (adds num_workers,
    communication_window, the PS service and worker allocation). Here
    ``allocate_algorithm()`` plays the role of the reference's
    ``allocate_worker()`` + ``allocate_parameter_server()`` pair: it fixes
    the commit protocol both sides of the (now compiled-in) exchange.
    """

    def __init__(self, keras_model: Model, num_workers: Optional[int] = None,
                 communication_window: int = 5,
                 parallelism_factor: int = 1, mesh=None, **kwargs):
        super().__init__(keras_model, **kwargs)
        self.num_workers = int(num_workers or len(jax.devices()))
        self.communication_window = communication_window
        # Reference semantics (trainers.py ctor): the epoch is
        # ``num_workers x parallelism_factor`` partitions; each worker
        # consumes ``parallelism_factor`` of them SEQUENTIALLY, starting
        # every partition as a fresh task from the current center (more,
        # smaller commit windows per epoch + a center re-sync per
        # partition). factor 1 = the persistent-worker engine default.
        self.parallelism_factor = int(parallelism_factor)
        if self.parallelism_factor < 1:
            raise ValueError(
                f"parallelism_factor must be >= 1, got {parallelism_factor}")
        self.mesh = mesh

    def allocate_algorithm(self) -> DistAlgorithm:
        raise NotImplementedError

    # window may be overridden per-train (AveragingTrainer binds it to the
    # epoch length)
    def _window(self, steps_per_epoch: int) -> Union[int, Sequence[int]]:
        return self.communication_window

    def train(self, dataset: Dataset) -> Model:
        self._reject_step_options()
        model = self.master_model
        X, y = self._training_arrays(dataset)

        mesh = self.mesh or make_mesh(self.num_workers)
        # probe epoch shape once to size the window (and fail fast on tiny
        # datasets)
        _, _, S = shard_epoch_data(X, y, self.num_workers, self.batch_size)
        engine = DistributedEngine(
            model.module, self.loss, self.worker_optimizer,
            self.allocate_algorithm(), mesh,
            EngineConfig(num_workers=self.num_workers,
                         window=self._window(S)),
            metric_fns=self._metric_fns(),
            param_mask=self._param_mask(model),
            state_mask=self._state_mask(model))

        # resume restores the CENTER; workers restart from it — the same
        # semantic as the reference's Spark task retry, which re-trains a
        # partition from the current PS center (SURVEY §5.3)
        manager = self._checkpoint_manager()
        tree, start_epoch = self._maybe_resume(
            manager, {"params": model.params, "state": model.state})
        state = engine.init_state(tree["params"], tree["state"],
                                  jax.random.PRNGKey(self.seed))
        state = jax.device_put(state, engine.shardings())

        from distkeras_tpu.utils.prefetch import Prefetcher
        assemble = lambda epoch: shard_epoch_data(
            X, y, self.num_workers, self.batch_size,
            self._epoch_perm(epoch, len(X)))
        self.record_training_start()
        extracted = None  # (params, state) pulled on the final-epoch save
        # next epoch's shuffle gather + [S, W, B, ...] stacking overlaps
        # with this epoch's device step (utils/prefetch.py)
        validator = self._make_validator(model.module)
        if validator is not None:
            # center model STATE never advances in the engine (only params
            # do); validate with the worker-averaged state, the same thing
            # extract_model ships (float leaves averaged, counters from
            # worker 0)
            @jax.jit
            def _val_state(wstate):
                return jax.tree_util.tree_map(
                    lambda s: s.mean(axis=0)
                    if jnp.issubdtype(s.dtype, jnp.floating) else s[0],
                    wstate)
        cbs = self._cb_list(lambda: engine.extract_model(state))
        try:
            with self._profile_ctx():
                for epoch, (Xs, Ys, S) in Prefetcher(
                        assemble, range(start_epoch, self.num_epoch)):
                    # chaos hook: mid-training crash; note the engine
                    # family resumes from the CENTER only (the documented
                    # PS-retry semantic), not bitwise like Single/SPMD
                    faults.point("train.epoch")
                    pf = self.parallelism_factor
                    if pf > 1:
                        # reference partition loop: each worker consumes
                        # pf sequential partitions, re-initialized from
                        # the center at every partition start (fresh
                        # Spark-task semantics)
                        if S < pf:
                            raise ValueError(
                                f"epoch has {S} steps/worker but "
                                f"parallelism_factor={pf} needs >= {pf}")
                        # equal-length partitions; the remainder steps are
                        # DROPPED (a shorter final chunk would recompile
                        # the epoch program for a second shape — minutes
                        # on a big model), matching shard_epoch_data's
                        # drop_remainder batching policy
                        chunk = S // pf
                        if chunk * pf < S:
                            import warnings
                            warnings.warn(
                                f"parallelism_factor={pf}: epoch has {S} "
                                f"steps/worker; the trailing "
                                f"{S - chunk * pf} steps are dropped every "
                                "epoch (equal-length partitions avoid a "
                                "second epoch-program compile). Size the "
                                "dataset so steps/worker divides by "
                                "parallelism_factor to train on all of "
                                "it.", stacklevel=2)
                        l_acc, m_acc = [], []
                        for j in range(pf):
                            lo, hi = j * chunk, (j + 1) * chunk
                            state = engine.reset_workers(state)
                            state, outs_j = engine.run_epoch(
                                state, Xs[lo:hi], Ys[lo:hi])
                            lj, mj = self._split_outs(outs_j)
                            l_acc.append(lj)
                            m_acc.append(mj)
                        losses = jnp.concatenate(l_acc)
                        mets = {k: jnp.concatenate([m[k] for m in m_acc])
                                for k in (m_acc[0] if m_acc else {})}
                    else:
                        state, outs = engine.run_epoch(state, Xs, Ys)
                        losses, mets = self._split_outs(outs)
                    extra = {}
                    if validator is not None:
                        # evaluate the CENTER (the model a user would ship)
                        extra = val_logs(host_fetch(validator(
                            state["center"]["params"],
                            _val_state(state["worker"]["state"]))))
                    losses, mets = host_fetch(losses), host_fetch(mets)
                    self.history.append_epoch(loss=losses, **mets, **extra)
                    # cadence check BEFORE extract_model: the full-state
                    # device->host transfer is expensive and must only
                    # happen on save epochs
                    extracted = None

                    def save_center(epoch):
                        nonlocal extracted
                        extracted = engine.extract_model(state)
                        if jax.process_index() == 0:  # one writer per ckpt
                            manager.save(epoch, {"params": extracted[0],
                                                 "state": extracted[1]},
                                         metadata={"epoch": epoch})

                    saved = False
                    if manager is not None and self._should_checkpoint(epoch):
                        save_center(epoch)
                        saved = True
                    cbs.epoch_end(epoch,
                                  self._epoch_logs(losses, mets, extra))
                    # stop_training stops ALL workers: the center is shared
                    # — there is no per-worker early stop in the engine
                    # protocol; a preemption request checkpoints the center
                    # first (same save-on-exit rule as the other trainers)
                    if self._epoch_exit(
                            epoch, saved,
                            save_center if manager is not None else None):
                        break
        finally:
            self.record_training_stop()
            cbs.train_end()  # closes callback resources on exceptions too
        if manager is not None:
            manager.wait()  # async snapshots durable before return

        # the forced last-epoch save already pulled the final state
        params, mstate = extracted if extracted is not None \
            else engine.extract_model(state)
        trained = model.replace(params=params, state=mstate)
        trained = self._apply_pending_weights(trained)
        self.master_model = trained
        return trained


class DOWNPOUR(DistributedTrainer):
    """Asynchronous DOWNPOUR SGD (Dean et al. 2012).

    Reference: ``trainers.py :: DOWNPOUR`` with ``DOWNPOURWorker`` +
    ``DeltaParameterServer`` (SURVEY §3.3): accumulate
    ``communication_window`` local steps, commit the delta, pull fresh
    center. Commits are staggered across workers to reproduce async PS
    arrival order (engine docstring).
    """

    def __init__(self, keras_model: Model, communication_window: int = 5,
                 commit_scale: float = 1.0, **kwargs):
        super().__init__(keras_model,
                         communication_window=communication_window, **kwargs)
        self.commit_scale = float(commit_scale)

    def allocate_algorithm(self):
        return DownpourAlgo(commit_scale=self.commit_scale)


class EASGD(DistributedTrainer):
    """Synchronous Elastic Averaging SGD (Zhang et al. 2015).

    Reference: ``trainers.py :: EASGD`` — barrier rounds: every worker
    exchanges an elastic difference with the center every
    ``communication_window`` steps, simultaneously. ``alpha = rho *
    learning_rate`` as in the reference worker; ``learning_rate`` here is
    the elastic/exploration rate (the worker optimizer's own learning rate
    is configured via ``worker_optimizer``/``optimizer_kwargs``).
    """

    def __init__(self, keras_model: Model, rho: float = 5.0,
                 learning_rate: float = 0.01, communication_window: int = 5,
                 center_mode: str = "sum", **kwargs):
        # learning_rate is the ELASTIC rate, not the worker optimizer's —
        # do not forward it to the base (which would configure the optimizer)
        super().__init__(keras_model,
                         communication_window=communication_window, **kwargs)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)
        self.center_mode = center_mode

    @property
    def alpha(self) -> float:
        return self.rho * self.learning_rate

    def allocate_algorithm(self):
        if (self.center_mode == "sum"
                and self.alpha * self.num_workers >= 1.0):
            import warnings
            warnings.warn(
                f"EASGD stability: num_workers * alpha = "
                f"{self.alpha * self.num_workers:.2f} >= 1 with "
                f"center_mode='sum'; the center update can oscillate. "
                f"Lower rho/learning_rate or use center_mode='mean'.",
                stacklevel=2)
        return ElasticAlgo(alpha=self.alpha, synchronous=True,
                           center_mode=self.center_mode)


class AEASGD(EASGD):
    """Asynchronous EASGD — the reference's flagship algorithm (SURVEY §3.2).

    Reference: ``trainers.py :: AEASGD`` with ``AEASGDWorker``: each worker
    elastic-exchanges with the center at its own cadence. Emulated by
    staggered commit offsets; each commit is a masked psum touching only
    that worker's elastic difference.
    """

    def __init__(self, keras_model: Model, rho: float = 5.0,
                 learning_rate: float = 0.01, communication_window: int = 32,
                 center_mode: str = "sum", **kwargs):
        super().__init__(keras_model, rho=rho, learning_rate=learning_rate,
                         communication_window=communication_window,
                         center_mode=center_mode, **kwargs)

    def allocate_algorithm(self):
        return ElasticAlgo(alpha=self.alpha, synchronous=False,
                           center_mode=self.center_mode)


class ADAG(DistributedTrainer):
    """ADAG — asynchronous commits with adaptive per-parameter server
    accumulation (reference: ``trainers.py :: ADAG`` +
    ``ADAGParameterServer``)."""

    def __init__(self, keras_model: Model, communication_window: int = 5,
                 adag_learning_rate: float = 0.05, epsilon: float = 1e-8,
                 **kwargs):
        super().__init__(keras_model,
                         communication_window=communication_window, **kwargs)
        self.adag_learning_rate = float(adag_learning_rate)
        self.epsilon = float(epsilon)

    def allocate_algorithm(self):
        return AdagAlgo(adag_lr=self.adag_learning_rate,
                        epsilon=self.epsilon)


class DynSGD(DistributedTrainer):
    """DynSGD — staleness-scaled asynchronous SGD (reference:
    ``trainers.py :: DynSGD`` + ``DynSGDParameterServer``; SURVEY §3.3:
    commit tagged with last-pull ``num_updates``, server scales delta by
    1/staleness).

    ``communication_window`` may be per-worker (a list of K_i) to model
    heterogeneous worker speeds — the scenario DynSGD exists for.
    """

    def __init__(self, keras_model: Model,
                 communication_window: Union[int, Sequence[int]] = 5,
                 **kwargs):
        super().__init__(keras_model,
                         communication_window=communication_window, **kwargs)

    def allocate_algorithm(self):
        return DynSGDAlgo()


class AveragingTrainer(DistributedTrainer):
    """Per-epoch weight averaging over independently training workers.

    Reference: ``trainers.py :: AveragingTrainer`` (SURVEY §2.1). The commit
    window is bound to the epoch length, so workers train a full epoch shard
    independently and then synchronously average — exactly the reference's
    per-epoch semantics, as one compiled program.
    """

    def __init__(self, keras_model: Model, **kwargs):
        kwargs.setdefault("communication_window", 0)  # bound at train time
        super().__init__(keras_model, **kwargs)

    def _window(self, steps_per_epoch: int):
        return steps_per_epoch

    def allocate_algorithm(self):
        return AveragingAlgo()
