"""Parameter servers: central-state pull/commit services.

Reference parity: ``distkeras/parameter_servers.py`` (SURVEY §2.1) —
``ParameterServer`` (center model, ``num_updates``, mutex),
``DeltaParameterServer``, ``ADAGParameterServer``, ``DynSGDParameterServer``,
and the EASGD server, fronted by the pickled-TCP protocol in
``networking.py``.

Role in the TPU framework: the DEFAULT distributed path has **no parameter
server at all** — the SPMD engine (``parallel/engine.py``) compiles the
center into the training program and replaces pull/commit with masked ICI
collectives. This module exists for the two cases a host-side center is
still the right tool:

  * **true-async training** across worker threads/processes whose step
    cadence genuinely differs (``parallel/async_host.py``) — the reference's
    actual concurrency model, where staleness arises from wall-clock races
    rather than the engine's deterministic staggering;
  * **DCN-scale fallback / job control**: coordination between hosts that
    do not share an ICI domain, where a framed-TCP round-trip per window is
    the honest transport.

Update rules are host-side numpy on flat leaf lists (cheap O(params) adds;
the heavy math stays on device in the workers). The wire protocol is the
reference's dict shape — ``{'action': 'pull'}`` / ``{'action': 'commit',
'delta': ...}`` — carried over framed messages.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from distkeras_tpu.parallel import networking

Pytree = Any


def _to_leaves(tree: Pytree) -> Tuple[List[np.ndarray], Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    # np.array(copy=True): views of jax arrays are read-only; the center
    # must be writable for in-place commits
    return [np.array(l, copy=True) for l in leaves], treedef


class ParameterServer:
    """Center state + update counter + mutex (reference:
    ``parameter_servers.py :: ParameterServer``).

    Subclasses implement ``handle_commit(payload)``; ``handle_pull`` is
    shared. The center is stored as a flat list of numpy leaves plus the
    treedef, so commits are plain array loops with no pytree traversal.
    """

    def __init__(self, center: Pytree):
        self._leaves, self._treedef = _to_leaves(center)
        self._lock = threading.Lock()
        self.num_updates = 0
        self._server: Optional[networking.MessageServer] = None

    # -- lifecycle (reference: initialize/start/stop/get_model) ------------
    def initialize(self) -> None:  # parity no-op; state built in __init__
        pass

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Expose this PS over TCP; returns the bound port. Without a call
        to ``start`` the PS is in-process only (pull/commit direct calls).

        Binds localhost by default — the wire format includes pickle, so
        pass a routable ``host`` only on a trusted-cluster network (see
        ``networking.MessageServer``)."""
        self._server = networking.MessageServer(self._dispatch, host, port)
        self._server.start()
        return self._server.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None

    def get_model(self) -> Pytree:
        with self._lock:
            leaves = [l.copy() for l in self._leaves]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # -- protocol ----------------------------------------------------------
    def handle_pull(self) -> Tuple[List[np.ndarray], int]:
        with self._lock:
            return [l.copy() for l in self._leaves], self.num_updates

    def handle_commit(self, payload: Dict[str, Any]) -> None:
        raise NotImplementedError

    def _dispatch(self, msg: Dict[str, Any]):
        action = msg.get("action")
        if action == "pull":
            leaves, clock = self.handle_pull()
            return {"center": leaves, "clock": clock}
        if action == "commit":
            self.handle_commit(msg)
            return {"ok": True}
        if action == "clock":
            with self._lock:
                return {"clock": self.num_updates}
        return {"error": f"unknown action {action!r}"}


class DeltaParameterServer(ParameterServer):
    """``center += delta`` (reference: ``parameter_servers.py ::
    DeltaParameterServer.handle_commit``) — DOWNPOUR / EASGD commits."""

    def handle_commit(self, payload):
        delta = payload["delta"]
        with self._lock:
            for c, d in zip(self._leaves, delta):
                c += d
            self.num_updates += 1


class ADAGParameterServer(ParameterServer):
    """Adaptive per-parameter accumulation (reference:
    ``parameter_servers.py :: ADAGParameterServer``): commits are scaled by
    an adagrad-style accumulator of committed deltas — the same rule as the
    SPMD engine's ``AdagAlgo`` so both paths converge identically."""

    def __init__(self, center: Pytree, learning_rate: float = 0.05,
                 epsilon: float = 1e-8):
        super().__init__(center)
        self.learning_rate = float(learning_rate)
        self.epsilon = float(epsilon)
        self._acc = [np.zeros_like(l) for l in self._leaves]

    def handle_commit(self, payload):
        delta = payload["delta"]
        with self._lock:
            for c, a, d in zip(self._leaves, self._acc, delta):
                a += np.square(d)
                c += self.learning_rate * d / (np.sqrt(a) + self.epsilon)
            self.num_updates += 1


class DynSGDParameterServer(ParameterServer):
    """Staleness-scaled commits (reference: ``parameter_servers.py ::
    DynSGDParameterServer``; SURVEY §3.3): each commit carries the worker's
    last-pull clock; the delta is scaled by 1/staleness."""

    def handle_commit(self, payload):
        delta, last_pull = payload["delta"], payload["clock"]
        with self._lock:
            staleness = max(1, self.num_updates - int(last_pull) + 1)
            inv = 1.0 / staleness
            for c, d in zip(self._leaves, delta):
                c += d * inv
            self.num_updates += 1


class EASGDParameterServer(DeltaParameterServer):
    """EASGD center: accumulates elastic differences committed by workers.
    The commit payload IS the elastic term ``alpha * (x_i - center)``
    (computed worker-side against its last view of the center), so the
    server rule is the plain add of ``DeltaParameterServer`` — kept as its
    own class for reference parity and synchronous-round bookkeeping."""


class PSClient:
    """Worker-side handle: pull/commit against an in-process PS object or a
    remote socket PS (reference: the socket code inside ``workers.py ::
    NetworkWorker``). Payloads are flat numpy leaf lists."""

    def __init__(self, ps: Optional[ParameterServer] = None,
                 host: Optional[str] = None, port: Optional[int] = None):
        if (ps is None) == (host is None):
            raise ValueError("pass exactly one of ps= or host=/port=")
        self._ps = ps
        self._sock = networking.connect(host, port) if host else None
        self._lock = threading.Lock()  # one request in flight per client

    @staticmethod
    def _checked(reply):
        if isinstance(reply, dict) and "error" in reply:
            raise RuntimeError(f"parameter server error: {reply['error']}")
        return reply

    def pull(self) -> Tuple[List[np.ndarray], int]:
        if self._ps is not None:
            return self._ps.handle_pull()
        with self._lock:
            reply = self._checked(
                networking.request(self._sock, {"action": "pull"}))
        return reply["center"], reply["clock"]

    def commit(self, delta: Sequence[np.ndarray],
               clock: Optional[int] = None) -> None:
        msg: Dict[str, Any] = {"action": "commit", "delta": list(delta)}
        if clock is not None:
            msg["clock"] = int(clock)
        if self._ps is not None:
            self._ps.handle_commit(msg)
            return
        with self._lock:
            self._checked(networking.request(self._sock, msg))

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None
