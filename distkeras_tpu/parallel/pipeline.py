"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

No reference equivalent (SURVEY §2.3: PP is "absent in the reference" — a
dist-keras worker always holds the whole model). This is the TPU-native
capability ADD for models deeper than one chip: the repeated trunk of a
network (N identical transformer blocks) is stacked into one
``[num_layers, ...]`` params pytree and sharded over the ``pp`` axis, so
each device owns ``num_layers / pp`` consecutive layers. Microbatches flow
through the stages on a ``ppermute`` ring under ``shard_map``:

  tick t:  device 0 injects microbatch t; device i processes the activation
           it received at tick t-1 through its local layers (a ``lax.scan``
           over the stacked params); every device then permutes its output
           to device i+1. After ``M + P - 1`` ticks all M microbatches have
           drained; the last stage's outputs are psum-broadcast to the ring.

Everything — schedule, stage compute, collectives — is ONE jitted program;
the schedule is a ``lax.scan`` over ticks, so there is no per-tick Python.
The whole pipeline is differentiable (``ppermute``'s transpose is the
reverse permute), so the same function serves forward and backward; XLA
overlaps the permute with stage compute where possible.

Composes with the other axes: batch sharded over ``workers`` (dp), sequence
sharded over ``sp`` with ring attention inside the blocks, giving dp×pp×sp
in one program (see ``PipelinedLM.make_train_step`` and
``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.compat import axis_size, shard_map
from distkeras_tpu.models.core import Layer
from distkeras_tpu.ops.optimizers import Optimizer, apply_updates

Pytree = Any


def init_stacked_blocks(block: Layer, rng: jax.Array,
                        input_shape: Tuple[int, ...], num_layers: int):
    """Init ``num_layers`` copies of ``block`` and stack the params along a
    leading layer axis. Blocks must be shape-preserving and stateless (no
    BatchNorm-style running stats) — the pipeline scan carries activations
    only."""
    ps, state = [], {}
    for k in jax.random.split(rng, num_layers):
        p, s, out_shape = block.init(k, tuple(input_shape))
        if tuple(out_shape) != tuple(input_shape):
            raise ValueError(
                f"pipeline blocks must preserve shape: {input_shape} -> "
                f"{out_shape}")
        if jax.tree_util.tree_leaves(s):
            raise ValueError(
                "pipeline blocks must be stateless (found non-empty state; "
                "BatchNorm-style layers are unsupported in the pipelined "
                "trunk — use LayerNorm/RMSNorm)")
        ps.append(p)
        state = s  # leafless structure template, passed back into apply
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ps), state


def make_pipeline_fn(block: Layer, axis_name: str = "pp",
                     state: Optional[Pytree] = None,
                     remat: bool = False,
                     virtual_stages: int = 1) -> Callable:
    """Returns ``fn(stacked_local_params, x_mb) -> y_mb`` for use under
    ``shard_map``: ``x_mb`` is ``[M, mb, ...]`` microbatched input
    (replicated over the pp axis), result likewise. ``state`` is the block's
    (leafless) state-structure template from ``init_stacked_blocks``.
    ``remat=True`` recomputes each layer's activations in the backward pass
    (peak memory O(1) per stage instead of O(layers/stage)).

    ``virtual_stages`` = v (round 4): the INTERLEAVED schedule. Each
    device's layers split into v chunks; global chunk j lives on device
    ``j % P``, so consecutive chunks are ring neighbors and the SAME
    ppermute ring carries the flow. Chunk j of microbatch m (grouped
    g = m//P, r = m%P; q = j//P) runs at tick

        T(m, j) = g*v*P + q*P + r + (j % P)

    — each activation is produced exactly one tick before its consumer
    needs it (T(m, j+1) - T(m, j) = 1 for both same-device wrap and
    cross-device hops), so no waiting buffers exist anywhere. Ticks
    total ``M*v + P - 1`` with each tick 1/v of a GPipe stage, giving
    bubble ``(P-1)/(M*v + P - 1)`` vs GPipe's ``(P-1)/(M + P - 1)``.
    v=1 IS the GPipe schedule (the formulas degenerate: q=0, m=t-d) —
    one code path serves both. Requires ``M % P == 0`` for v > 1
    (microbatches inject in groups of P; validated in make_train_step).

    **Params layout contract for v > 1** (advisor r4): GSPMD tiles the
    stacked layer axis CONTIGUOUSLY over the pp axis, so the stacked
    params this function receives must already be permuted into
    device-major/chunk-minor order — device d's slice holds its v chunks
    back to back, NOT the canonical layer order. Build the permutation
    with :func:`interleaved_params_perm` (``PipelinedLM.make_train_step``
    applies it at the jit boundary); passing canonically ordered stacked
    params with v > 1 silently assigns the wrong layers to each chunk.
    """
    state = {} if state is None else state
    v = int(virtual_stages)
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")

    def layer_apply(p, h):
        return block.apply(p, state, h, training=False)[0]

    if remat:
        layer_apply = jax.checkpoint(layer_apply)

    def stage(chunk_params, h):
        def body(h, p):
            return layer_apply(p, h), None
        h, _ = lax.scan(body, h, chunk_params)
        return h

    def fn(local_params, x_mb):
        nstages = axis_size(axis_name)
        idx = lax.axis_index(axis_name)
        M = x_mb.shape[0]
        ticks = M * v + nstages - 1
        ring = [(j, (j + 1) % nstages) for j in range(nstages)]
        if v > 1 and M % nstages:
            raise ValueError(
                f"interleaved schedule (virtual_stages={v}) injects "
                f"microbatches in groups of P: M={M} must divide by the "
                f"pp axis size {nstages} (trailing microbatches would "
                "silently drain as zeros)")
        layers_local = jax.tree_util.tree_leaves(local_params)[0].shape[0]
        if layers_local % v:
            raise ValueError(
                f"per-device layer count {layers_local} must divide by "
                f"virtual_stages={v} (trailing layers would be silently "
                "skipped)")
        lpc = layers_local // v                       # layers per chunk

        def chunk_of(p, q):
            return jax.tree_util.tree_map(
                lambda leaf: lax.dynamic_slice_in_dim(leaf, q * lpc, lpc,
                                                      axis=0), p)

        def tick(carry, t):
            buf, outs = carry
            s = t - idx
            # mixed-radix decode of s = (g*v + q)*P + r  (garbage for the
            # bubble slots s < 0 / m >= M; masked below, and the clamps
            # keep every index in range)
            r = jnp.where(s >= 0, s % nstages, 0)
            gq = jnp.where(s >= 0, s // nstages, 0)
            q = gq % v
            m = (gq // v) * nstages + r
            inject = (idx == 0) & (q == 0)
            inp = jnp.where(inject, x_mb[jnp.clip(m, 0, M - 1)], buf)
            h = stage(chunk_of(local_params, q), inp)
            valid = ((s >= 0) & (m < M) & (q == v - 1)
                     & (idx == nstages - 1))
            cidx = jnp.clip(m, 0, M - 1)
            outs = outs.at[cidx].set(jnp.where(valid, h, outs[cidx]))
            buf = lax.ppermute(h, axis_name, ring)
            return (buf, outs), None

        buf = jnp.zeros_like(x_mb[0])
        outs = jnp.zeros_like(x_mb)
        (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(ticks))
        # broadcast the drained outputs from the last stage to the ring
        outs = lax.psum(jnp.where(idx == nstages - 1, outs, 0.), axis_name)
        return outs

    return fn


def interleaved_params_perm(num_layers: int, pp: int,
                            virtual_stages: int) -> "np.ndarray":
    """Index permutation taking CANONICALLY stacked layer params (layer 0
    first) into the device-major/chunk-minor order
    :func:`make_pipeline_fn` requires when ``virtual_stages > 1``:
    position ``(d, q, l)`` of the permuted stack holds canonical layer
    ``(q*pp + d)*lpc + l`` (global chunk ``j = q*pp + d`` lives on device
    ``j % pp``; ``lpc = num_layers // (pp*virtual_stages)``). Apply with
    ``jnp.take(leaf, perm, axis=0)``; invert with ``np.argsort(perm)``
    for the gradient scatter. Exposed (advisor r4) so direct shard_map
    callers of ``make_pipeline_fn`` can honor the layout contract —
    ``PipelinedLM.make_train_step`` applies it at the jit boundary."""
    v = int(virtual_stages)
    if num_layers % (pp * v):
        raise ValueError(
            f"num_layers {num_layers} must divide evenly over pp={pp} x "
            f"virtual_stages={v}")
    lpc = num_layers // (pp * v)
    return np.array([(q * pp + d) * lpc + l
                     for d in range(pp)
                     for q in range(v)
                     for l in range(lpc)])


class PipelinedLM:
    """Embed -> pp-sharded block stack -> head, with a dp×pp(×sp) train step.

    ``embed``/``head`` are replicated (their grads psum over the pp axis —
    contributions are zero except on the inject/drain stages); the trunk is
    ``num_layers`` copies of ``block`` sharded over ``pp``.

    ``num_microbatches`` default changed 2 → 4 in round 3 (GPipe bubble
    at P=2: 33% → 20%; see ``bubble_fraction``). Per-worker batches must
    divide by it — callers relying on the old default with per-worker
    batch 2 should pass ``num_microbatches=2`` explicitly.
    """

    def __init__(self, embed: Layer, block: Layer, head: Layer,
                 num_layers: int, num_microbatches: int = 4,
                 remat: bool = False, virtual_stages: int = 1):
        self.embed = embed
        self.block = block
        self.head = head
        self.num_layers = int(num_layers)
        self.num_microbatches = int(num_microbatches)
        self.remat = bool(remat)
        self.virtual_stages = int(virtual_stages)
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {virtual_stages}")
        self._estate = self._bstate = self._hstate = {}  # set by init()

    def bubble_fraction(self, pp: int) -> float:
        """Idle fraction of the schedule: (P-1)/(M*v + P-1) — with v
        virtual stages per device each tick is 1/v of a full stage, so
        the (P-1)-tick fill/drain shrinks accordingly (round 4; at v=1
        this is GPipe's (P-1)/(M+P-1)). The same fraction applies to the
        forward and backward sweeps (autodiff replays the tick scan in
        reverse). A 1F1B reordering at v=1 would NOT shrink the bubble
        (it equals GPipe's at equal M) — 1F1B's real advantage is O(P)
        activation memory, which ``remat=True`` already provides at O(1)
        per stage; interleaving attacks the bubble itself at the price
        of one params-permutation gather per step and P | M. See
        docs/parallelism.md."""
        m = self.num_microbatches
        return (pp - 1) / (m * self.virtual_stages + pp - 1)

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array, input_shape: Tuple[int, ...]):
        k1, k2, k3 = jax.random.split(rng, 3)
        pe, se, shape = self.embed.init(k1, tuple(input_shape))
        if jax.tree_util.tree_leaves(se):
            raise ValueError("embed must be stateless")
        blocks, bstate = init_stacked_blocks(self.block, k2, shape,
                                             self.num_layers)
        ph, sh, out_shape = self.head.init(k3, shape)
        if jax.tree_util.tree_leaves(sh):
            raise ValueError("head must be stateless")
        # leafless state-structure templates for the pure applies
        self._estate, self._bstate, self._hstate = se, bstate, sh
        return {"embed": pe, "blocks": blocks, "head": ph}, out_shape

    # -- unsharded reference forward (host inference / tests) ---------------
    def apply(self, params, x):
        h, _ = self.embed.apply(params["embed"], self._estate, x,
                                training=False)

        def body(h, p):
            y, _ = self.block.apply(p, self._bstate, h, training=False)
            return y, None

        h, _ = lax.scan(body, h, params["blocks"])
        y, _ = self.head.apply(params["head"], self._hstate, h,
                               training=False)
        return y

    # -- sharded step -------------------------------------------------------
    def make_train_step(self, loss_fn: Callable, optimizer: Optimizer,
                        mesh: Mesh, data_axes: Sequence[str] = ("workers",),
                        pp_axis: str = "pp",
                        seq_axis: Optional[str] = None,
                        metric_fns: Optional[dict] = None) -> Callable:
        """Build ``step((params, opt_state), (x, y)) -> ((params, opt),
        loss)`` — or ``((params, opt), (loss, metrics_dict))`` when
        ``metric_fns`` is non-empty.

        ``data_axes``: mesh axes the batch dim is sharded over (dp).
        ``seq_axis``: mesh axis the sequence dim is sharded over (sp, ring
        attention inside the blocks); None for no sequence parallelism.
        ``metric_fns``: {name: fn(y, logits)} evaluated on the training
        batch (same psum accounting as the loss).
        """
        M = self.num_microbatches
        v = self.virtual_stages
        pp = mesh.shape[pp_axis]
        if self.num_layers % (pp * v):
            raise ValueError(
                f"num_layers {self.num_layers} must divide evenly over "
                f"pp axis {pp_axis!r} (size {pp}) x virtual_stages {v}")
        if v > 1 and M % pp:
            raise ValueError(
                f"the interleaved schedule injects microbatches in groups "
                f"of P: num_microbatches {M} must divide by the pp axis "
                f"size {pp} when virtual_stages > 1")
        pipeline = make_pipeline_fn(self.block, pp_axis, self._bstate,
                                    remat=self.remat, virtual_stages=v)
        # interleaved layer->device map: global chunk j (layers
        # [j*lpc, (j+1)*lpc)) lives on device j % P, but GSPMD tiles the
        # stacked axis CONTIGUOUSLY — so the step permutes the canonical
        # layer order into device-major/chunk-minor order at the jit
        # boundary (params and optimizer state stay canonical; the
        # gather + its scatter transpose cost one params-shuffle per
        # step, noise next to a pipelined batch)
        if v > 1:
            perm = interleaved_params_perm(self.num_layers, pp, v)
            inv_perm = np.argsort(perm)
        else:
            perm = inv_perm = None
        embed, head = self.embed, self.head
        estate, hstate = self._estate, self._hstate
        d_axes = tuple(data_axes)
        loss_div_axes = d_axes + ((seq_axis,) if seq_axis else ())
        div = int(np.prod([mesh.shape[a] for a in loss_div_axes])) or 1
        metric_fns = metric_fns or {}

        def local_grads(params, x, y):
            def obj(params):
                h, _ = embed.apply(params["embed"], estate, x,
                                   training=False)
                mb = h.reshape((M, h.shape[0] // M) + h.shape[1:])
                out = pipeline(params["blocks"], mb)
                out = out.reshape(h.shape[:-1] + out.shape[-1:])
                logits, _ = head.apply(params["head"], hstate, out,
                                       training=False)
                # The pipeline broadcast the outputs to every pp rank, so
                # every rank computes the same loss; count it ONCE (last
                # stage) or replicated-param grads would be pp-times too
                # large after the psum. Cross-rank grad flow (last rank's
                # loss -> ring -> stage params -> first rank's embed) is
                # handled by the collective transposes inside jax.grad.
                is_last = (lax.axis_index(pp_axis)
                           == axis_size(pp_axis) - 1)
                # scaled so that psum over data+pp axes == global mean loss
                return loss_fn(y, logits) * is_last / div, (logits, is_last)

            (loss, (logits, is_last)), grads = \
                jax.value_and_grad(obj, has_aux=True)(params)
            all_axes = loss_div_axes + (pp_axis,)
            grads = {
                # replicated components: nonzero on one rank; sum everywhere
                "embed": lax.psum(grads["embed"], all_axes),
                "head": lax.psum(grads["head"], all_axes),
                # pp-sharded trunk: each rank already holds the full grad of
                # its own stage; reduce over data axes only
                "blocks": lax.psum(grads["blocks"], loss_div_axes),
            }
            mets = {name: lax.psum(fn(y, logits) * is_last / div, all_axes)
                    for name, fn in metric_fns.items()}
            return grads, lax.psum(loss, all_axes), mets

        # x/y: [B, S] -> batch over dp axes, sequence over sp
        seq_entry = (seq_axis,) if seq_axis else (None,)
        data_spec = P(d_axes, *seq_entry)
        pspecs = {"embed": P(), "blocks": P(pp_axis), "head": P()}
        grads_fn = shard_map(
            local_grads, mesh=mesh,
            in_specs=(pspecs, data_spec, data_spec),
            out_specs=(pspecs, P(), {n: P() for n in metric_fns}),
            check_vma=False)

        def step(carry, batch):
            params, opt_state = carry
            x, y = batch
            if perm is not None:
                px = dict(params, blocks=jax.tree_util.tree_map(
                    lambda l: jnp.take(l, perm, axis=0), params["blocks"]))
            else:
                px = params
            grads, loss, mets = grads_fn(px, x, y)
            if perm is not None:
                grads = dict(grads, blocks=jax.tree_util.tree_map(
                    lambda g: jnp.take(g, inv_perm, axis=0),
                    grads["blocks"]))
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = apply_updates(params, updates)
            return (params, opt_state), (loss, mets) if metric_fns else loss

        return jax.jit(step, donate_argnums=(0,))

    def shard_variables(self, params: Pytree, mesh: Mesh,
                        pp_axis: str = "pp") -> Pytree:
        """device_put the params tree: trunk layer-sharded over pp, embed and
        head replicated."""
        repl = NamedSharding(mesh, P())
        blk = NamedSharding(mesh, P(pp_axis))
        put = jax.tree_util.tree_map
        return {"embed": put(lambda x: jax.device_put(x, repl),
                             params["embed"]),
                "blocks": put(lambda x: jax.device_put(x, blk),
                              params["blocks"]),
                "head": put(lambda x: jax.device_put(x, repl),
                            params["head"])}


class PipelineTrainer:
    """Trainer-style wrapper: epoch loop + history over a ``PipelinedLM``.

    Mirrors the ``Trainer.train(dataset)`` ergonomics of the rest of the
    family (reference: ``distkeras/trainers.py`` constructor-kwargs style)
    for the language-model shape: ``features_col`` holds token ids
    ``[N, S]``, ``label_col`` the per-token targets ``[N, S]``.

    Family-parity services (round 3; previously a feature island): the
    epoch is ONE jitted ``lax.scan`` over stacked batches (no per-step
    Python dispatch), training ``metrics``, held-out ``validation_data``
    scalars per epoch, Keras-style ``callbacks`` (EarlyStopping &co.), and
    full-carry checkpoint/resume (params + optimizer state), all matching
    ``Trainer``'s semantics. ``snapshot_model`` is the one deliberate
    exception: a pipelined trunk is not a ``Model`` (stacked-layer params
    over a mesh), so ``ModelCheckpoint`` does not apply — use
    ``checkpoint_dir``.
    """

    def __init__(self, lm: PipelinedLM, mesh: Mesh,
                 data_axes: Sequence[str] = ("workers",),
                 pp_axis: str = "pp", seq_axis: Optional[str] = None,
                 worker_optimizer="sgd", optimizer_kwargs=None,
                 loss="sparse_categorical_crossentropy_from_logits",
                 batch_size: int = 32, num_epoch: int = 1,
                 features_col: str = "features", label_col: str = "label",
                 seed: int = 0, shuffle_each_epoch: bool = True,
                 clip_grad_norm: Optional[float] = None,
                 class_weight: Optional[dict] = None,
                 metrics: Optional[Sequence] = None,
                 validation_data=None,
                 callbacks: Optional[Sequence] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, resume: bool = False,
                 checkpoint_async: bool = False,
                 telemetry=None):
        from distkeras_tpu.ops.losses import get_loss, with_class_weight
        from distkeras_tpu.ops.optimizers import (clip_by_global_norm,
                                                  get_optimizer)
        from distkeras_tpu.utils.history import History

        self.lm = lm
        self.mesh = mesh
        self.data_axes = tuple(data_axes)
        self.pp_axis = pp_axis
        self.seq_axis = seq_axis
        self.optimizer = get_optimizer(worker_optimizer,
                                       **(optimizer_kwargs or {}))
        if clip_grad_norm is not None:
            self.optimizer = clip_by_global_norm(self.optimizer,
                                                 clip_grad_norm)
        self.eval_loss = get_loss(loss)
        self.loss = (with_class_weight(loss, class_weight)
                     if class_weight is not None else self.eval_loss)
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.features_col = features_col
        self.label_col = label_col
        self.seed = int(seed)
        self.shuffle_each_epoch = bool(shuffle_each_epoch)
        self.metrics = list(metrics or [])
        self.validation_data = validation_data
        self.callbacks = list(callbacks or [])
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        if self.checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        self.resume = bool(resume)
        self.checkpoint_async = bool(checkpoint_async)
        # same telemetry contract as Trainer: None = auto-tape, False =
        # off, or a configured obs.TrainingTape (tokens are this
        # trainer's example unit: one example row = one [S] sequence)
        self.telemetry = telemetry
        self.tape = None
        self.stop_training = False
        self.history = History()
        self.params_ = None
        self._fwd = None  # cached jitted forward for predict()
        self._weights_fn = None
        self._pending_weights = None
        # preemption contract shared with the Trainer family (the
        # supervisor drives it duck-typed; trainers.epoch_exit is the
        # ONE copy of the stop/consume/save-on-exit rule): a standing
        # request_preempt() asks the loop to checkpoint the current
        # epoch and return cleanly
        self._preempt = threading.Event()
        self.preempted = False

    def request_preempt(self) -> None:
        """See ``Trainer.request_preempt`` — same contract (the notice
        stands until an epoch loop consumes it)."""
        self._preempt.set()

    def get_history(self):
        return self.history

    # -- callback API (Trainer-compatible surface) -------------------------
    def get_weights(self):
        """Host-side ``(params, state)`` of the in-progress weights
        (callback API; the pipeline has no layer state, so state is {})."""
        if self._weights_fn is None:
            raise RuntimeError(
                "get_weights() is only available to callbacks while "
                "train() is running")
        return self._weights_fn()

    def set_weights(self, params, state=None) -> None:
        self._pending_weights = (params, state or {})

    def snapshot_model(self):
        raise RuntimeError(
            "PipelineTrainer has no single-device Model to snapshot "
            "(pp-sharded stacked trunk); use checkpoint_dir for "
            "durable snapshots")

    def _metric_fns(self):
        if not self.metrics:
            return None
        from distkeras_tpu.ops.metrics import get_metric, metric_name
        return {metric_name(m): get_metric(m) for m in self.metrics}

    def _make_validator(self):
        """Jitted full-set eval: ``validator(params) -> {"val_loss": ...,
        "val_<metric>": ...}``. Runs under ``shard_map`` over the
        training mesh — batch over the data axes, sequence over
        ``seq_axis`` — because sequence-parallel blocks (ring/ulysses)
        contain collectives that need their axis bound; the pp-sharded
        trunk is viewed replicated for the reference forward (an
        all-gather per validation pass, not per step)."""
        if self.validation_data is None:
            return None
        vd = self.validation_data
        if isinstance(vd, tuple):
            Xv, yv = vd
        else:
            Xv = np.asarray(vd[self.features_col])
            yv = np.asarray(vd[self.label_col])
        # device-cached across epochs AND train() calls (supervisor
        # restarts), keyed on dataset identity — trainers.py holds the
        # one copy of the invalidation rule
        from distkeras_tpu.parallel.trainers import cache_validation_on_device
        Xv, yv = cache_validation_on_device(self, np.asarray(Xv),
                                            np.asarray(yv))
        loss_fn = self.eval_loss
        metric_fns = self._metric_fns() or {}
        lm = self.lm

        if self.seq_axis is None:
            # no collectives in the blocks: plain unsharded eval (any
            # validation-set size; the pre-round-3 behavior)
            @jax.jit
            def evalf_plain(params, Xv, yv):
                logits = lm.apply(params, Xv)
                res = {"val_loss": loss_fn(yv, logits)}
                for name, fn in metric_fns.items():
                    res[f"val_{name}"] = fn(yv, logits)
                return res

            return lambda params: evalf_plain(params, Xv, yv)

        # sequence-parallel blocks (ring/ulysses) contain collectives that
        # need their axis bound — run under shard_map over the mesh
        dp = int(np.prod([self.mesh.shape[a] for a in self.data_axes])) or 1
        if len(Xv) % dp:
            raise ValueError(
                f"validation set size {len(Xv)} must divide over data "
                f"axes {self.data_axes} (size {dp}) for the "
                f"sequence-parallel validator")
        mean_axes = self.data_axes + (self.seq_axis,)

        def evalf(params, Xv, yv):
            logits = lm.apply(params, Xv)
            res = {"val_loss": lax.pmean(loss_fn(yv, logits), mean_axes)}
            for name, fn in metric_fns.items():
                res[f"val_{name}"] = lax.pmean(fn(yv, logits), mean_axes)
            return res

        data_spec = P(self.data_axes, self.seq_axis)
        pspecs = {"embed": P(), "blocks": P(), "head": P()}
        sharded = jax.jit(shard_map(
            evalf, mesh=self.mesh,
            in_specs=(pspecs, data_spec, data_spec),
            out_specs={"val_loss": P(),
                       **{f"val_{n}": P() for n in metric_fns}},
            check_vma=False))
        return lambda params: sharded(params, Xv, yv)

    def _validate(self, X, Y):
        """Fail fast with microbatch/sharding-aware messages instead of a
        reshape error from deep inside shard_map tracing."""
        dp = int(np.prod([self.mesh.shape[a] for a in self.data_axes])) or 1
        if self.batch_size % dp:
            raise ValueError(
                f"batch_size {self.batch_size} must divide evenly over "
                f"data axes {self.data_axes} (size {dp})")
        local_b = self.batch_size // dp
        if local_b % self.lm.num_microbatches:
            hint = ""
            if self.lm.num_microbatches == 4 and local_b % 2 == 0:
                # targeted migration error: the default changed 2 -> 4 in
                # round 3 (ADVICE r3) — callers sized for the old default
                # get told exactly what to pass instead of a bare reshape
                hint = (" (note: PipelinedLM's num_microbatches DEFAULT "
                        "changed 2 -> 4; pass num_microbatches=2 to keep "
                        "the old behavior)")
            raise ValueError(
                f"per-worker batch {local_b} (batch_size {self.batch_size} "
                f"/ dp {dp}) must divide into num_microbatches="
                f"{self.lm.num_microbatches}{hint}")
        if self.seq_axis:
            sp = self.mesh.shape[self.seq_axis]
            if X.shape[1] % sp:
                raise ValueError(
                    f"sequence length {X.shape[1]} must divide over "
                    f"seq axis {self.seq_axis!r} (size {sp})")
        if len(X) < self.batch_size:
            raise ValueError(f"dataset ({len(X)}) smaller than one batch")

    def train(self, dataset) -> Pytree:
        from distkeras_tpu.data.sharded import ShardedDataset
        from distkeras_tpu.utils.callbacks import CallbackList
        if isinstance(dataset, ShardedDataset):
            raise ValueError(
                "PipelineTrainer does not support ShardedDataset "
                "(out-of-core training is a SingleTrainer/SPMDTrainer "
                "capability); load shards into one Dataset, or switch "
                "trainer")
        X = np.asarray(dataset[self.features_col])
        Y = np.asarray(dataset[self.label_col])
        lm = self.lm
        self._validate(X, Y)

        params, _ = lm.init(jax.random.PRNGKey(self.seed), X.shape[1:])
        manager = None
        start_epoch = 0
        if self.checkpoint_dir is not None:
            from distkeras_tpu.utils.checkpoint import CheckpointManager
            manager = CheckpointManager(self.checkpoint_dir,
                                        async_writes=self.checkpoint_async)
        opt_state = None
        resumed = False
        if manager is not None and self.resume:
            latest = manager.latest_step()
            if latest is not None:
                # restore template from eval_shape (host zeros) — a real
                # optimizer.init here would materialize full unsharded
                # moments on one device, the very allocation pipeline
                # parallelism exists to avoid
                opt_template = jax.tree_util.tree_map(
                    lambda s: np.zeros(s.shape, s.dtype),
                    jax.eval_shape(self.optimizer.init, params))
                tree = manager.restore(
                    {"params": params, "opt": opt_template}, step=latest)
                params, opt_state = tree["params"], tree["opt"]
                start_epoch = int(
                    manager.metadata(step=latest).get("epoch", -1)) + 1
                resumed = True
        # opt state sharded LIKE the params (trunk moments on pp, not
        # replicated — replicating Adam m+v would defeat the memory point
        # of pipeline parallelism). Same mirror rule as SPMDTrainer: moment
        # subtrees shaped like the params tree take the params' shardings;
        # anything else (step counters) replicates.
        repl = NamedSharding(self.mesh, P())
        param_sh = {
            "embed": jax.tree_util.tree_map(lambda _: repl,
                                            params["embed"]),
            "blocks": jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P(self.pp_axis)),
                params["blocks"]),
            "head": jax.tree_util.tree_map(lambda _: repl, params["head"]),
        }
        pstruct = jax.tree_util.tree_structure(params)
        opt_shapes = jax.eval_shape(self.optimizer.init, params)
        rmap = lambda tree: jax.tree_util.tree_map(lambda _: repl, tree)
        mirror = lambda sub: param_sh if jax.tree_util.tree_structure(
            sub) == pstruct else rmap(sub)
        opt_sh = ({k: mirror(v) for k, v in opt_shapes.items()}
                  if isinstance(opt_shapes, dict) else rmap(opt_shapes))
        params = lm.shard_variables(params, self.mesh, self.pp_axis)
        if resumed:
            # REMATERIALIZE the restored trees through a non-donated
            # jitted copy before anything donates them: a SHARDED
            # device_put of a host numpy array zero-copy-aliases the
            # numpy buffer on this CPU client (each shard's device
            # pointer is a slice of the host allocation — verified), so
            # the np.load'd checkpoint tree would enter the donating
            # run_epoch backed by memory XLA does not own; reuse then
            # corrupts the values nondeterministically (resume-exactness
            # drifted run to run before this copy; same hazard class as
            # SPMDTrainer's restored carry, see spmd.py). The jitted
            # copy's outputs are XLA-allocated, which makes the first
            # donation safe. One-time cost at resume.
            params = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t),
                out_shardings=param_sh)(params)
            opt_state = jax.tree_util.tree_map(
                lambda host, sh: jax.device_put(host, sh),
                opt_state, opt_sh)
            opt_state = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t),
                out_shardings=opt_sh)(opt_state)
        else:
            opt_state = jax.jit(self.optimizer.init,
                                out_shardings=opt_sh)(params)
        step = lm.make_train_step(self.loss, self.optimizer, self.mesh,
                                  data_axes=self.data_axes,
                                  pp_axis=self.pp_axis,
                                  seq_axis=self.seq_axis,
                                  metric_fns=self._metric_fns())

        have_mets = bool(self._metric_fns())

        # whole epoch = ONE jitted scan over [steps, ...] stacked batches
        # (family parity with make_epoch_runner; no per-step Python)
        @partial(jax.jit, donate_argnums=(0,))
        def run_epoch(carry, Xs, Ys):
            def body(c, xy):
                c, out = step(c, xy)
                return c, out if have_mets else (out, {})
            return lax.scan(body, carry, (Xs, Ys))

        seq_entry = (self.seq_axis,) if self.seq_axis else (None,)
        data_sh = NamedSharding(self.mesh,
                                P(None, self.data_axes, *seq_entry))

        from distkeras_tpu.parallel.worker import stack_batches

        from distkeras_tpu.obs import resolve_tape
        tape = self.tape = resolve_tape(self.telemetry, "PipelineTrainer",
                                        unit="tokens")
        tape.watch("PipelineTrainer.epoch", run_epoch)

        validator = self._make_validator()
        carry = (params, opt_state)
        carry_box = [carry]
        self.stop_training = False
        # standing preemption notices survive train() entry (see
        # trainers.epoch_exit: consumed when acted on)
        self.preempted = False
        self._pending_weights = None
        self._weights_fn = lambda: (  # callback API: explicit user fetch
            jax.device_get(carry_box[0][0]), {})  # lint: allow-host-sync
        cbs = CallbackList(self.callbacks, self)
        cbs.train_begin()
        self.history.record_training_start()
        tape.train_begin()
        try:
            from distkeras_tpu.obs import timed_stream
            from distkeras_tpu.parallel.trainers import epoch_exit, val_logs
            from distkeras_tpu.resilience import faults
            from distkeras_tpu.utils.prefetch import Prefetcher, \
                device_stager

            def assemble(epoch):
                # same shuffle-seed convention as Trainer._epoch_perm
                perm = (np.random.RandomState(self.seed + 1000 * epoch)
                        .permutation(len(X))
                        if self.shuffle_each_epoch else None)
                return stack_batches(X, Y, self.batch_size, perm)

            # epoch e+1's shuffle gather + stacking + sharded H2D staging
            # run on the loader thread while the device trains epoch e
            # (docs/overlap.md; depth=1 — a chunk is the whole stacked
            # epoch, one-ahead is full overlap). device_put of the
            # numpy stack DIRECTLY with the target sharding — the old
            # jax.device_put(jnp.asarray(Xs)) first materialized a
            # default-device copy, then moved it (double host copy)
            stream = Prefetcher(assemble,
                                range(start_epoch, self.num_epoch),
                                depth=1, place=device_stager(data_sh),
                                name="pipeline-feed")
            for epoch, (xb, yb, nsteps) in timed_stream(stream, tape):
                # chaos hook: a mid-training crash at an arbitrary epoch
                faults.point("train.epoch")
                with tape.phase("device"):
                    carry, (losses, mets) = run_epoch(carry, xb, yb)
                    carry_box[0] = carry
                    # the epoch-boundary fetch (one per epoch; device_get
                    # enqueues the per-leaf async copies itself)
                    losses, mets = jax.device_get(  # lint: allow-host-sync
                        (losses, mets))
                # chaos hook: NaN-poison the epoch losses the
                # anomaly guard watches
                losses = faults.corrupt("train.loss", losses)
                extra = {}
                if validator is not None:
                    with tape.phase("validation"):
                        extra = val_logs(validator(carry[0]))
                self.history.append_epoch(loss=np.asarray(losses),
                                          **{k: np.asarray(v)
                                             for k, v in mets.items()},
                                          **extra)
                saved = False
                if manager is not None and (
                        (epoch + 1) % self.checkpoint_every == 0
                        or epoch == self.num_epoch - 1):
                    with tape.phase("checkpoint"):
                        manager.save(
                            epoch,
                            {"params": carry[0], "opt": carry[1]},
                            metadata={"epoch": epoch})
                    saved = True
                logs = {"loss": float(np.mean(losses))}
                logs.update({k: float(np.mean(np.asarray(v)))
                             for k, v in mets.items()})
                logs.update({k: float(np.asarray(v).ravel()[0])
                             for k, v in extra.items()})
                logs.update(tape.epoch_end(
                    nsteps * self.batch_size * X.shape[1]))
                if epoch == start_epoch:
                    tape.mark_warm()
                cbs.epoch_end(epoch, logs)
                # early stop / preemption between checkpoint_every
                # boundaries saves the final state, or resume would
                # lose these epochs (trainers.epoch_exit: the shared
                # exit rule, one copy for the whole family)
                if epoch_exit(self, epoch, saved,
                              (lambda ep: manager.save(
                                  ep, {"params": carry[0],
                                       "opt": carry[1]},
                                  metadata={"epoch": ep}))
                              if manager is not None else None):
                    break
        finally:
            self.history.record_training_stop()
            tape.train_end()
            cbs.train_end()
        if manager is not None:
            manager.wait()

        # end-of-train result fetch
        self.params_ = jax.device_get(carry[0])  # lint: allow-host-sync
        if self._pending_weights is not None:
            self.params_ = self._pending_weights[0]
        return self.params_

    def predict(self, x) -> np.ndarray:
        if self.params_ is None:
            raise RuntimeError("call train() first")
        if self._fwd is None:  # built once; params are a traced argument
            self._fwd = jax.jit(self.lm.apply)
        return np.asarray(self._fwd(self.params_, jnp.asarray(x)))
