"""SPMDTrainer — synchronous data×tensor×expert-parallel training via GSPMD.

No reference equivalent: dist-keras workers each hold a full model replica
(SURVEY §2.3 — TP/EP rows are "absent in the reference"). This trainer is
the capability ADD that trains models larger than one chip's HBM, and the
scaling path for the north-star config: params are sharded by the rules in
``parallel/sharding.py`` (Megatron column→row TP, expert-axis EP, optional
ZeRO/FSDP), the batch is sharded over the data axes, and ONE ``jax.jit``
over the whole epoch scan lets XLA's GSPMD partitioner place every
collective (all-reduce of grads over data axes, all-gather/reduce-scatter
around TP matmuls) on ICI.

Contrast with ``parallel/engine.py``: the engine reproduces the reference's
*algorithm family* (async PS semantics) with replicated models under
``shard_map``; SPMDTrainer is plain synchronous SGD but composes every
sharding dimension. Use the engine for DOWNPOUR/EASGD parity, SPMDTrainer
for big models.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.core import Model
from distkeras_tpu.parallel.engine import host_fetch
from distkeras_tpu.resilience import faults
from distkeras_tpu.parallel.sharding import named_shardings, param_specs
from distkeras_tpu.parallel.trainers import Trainer
from distkeras_tpu.parallel.worker import (TrainCarry, make_train_step,
                                           stack_batches)


class SPMDTrainer(Trainer):
    """Synchronous large-model trainer over an N-D mesh.

    ``mesh`` axes: data axes (``data_axes``, default ``("workers",)``) shard
    the batch; ``tp_axis``/``ep_axis`` shard params per
    ``sharding.ShardingRules``; ``fsdp_axis`` (usually the data axis itself)
    ZeRO-shards remaining large kernels. ``batch_size`` is the GLOBAL batch.
    """

    def __init__(self, keras_model: Model, mesh: Optional[Mesh] = None,
                 data_axes: Union[str, Sequence[str]] = ("workers",),
                 tp_axis: Optional[str] = "tp",
                 ep_axis: Optional[str] = None,
                 fsdp_axis: Optional[str] = None,
                 sharded_checkpoints: bool = True, **kwargs):
        super().__init__(keras_model, **kwargs)
        #: per-shard checkpoint files (utils.checkpoint.
        #: ShardedCheckpointManager): saves write only addressable shards,
        #: restores device_put shard-by-shard — the full tree never lands
        #: on one host (this trainer exists for models where it can't).
        #: Requires checkpoint_dir on SHARED storage under multi-process.
        self.sharded_checkpoints = bool(sharded_checkpoints)
        if mesh is None:
            from distkeras_tpu.parallel.mesh import make_mesh
            mesh = make_mesh()
        self.mesh = mesh
        if isinstance(data_axes, str):
            data_axes = (data_axes,)
        unknown = [a for a in data_axes if a not in mesh.shape]
        if unknown:
            # unlike tp/ep (where replicated fallback is documented), a
            # missing data axis silently disables data parallelism — fail
            raise ValueError(
                f"data_axes {unknown} not in mesh axes "
                f"{tuple(mesh.shape)}")
        self.data_axes = tuple(data_axes)
        self.tp_axis = tp_axis
        self.ep_axis = ep_axis
        self.fsdp_axis = fsdp_axis
        dp = int(np.prod([mesh.shape[a] for a in self.data_axes])) \
            if self.data_axes else 1
        if self.batch_size % max(dp, 1):
            raise ValueError(
                f"global batch_size {self.batch_size} must divide evenly "
                f"over data axes {self.data_axes} (size {dp})")

    # -- sharding plumbing --------------------------------------------------
    def _placements(self, model: Model):
        specs = param_specs(model.module, model.params, self.mesh,
                            tp_axis=self.tp_axis, ep_axis=self.ep_axis,
                            fsdp_axis=self.fsdp_axis)
        param_sh = named_shardings(specs, self.mesh)
        repl = NamedSharding(self.mesh, P())
        data_sh = NamedSharding(
            self.mesh, P(None, self.data_axes or None))  # [S, B, ...]
        return param_sh, repl, data_sh

    def param_partition_specs(self, model: Optional[Model] = None):
        """The PartitionSpec tree this trainer uses (introspection/tests)."""
        model = model or self.master_model
        return param_specs(model.module, model.params, self.mesh,
                           tp_axis=self.tp_axis, ep_axis=self.ep_axis,
                           fsdp_axis=self.fsdp_axis)

    # -- resume plumbing ----------------------------------------------------
    def _checkpoint_manager(self):
        if self.checkpoint_dir is None:
            return None
        if self.sharded_checkpoints:
            if self.checkpoint_async:
                raise ValueError(
                    "checkpoint_async is not supported with "
                    "sharded_checkpoints: the sharded save runs "
                    "multi-process barriers that must stay on the training "
                    "thread. Pass sharded_checkpoints=False to keep async "
                    "dense snapshots.")
            from distkeras_tpu.utils.checkpoint import \
                ShardedCheckpointManager
            return ShardedCheckpointManager(self.checkpoint_dir)
        return super()._checkpoint_manager()

    def _opt_shardings(self, params_host, param_sh, repl):
        """Shardings for the optimizer state: moment subtrees that mirror
        the params tree get the params' shardings (moments live WITH their
        params); anything else (step counters) replicates. Used both to
        constrain the fresh ``jit(init)`` (GSPMD would otherwise be free to
        shard unconstrained zeros however it likes) and to place restored
        checkpoint shards — keeping save and restore layouts identical."""
        opt_shapes = jax.eval_shape(self.worker_optimizer.init, params_host)
        pstruct = jax.tree_util.tree_structure(params_host)
        rmap = lambda tree: jax.tree_util.tree_map(lambda _: repl, tree)
        mirror = lambda sub: param_sh if jax.tree_util.tree_structure(
            sub) == pstruct else rmap(sub)
        if isinstance(opt_shapes, dict):
            return {k: mirror(v) for k, v in opt_shapes.items()}
        return rmap(opt_shapes)

    def _restore_sharded(self, manager, model: Model, param_sh, repl):
        """Device-direct resume: build the sharding tree matching the saved
        carry and let the manager place every stored shard. Returns
        ``(device carry tree | None, start_epoch)``. Old dense or
        params-only checkpoints restore too (full-copy slicing / fresh
        moments)."""
        if manager is None or not self.resume:
            return None, 0
        latest = manager.latest_step()
        if latest is None:
            return None, 0
        keys = manager.keys(latest) or []
        full_carry = any(k == "rng" or k.startswith("rng/") for k in keys)

        rmap = lambda tree: jax.tree_util.tree_map(lambda _: repl, tree)
        shardings = {"params": param_sh, "state": rmap(model.state)}
        if full_carry:
            shardings["opt"] = self._opt_shardings(model.params, param_sh,
                                                   repl)
            shardings["rng"] = repl
        else:
            import warnings
            warnings.warn(
                "checkpoint predates the full-carry format; restoring "
                "params/state only (optimizer moments and rng restart "
                "fresh)", stacklevel=2)
        tree = manager.restore_sharded(shardings, step=latest)
        meta = manager.metadata(step=latest)
        start = int(meta.get("epoch", -1)) + 1
        return (tree if start > 0 else None), start

    def _ckpt_format(self, manager) -> int:
        """0: no checkpoint; 1: old params/state-only; 2: full carry.

        Detected by the rng key, not the opt keys: an EMPTY optimizer state
        (plain sgd) flattens to no ``opt/`` entries at all, but every
        full-carry snapshot stores ``rng``."""
        latest = manager.latest_step()
        if latest is None:
            return 0
        ks = manager.keys(latest) or []
        return 2 if any(k == "rng" or k.startswith("rng/") or k == "opt"
                        or k.startswith("opt/") for k in ks) else 1

    def _restore_full_carry(self, manager, model: Model):
        """Returns ``(restored_host_tree | None, start_epoch)``.

        The restore template's optimizer slot is host-numpy zeros built from
        ``jax.eval_shape`` — nothing touches a device until placement. Old
        checkpoints written before the full-carry format (params/state only)
        restore with a warning and fresh optimizer moments. The format is
        detected from the manifest and broadcast BEFORE the collective
        restore, so every process enters ``_maybe_resume`` with the SAME
        template structure (detecting via try/except on process 0 alone
        would desynchronize the broadcast).
        """
        if manager is None or not self.resume:
            return None, 0
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            flag = np.int32(self._ckpt_format(manager)
                            if jax.process_index() == 0 else 0)
            flag = int(multihost_utils.broadcast_one_to_all(flag))
        else:
            flag = self._ckpt_format(manager)
        if flag == 0:
            return None, 0

        host_zeros = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype),
            jax.eval_shape(self.worker_optimizer.init, model.params))
        fresh_rng = np.asarray(jax.random.PRNGKey(self.seed))
        template = {"params": model.params, "state": model.state}
        if flag == 2:
            template.update(opt=host_zeros, rng=fresh_rng)
        else:
            import warnings
            warnings.warn(
                "checkpoint predates the full-carry format; restoring "
                "params/state only (optimizer moments and rng restart "
                "fresh)", stacklevel=2)
        tree, start_epoch = self._maybe_resume(manager, template)
        if flag == 1:
            # fresh moments are zeros for every optimizer in the registry,
            # so the host-zeros stand-in IS the fresh state
            tree = {**tree, "opt": host_zeros, "rng": fresh_rng}
        return (tree if start_epoch > 0 else None), start_epoch

    def _place_opt(self, opt_host, host_params, param_sh):
        """Place restored optimizer state: subtrees that mirror the params
        structure (momentum/adam moments) are device_put shard-by-shard with
        the params' shardings; anything else (step counters) goes up as
        uncommitted scalars."""
        pstruct = jax.tree_util.tree_structure(host_params)

        def place(sub):
            if jax.tree_util.tree_structure(sub) == pstruct:
                return jax.tree_util.tree_map(jax.device_put, sub, param_sh)
            return jax.tree_util.tree_map(jnp.asarray, sub)

        if isinstance(opt_host, dict):
            return {k: place(v) for k, v in opt_host.items()}
        return jax.tree_util.tree_map(jnp.asarray, opt_host)

    # -- training -----------------------------------------------------------
    def train(self, dataset: Dataset) -> Model:
        from distkeras_tpu.data.sharded import ShardedDataset
        model = self.master_model
        sharded = isinstance(dataset, ShardedDataset)
        if not sharded:
            X, y = self._training_arrays(dataset)
        param_sh, repl, data_sh = self._placements(model)

        # full-carry checkpoint (params + model state + optimizer moments +
        # rng) so a resumed run is bitwise-identical to an uninterrupted
        # one — same contract as SingleTrainer
        manager = self._checkpoint_manager()
        if self.sharded_checkpoints:
            restored, start_epoch = self._restore_sharded(
                manager, model, param_sh, repl)
        else:
            restored, start_epoch = self._restore_full_carry(manager, model)

        if restored is None:
            # fresh start: shard params first, then init the optimizer
            # UNDER jit so the moments are created already sharded/lazy —
            # never materialized whole on one device
            params = jax.tree_util.tree_map(jax.device_put, model.params,
                                            param_sh)
            state = jax.device_put(model.state, repl)
            opt_state = jax.jit(
                self.worker_optimizer.init,
                out_shardings=self._opt_shardings(model.params, param_sh,
                                                  repl))(params)
            rng = jax.device_put(jax.random.PRNGKey(self.seed), repl)
        elif self.sharded_checkpoints:
            # already device-resident with the right shardings; fill any
            # missing slots (params-only legacy checkpoints)
            params = restored["params"]
            state = restored["state"]
            opt_state = restored.get("opt")
            if opt_state is None:
                opt_state = jax.jit(
                    self.worker_optimizer.init,
                    out_shardings=self._opt_shardings(
                        model.params, param_sh, repl))(params)
            rng = restored.get("rng")
            if rng is None:
                rng = jax.device_put(jax.random.PRNGKey(self.seed), repl)
        else:
            params = jax.tree_util.tree_map(jax.device_put,
                                            restored["params"], param_sh)
            state = jax.device_put(restored["state"], repl)
            opt_state = self._place_opt(restored["opt"], model.params,
                                        param_sh)
            rng = jax.device_put(jnp.asarray(restored["rng"]), repl)
        carry = TrainCarry(params, state, opt_state, rng)

        step = make_train_step(model.module, self.loss, self.worker_optimizer,
                               self._metric_fns(), self.grad_accum_steps,
                               param_mask=self._param_mask(model),
                               state_mask=self._state_mask(model),
                               fused_vocab_head=self.fused_vocab_head)

        # pin the carry's layout across epochs: GSPMD is otherwise free to
        # re-shard unconstrained outputs (e.g. row-shard a replicated
        # param's adam moment), which would drift the layout away from
        # what _opt_shardings promised the checkpoint format
        rmap = lambda tree: jax.tree_util.tree_map(lambda _: repl, tree)
        carry_sh = TrainCarry(
            param_sh, rmap(model.state),
            self._opt_shardings(model.params, param_sh, repl), repl)

        if restored is not None:
            # A restored carry can hold leaves whose device buffers ALIAS
            # host numpy memory: a sharded device_put of a host array
            # zero-copy-aliases the numpy buffer on this CPU client (each
            # shard's device pointer is a slice of the host allocation —
            # verified), and both restore paths device_put np.load'd
            # trees. run_epoch donates the carry, so XLA would reuse/free
            # buffers it does not own — intermittent heap corruption
            # (`free(): corrupted unsorted chunks` aborts on the resume
            # path; ~3-in-4 before this copy, 0 after). A non-donated
            # jitted copy rematerializes every leaf into XLA-owned
            # buffers once, before anything is donated.
            carry = jax.jit(
                lambda c: jax.tree_util.tree_map(jnp.copy, c),
                out_shardings=carry_sh)(carry)

        @partial(jax.jit, donate_argnums=(0,), out_shardings=(carry_sh, None))
        def run_epoch(carry, Xs, Ys):
            return jax.lax.scan(step, carry, (Xs, Ys))

        tape = self._make_tape()
        tape.watch("SPMDTrainer.epoch", run_epoch)

        from distkeras_tpu.utils.prefetch import Prefetcher, device_stager
        validator = self._make_validator(model.module)
        cbs = self._cb_list(
            lambda: host_fetch((carry.params, carry.state)))

        # loader-thread staging with the TRAINER'S data sharding: the
        # epoch loop consumes batches already resident (or streaming)
        # across the data axes — no inline device_put on the training
        # thread (docs/overlap.md)
        stage = device_stager(data_sh)
        if sharded:
            # out-of-core (data.sharded.ShardedDataset): compiled scan per
            # shard; ONE flat prefetch stream spans epoch boundaries so the
            # loader thread never idles (Trainer._sharded_stream)
            stream = self._sharded_stream(dataset, start_epoch, place=stage)
        else:
            # in-memory: ONE chunk per epoch; the Prefetcher overlaps the
            # next epoch's shuffle+stack+H2D with this epoch's device
            # scan. depth=1: a chunk is the whole stacked epoch, and
            # one-ahead is full overlap — deeper only multiplies the
            # dataset's device-memory footprint
            stream = (((e, 0, True), chunk) for e, chunk in Prefetcher(
                lambda e: stack_batches(X, y, self.batch_size,
                                        self._epoch_perm(e, len(X))),
                range(start_epoch, self.num_epoch), depth=1, place=stage))

        self.record_training_start()
        tape.train_begin()
        try:
            with self._profile_ctx():
                from distkeras_tpu.obs import timed_stream
                l_acc, m_acc = [], []
                examples = 0

                def save_now(epoch):
                    carry_tree = {"params": carry.params,
                                  "state": carry.state,
                                  "opt": carry.opt_state,
                                  "rng": carry.rng}
                    with tape.phase("checkpoint"):
                        if self.sharded_checkpoints \
                                or jax.process_count() == 1:
                            # sharded: every process writes ITS shards
                            # (barriers inside), no host gather. Dense
                            # single-process: the manager's async-D2H
                            # snapshot fences the device tree itself
                            # (overlap PR) — transfers run concurrently,
                            # and with checkpoint_async the
                            # serialize+rename overlaps the next scan
                            manager.save(epoch, carry_tree,
                                         metadata={"epoch": epoch})
                        else:
                            # host_fetch is a COLLECTIVE under
                            # multi-process (allgather of
                            # non-addressable shards) — every process
                            # must enter it; only the write is gated
                            # on process 0
                            snapshot = host_fetch(carry_tree)
                            if jax.process_index() == 0:
                                manager.save(epoch, snapshot,
                                             metadata={"epoch": epoch})

                from distkeras_tpu.parallel.engine import host_async
                from distkeras_tpu.parallel.trainers import val_logs
                for (epoch, _, last), (Xs, Ys, S) in timed_stream(stream,
                                                                  tape):
                    # chaos hook: a mid-training crash at an arbitrary
                    # loop iteration (tests/test_resilience.py)
                    faults.point("train.epoch")
                    with tape.phase("device"):
                        # batches arrive device-resident from the
                        # loader thread (device_stager above); per-step
                        # losses/metrics stay on device until the
                        # epoch-boundary fetch (overlap PR)
                        carry, outs = run_epoch(carry, Xs, Ys)
                        losses, mets = self._split_outs(outs)
                        host_async((losses, mets))
                        l_acc.append(losses)
                        m_acc.append(mets)
                    examples += int(S) * self.batch_size
                    if not last:
                        continue
                    with tape.phase("device"):
                        # ONE boundary fetch (collective allgather under
                        # multi-process — same count/order on every
                        # process as the per-shard fetches it replaces)
                        l_acc, m_acc = host_fetch((l_acc, m_acc))
                    # chaos hook: NaN-poison the epoch losses the
                    # anomaly guard watches
                    losses = faults.corrupt(
                        "train.loss", np.concatenate(l_acc))
                    mets = {k: np.concatenate([m[k] for m in m_acc])
                            for k in (m_acc[0] if m_acc else {})}
                    l_acc, m_acc = [], []
                    extra = {}
                    if validator is not None:
                        with tape.phase("validation"):
                            extra = val_logs(host_fetch(validator(
                                carry.params, carry.state)))
                    self.history.append_epoch(loss=losses, **mets, **extra)
                    saved = False
                    if manager is not None and self._should_checkpoint(epoch):
                        save_now(epoch)
                        saved = True
                    # logs derive from replicated values, so every process
                    # sees identical callback decisions (incl. stop_training
                    # and any collective get_weights fetch inside a callback)
                    logs = self._epoch_logs(losses, mets, extra)
                    logs.update(tape.epoch_end(examples))
                    examples = 0
                    if epoch == start_epoch:
                        tape.mark_warm()
                    cbs.epoch_end(epoch, logs)
                    # preemption is delivered per-process (SIGTERM to the
                    # job hits every worker); the stop decision below
                    # must stay consistent across processes, which holds
                    # when the preemption notice reaches all of them
                    if self._epoch_exit(
                            epoch, saved,
                            save_now if manager is not None else None):
                        break
        finally:
            self.record_training_stop()
            tape.train_end()
            cbs.train_end()  # closes callback resources on exceptions too
        if manager is not None:
            manager.wait()  # async snapshots durable before return

        trained = model.replace(params=host_fetch(carry.params),
                                state=host_fetch(carry.state))
        trained = self._apply_pending_weights(trained)
        self.master_model = trained
        return trained
