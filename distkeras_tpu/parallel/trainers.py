"""Trainer hierarchy — orchestration layer.

Reference parity: ``distkeras/trainers.py`` (SURVEY §2.1): ``Trainer`` base
(master model, loss, worker optimizer, history/time bookkeeping, serialize),
``SingleTrainer``, ``AveragingTrainer``, ``EnsembleTrainer``, and the
distributed family (``DOWNPOUR``, ``EASGD``, ``AEASGD``, ``ADAG``,
``DynSGD``) — those distributed trainers live in
``distkeras_tpu/parallel/distributed.py`` and share this base.

API ergonomics match the reference: constructor kwargs
``(model, worker_optimizer, loss, batch_size, num_epoch, features_col,
label_col, ...)`` and ``trainer.train(dataset) -> Model``.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.serialization import serialize_model
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.optimizers import Optimizer, get_optimizer
from distkeras_tpu.parallel.worker import (
    TrainCarry, make_epoch_runner, make_train_step, stack_batches)
from distkeras_tpu.resilience import faults
from distkeras_tpu.utils.history import History


def val_logs(fetched_or_device) -> dict:
    """Validator outputs -> the ``extra`` logs dict (``{key: [scalar]}``
    float arrays) every epoch loop records. The device->host read of the
    validation scalars happens HERE — the ONE sanctioned validation
    fetch point shared by the whole trainer family (it runs once per
    epoch, at the boundary, after the epoch program was dispatched)."""
    fetched = jax.device_get(fetched_or_device)  # lint: allow-host-sync
    return {k: np.asarray([float(v)])            # lint: allow-host-sync
            for k, v in fetched.items()}


def cache_validation_on_device(trainer, Xv, yv):
    """Device-resident validation arrays, cached on ``trainer`` ACROSS
    ``train()`` calls keyed on the ``validation_data`` object's identity
    (plus shape/dtype): a supervised run restarting after a crash — or
    any repeated ``train()`` on one trainer — stops re-paying the full
    validation-set H2D copy every attempt. Shared by the ``Trainer``
    family AND the duck-typed ``PipelineTrainer`` (one copy of the
    invalidation rule). The cache holds the key object itself, so
    identity can't be recycled; swapping ``validation_data`` (or a
    shape/dtype change) invalidates. In-place mutation of a kept
    ``validation_data`` is not detected — replace the object to change
    the data."""
    key = (Xv.shape, str(Xv.dtype), yv.shape, str(yv.dtype))
    cached = getattr(trainer, "_val_device_cache", None)
    if cached is not None and cached[0] is trainer.validation_data \
            and cached[1] == key:
        return cached[2]
    arrs = (jnp.asarray(Xv), jnp.asarray(yv))
    trainer._val_device_cache = (trainer.validation_data, key, arrs)
    return arrs


def epoch_exit(trainer, epoch: int, saved: bool, save_fn) -> bool:
    """Shared end-of-epoch stop logic for every epoch-loop trainer
    (``Trainer`` subclasses AND the duck-typed ``PipelineTrainer`` —
    ONE copy so the exit rule cannot drift between loops): on callback
    stop OR a preemption request, make sure THIS epoch is checkpointed
    (or resume would silently lose it) and tell the loop to break.

    Also the step-ring hook: every epoch lands one record in the
    flight recorder (``obs.recorder``), so a crash dump shows the
    recent training timeline next to the serving iterations — a no-op
    NULL object when telemetry is disabled.

    The preempt Event is consumed HERE, when it is acted on — not
    cleared at train() entry — so a SIGTERM landing between a
    supervisor's restart attempts (after the crash, before the resumed
    run installs its loop) still stops the resumed run at its first
    epoch instead of being silently dropped."""
    trainer.preempted = trainer._preempt.is_set()
    from distkeras_tpu.obs.recorder import resolve_recorder
    resolve_recorder().record(
        "train.epoch", trainer=type(trainer).__name__, epoch=int(epoch),
        saved=bool(saved), stop=bool(trainer.stop_training),
        preempted=bool(trainer.preempted))
    if not (trainer.stop_training or trainer.preempted):
        return False
    if trainer.preempted:
        trainer._preempt.clear()   # consumed: acted on exactly once
    if save_fn is not None and not saved:
        save_fn(epoch)
    return True


class Trainer:
    """Base trainer: holds the master model + loss/optimizer spec + history.

    Reference: ``trainers.py :: Trainer`` (serialized master model, loss,
    worker_optimizer, history, training-time bookkeeping).
    """

    def __init__(self, keras_model: Model,
                 worker_optimizer: Union[str, Optimizer] = "sgd",
                 loss: Union[str, Callable] = "categorical_crossentropy",
                 metrics: Optional[List[str]] = None,
                 features_col: str = "features", label_col: str = "label",
                 batch_size: int = 32, num_epoch: int = 1,
                 learning_rate: Optional[float] = None, seed: int = 0,
                 shuffle_each_epoch: bool = True,
                 optimizer_kwargs: Optional[dict] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, resume: bool = False,
                 checkpoint_async: bool = False,
                 profile_dir: Optional[str] = None,
                 grad_accum_steps: int = 1,
                 validation_data=None,
                 callbacks: Optional[Sequence] = None,
                 clip_grad_norm: Optional[float] = None,
                 class_weight: Optional[dict] = None,
                 fused_vocab_head: bool = False,
                 telemetry=None):
        self.master_model = keras_model
        opt_kwargs = dict(optimizer_kwargs or {})
        if learning_rate is not None and not isinstance(worker_optimizer,
                                                        Optimizer):
            opt_kwargs.setdefault("learning_rate", learning_rate)
        self.worker_optimizer = get_optimizer(worker_optimizer, **opt_kwargs)
        # global-norm gradient clipping as a pure optimizer wrapper — works
        # identically under jit/vmap/shard_map on every trainer
        if clip_grad_norm is not None:
            from distkeras_tpu.ops.optimizers import clip_by_global_norm
            self.worker_optimizer = clip_by_global_norm(
                self.worker_optimizer, clip_grad_norm)
        # eval_loss stays UNWEIGHTED (Keras semantics: class_weight shapes
        # the TRAINING objective only — val_loss must remain comparable
        # across weighted and unweighted runs)
        self.eval_loss = get_loss(loss)
        if class_weight is not None:
            # Keras class_weight: per-sample losses scaled by the true
            # class's weight (pure loss wrapper — every trainer inherits)
            from distkeras_tpu.ops.losses import with_class_weight
            self.loss = with_class_weight(loss, class_weight)
        else:
            self.loss = self.eval_loss
        self.metrics = metrics or []
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = int(seed)
        self.shuffle_each_epoch = bool(shuffle_each_epoch)
        self.history = History()
        # checkpoint/resume (capability ADD over the reference, which has
        # none — SURVEY §5.4); snapshots the master/center model per epoch
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        if self.checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        self.resume = bool(resume)
        # background-thread checkpoint writes (big snapshots stop stalling
        # the step loop); the final wait() happens at train() end
        self.checkpoint_async = bool(checkpoint_async)
        # XLA/device trace of the whole run, viewable in XProf/TensorBoard
        # (SURVEY §5.1: the reference has wall-clock bookkeeping only)
        self.profile_dir = profile_dir
        # microbatch gradient accumulation inside each step (memory lever;
        # honored by SingleTrainer and SPMDTrainer)
        self.grad_accum_steps = int(grad_accum_steps)
        # per-epoch held-out evaluation: a Dataset (features/label cols as
        # configured) or an (X, y) pair; records val_loss / val_<metric>
        # scalars per epoch in History
        self.validation_data = validation_data
        # Keras-style per-epoch callbacks (utils/callbacks.py) — a
        # capability ADD; the reference leaves all of this to Keras, which
        # its bare train_on_batch worker loop never invokes
        self.callbacks = list(callbacks or [])
        # fuse the final vocab projection into a chunked cross-entropy
        # (ops.losses.fused_linear_cross_entropy) — the large-vocab LM
        # memory lever; honored by SingleTrainer and SPMDTrainer (the
        # trainers that train LM-shaped models), rejected loudly by the
        # rest (mirrors grad_accum_steps). True = default chunking; an
        # int picks the token-chunk count (passed through verbatim to
        # make_train_step, same contract).
        if fused_vocab_head and class_weight is not None:
            raise ValueError(
                "fused_vocab_head does not compose with class_weight: "
                "the fused loss never materializes the per-sample logits "
                "the class-weight wrapper scales. Drop one of the two.")
        self.fused_vocab_head = fused_vocab_head
        # telemetry (obs subsystem): None = auto-tape when obs is
        # enabled; False = off for this trainer; or pass a configured
        # obs.TrainingTape (e.g. with flops_per_example for MFU). The
        # live tape is exposed as ``self.tape`` during/after train();
        # its per-epoch logs (examples_per_sec, data_wait_s, device_s,
        # host_s, goodput, mfu, ...) merge into the callback logs.
        self.telemetry = telemetry
        self.tape = None
        self.stop_training = False
        self._weights_fn = None       # bound by trainers during train()
        self._pending_weights = None  # set via set_weights()
        # preemption (resilience PR): request_preempt() — signal-handler
        # safe (an Event set is async-signal tolerable) — asks the epoch
        # loop to checkpoint the CURRENT epoch and return cleanly;
        # ``preempted`` reports whether the last train() ended that way
        self._preempt = threading.Event()
        self.preempted = False

    def request_preempt(self) -> None:
        """Ask the running epoch loop to checkpoint and stop at the end
        of the current epoch (SIGTERM/preemption-notice path — see
        ``resilience.TrainingSupervisor``). Safe to call from a signal
        handler or another thread. The notice STANDS until an epoch
        loop acts on it (``epoch_exit`` consumes it), so a preemption
        delivered between a crash and the supervisor's resumed run is
        honored by that run's first epoch, never dropped."""
        self._preempt.set()

    def _epoch_exit(self, epoch: int, saved: bool, save_fn) -> bool:
        return epoch_exit(self, epoch, saved, save_fn)

    def _reject_step_options(self):
        """Trainers whose step semantics don't compose with the
        SingleTrainer/SPMDTrainer-only step options (gradient
        accumulation, the fused vocab head) must fail loudly rather than
        silently ignore them — the engine family counts WINDOW steps;
        ensembles/host-async have their own loops."""
        if self.grad_accum_steps != 1:
            raise ValueError(
                f"{type(self).__name__} does not support grad_accum_steps "
                "(only SingleTrainer and SPMDTrainer do)")
        if self.fused_vocab_head:
            raise ValueError(
                f"{type(self).__name__} does not support fused_vocab_head "
                "(only SingleTrainer and SPMDTrainer do)")

    def _param_mask(self, model):
        """Boolean mask honoring Keras-style ``layer.trainable = False``
        (``models.core.trainable_mask``); None when nothing is frozen."""
        from distkeras_tpu.models.core import trainable_mask
        return trainable_mask(model.module, model.params)

    def _state_mask(self, model):
        """Same, over the STATE tree (frozen BatchNorm keeps its running
        stats — Keras inference-mode semantics)."""
        from distkeras_tpu.models.core import trainable_mask
        return trainable_mask(model.module, model.state)

    def _checkpoint_manager(self):
        if self.checkpoint_dir is None:
            return None
        from distkeras_tpu.utils.checkpoint import CheckpointManager
        return CheckpointManager(self.checkpoint_dir,
                                 async_writes=self.checkpoint_async)

    def _maybe_resume(self, manager, template):
        """Restore the checkpointed tree (same structure as ``template``).
        Returns ``(tree, start_epoch)``; the step is fixed once so weights
        and metadata always come from the SAME checkpoint.

        Multi-process: only process 0 reads (it is also the only writer —
        see the save path), and the restored tree + start epoch broadcast
        to every process, so resume stays consistent even when
        ``checkpoint_dir`` is host-local disk."""
        if manager is None or not self.resume:
            return template, 0
        if jax.process_count() > 1:
            tree, start = template, 0
            if jax.process_index() == 0:
                tree, start = self._restore_local(manager, template)
            from jax.experimental import multihost_utils
            tree = multihost_utils.broadcast_one_to_all(tree)
            start = int(multihost_utils.broadcast_one_to_all(
                np.int32(start)))
            # resume path, runs once before the loop starts
            return jax.device_get(tree), start  # lint: allow-host-sync
        return self._restore_local(manager, template)

    @staticmethod
    def _restore_local(manager, template):
        latest = manager.latest_step()
        if latest is None:
            return template, 0
        tree = manager.restore(template, step=latest)
        meta = manager.metadata(step=latest)
        return tree, int(meta.get("epoch", -1)) + 1

    def _should_checkpoint(self, epoch: int) -> bool:
        return ((epoch + 1) % self.checkpoint_every == 0
                or epoch == self.num_epoch - 1)

    def _profile_ctx(self):
        if self.profile_dir is None:
            import contextlib
            return contextlib.nullcontext()
        from distkeras_tpu.utils.profiling import trace
        return trace(self.profile_dir)

    def _make_tape(self, unit: str = "examples"):
        """Bind this run's telemetry tape (obs.NULL_TAPE when disabled:
        every hook is a no-op, so the epoch loops stay branch-free)."""
        from distkeras_tpu.obs import resolve_tape
        self.tape = resolve_tape(self.telemetry, type(self).__name__,
                                 unit)
        return self.tape

    # -- reference-parity bookkeeping -------------------------------------
    def record_training_start(self):
        self.history.record_training_start()

    def record_training_stop(self):
        self.history.record_training_stop()

    def get_training_time(self) -> float:
        return self.history.get_training_time()

    def get_history(self) -> History:
        return self.history

    def get_averaged_history(self) -> np.ndarray:
        """Per-step losses averaged over workers (scalar per step)."""
        losses = self.history.losses()
        return losses.mean(axis=-1) if losses.ndim > 1 else losses

    def serialize(self):
        """Reference: ``Trainer.serialize`` — serialized master model."""
        return serialize_model(self.master_model)

    def _metric_fns(self):
        """{name: fn} for the constructor's ``metrics`` list (reference:
        Keras ``model.compile(metrics=...)`` per worker), or None."""
        if not self.metrics:
            return None
        from distkeras_tpu.ops.metrics import get_metric, metric_name
        return {metric_name(m): get_metric(m) for m in self.metrics}

    @staticmethod
    def _split_outs(outs):
        """Scan outputs -> (losses, metrics_dict) for either step shape."""
        if isinstance(outs, tuple):
            return outs[0], outs[1]
        return outs, {}

    # -- callbacks ----------------------------------------------------------
    def _cb_list(self, weights_fn: Optional[Callable] = None):
        """Bind callbacks for a fresh train() run. ``weights_fn`` returns
        host-side ``(params, state)`` of the CURRENT training weights (each
        trainer supplies its own view — carry, engine center, ...)."""
        from distkeras_tpu.utils.callbacks import CallbackList
        self.stop_training = False
        # NOT clearing self._preempt here: a standing preemption notice
        # (e.g. SIGTERM delivered while the supervisor was mid-restart)
        # must stop the next run; epoch_exit consumes it when acted on
        self.preempted = False
        self._pending_weights = None
        self._weights_fn = weights_fn
        cbs = CallbackList(self.callbacks, self)
        cbs.train_begin()
        return cbs

    def _epoch_logs(self, losses, mets, extra) -> dict:
        """Per-epoch scalar logs for callbacks: epoch-mean loss/metrics +
        validation scalars. Inputs are host arrays (already fetched)."""
        logs = {"loss": float(np.mean(np.asarray(losses)))}
        for k, v in mets.items():
            logs[k] = float(np.mean(np.asarray(v)))
        for k, v in extra.items():
            logs[k] = float(np.asarray(v).ravel()[0])
        return logs

    def get_weights(self):
        """Host-side ``(params, state)`` of the in-progress training weights
        (callback API; only valid while train() is running)."""
        if self._weights_fn is None:
            raise RuntimeError(
                "get_weights() is only available to callbacks while "
                "train() is running")
        return self._weights_fn()

    def set_weights(self, params, state) -> None:
        """Replace the weights the trainer will return (callback API —
        e.g. EarlyStopping(restore_best_weights=True))."""
        self._pending_weights = (params, state)

    def snapshot_model(self) -> Model:
        """A Model carrying the current training weights (callback API)."""
        params, state = self.get_weights()
        m = self.master_model
        return Model(m.module, params, state, m.input_shape, m.output_shape)

    def _apply_pending_weights(self, trained: Model) -> Model:
        if self._pending_weights is None:
            return trained
        params, state = self._pending_weights
        return trained.replace(params=params, state=state)

    def _reject_callbacks(self):
        if self.callbacks:
            raise ValueError(
                f"{type(self).__name__} does not support callbacks (no "
                "single evolving model to monitor)")

    # -- validation ---------------------------------------------------------
    def _validation_arrays(self):
        if self.validation_data is None:
            return None
        vd = self.validation_data
        if isinstance(vd, Dataset):
            return vd.arrays(self.features_col, self.label_col)
        X, y = vd
        from distkeras_tpu.data.dataset import coerce_column
        return coerce_column(X), coerce_column(y)

    def _device_validation_arrays(self, Xv, yv):
        return cache_validation_on_device(self, Xv, yv)

    def _make_validator(self, module):
        """Jitted full-set eval: ``validator(params, state) ->
        {"val_loss": ..., "val_<metric>": ...}`` (scalars). Built once; the
        validation set must fit device memory (use a subsample otherwise).
        """
        val = self._validation_arrays()
        if val is None:
            return None
        Xv, yv = val
        loss_fn = self.eval_loss  # unweighted even under class_weight
        metric_fns = self._metric_fns() or {}

        # the arrays are jit ARGUMENTS (not closure captures) so the whole
        # validation set is not constant-folded into the executable; the
        # device cache places them ONCE per dataset — across epochs AND
        # across train() calls (supervisor restarts)
        Xv, yv = self._device_validation_arrays(Xv, yv)

        @jax.jit
        def evalf(params, state, Xv, yv):
            out, _ = module.apply(params, state, Xv, training=False)
            res = {"val_loss": loss_fn(yv, out)}
            for name, fn in metric_fns.items():
                res[f"val_{name}"] = fn(yv, out)
            return res

        return lambda params, state: evalf(params, state, Xv, yv)

    # -- out-of-core plumbing ----------------------------------------------
    def _sharded_stream(self, sds, start_epoch: int, place=None):
        """ONE Prefetcher over the flattened (epoch, shard) sequence of a
        ``ShardedDataset`` (``ShardedDataset.epoch_items``): yields
        ``((epoch, shard_idx, is_epoch_last), (Xs, Ys, n_steps))``. A
        single flat stream keeps the background loader busy ACROSS epoch
        boundaries (a per-epoch prefetcher would stall one shard-load at
        every boundary), and one definition keeps the shuffle determinism
        formula shared by every sharded trainer. ``place`` stages each
        stacked chunk onto device ON THE LOADER THREAD
        (``prefetch.device_stager``) with a 2-deep device buffer —
        consumers receive device-resident batches (docs/overlap.md)."""
        from distkeras_tpu.utils.prefetch import Prefetcher
        items = sds.epoch_items(start_epoch, self.num_epoch, self.seed,
                                self.shuffle_each_epoch)

        from distkeras_tpu.resilience.retry import io_retry
        fetch_retry = io_retry()

        def assemble(item):
            epoch, si, _ = item

            def fetch():
                # chaos hook + transient-IO retry: a flaky shard read
                # (NFS blip, injected "data.fetch" fault) costs a
                # jittered backoff on the loader thread, not the run
                faults.point("data.fetch")
                return sds.load_shard(si)

            Xc, yc = self._training_arrays(
                fetch_retry.call(fetch, op="data.fetch"))
            perm = None
            if self.shuffle_each_epoch:
                perm = np.random.RandomState(
                    self.seed + 1000 * epoch + 31 * si).permutation(len(Xc))
            return stack_batches(Xc, yc, self.batch_size, perm)

        return Prefetcher(assemble, items, depth=2 if place else 1,
                          place=place)

    # -- data plumbing -----------------------------------------------------
    def _training_arrays(self, dataset: Dataset):
        from distkeras_tpu.data.sharded import ShardedDataset
        if isinstance(dataset, ShardedDataset):
            raise ValueError(
                f"{type(self).__name__} does not support ShardedDataset "
                "(out-of-core training is a SingleTrainer/SPMDTrainer "
                "capability); load shards into one Dataset, or switch "
                "trainer")
        X, y = dataset.arrays(self.features_col, self.label_col)
        if y is None:
            raise ValueError(
                f"label column {self.label_col!r} not in dataset "
                f"(columns: {dataset.columns})")
        return X, y

    def _epoch_perm(self, epoch: int, n: int):
        if not self.shuffle_each_epoch:
            return None
        return np.random.RandomState(self.seed + 1000 * epoch).permutation(n)

    def train(self, dataset: Dataset) -> Model:
        raise NotImplementedError


class SingleTrainer(Trainer):
    """Single-device training — the minimum end-to-end slice.

    Reference: ``trainers.py :: SingleTrainer.train`` coalesces the DataFrame
    to one partition and runs a SequentialWorker's per-batch Keras loop there
    (SURVEY §3.1). Here the whole epoch is ONE jitted ``lax.scan`` over
    ``[steps, batch, ...]`` stacked columnar data.
    """

    def train(self, dataset: Dataset) -> Model:
        from distkeras_tpu.data.sharded import ShardedDataset
        from distkeras_tpu.utils.prefetch import Prefetcher
        model = self.master_model
        sharded = isinstance(dataset, ShardedDataset)
        if not sharded:
            X, y = self._training_arrays(dataset)
        step = make_train_step(model.module, self.loss, self.worker_optimizer,
                               self._metric_fns(), self.grad_accum_steps,
                               param_mask=self._param_mask(model),
                               state_mask=self._state_mask(model),
                               fused_vocab_head=self.fused_vocab_head)
        runner = make_epoch_runner(step)
        tape = self._make_tape()
        # after the first epoch's legitimate compiles, any cache growth
        # on the epoch program is a shape leak (warned via check() in
        # tape.epoch_end)
        tape.watch("SingleTrainer.epoch", runner)

        # SingleTrainer checkpoints the FULL carry (params + model state +
        # optimizer state + rng), so a resumed run is bitwise-identical to
        # an uninterrupted one. (Distributed trainers checkpoint the center
        # only — the documented PS-retry semantic.)
        manager = self._checkpoint_manager()
        fresh = {"params": model.params, "state": model.state,
                 "opt": self.worker_optimizer.init(model.params),
                 "rng": jax.random.PRNGKey(self.seed)}
        tree, start_epoch = self._maybe_resume(manager, fresh)
        # place the (numpy, when resumed) carry on device ONCE: the first
        # epoch's runner signature then matches every later epoch's — a
        # numpy carry on the first call plus a device carry on the next
        # adds a second jit-cache entry and false-positives the recompile
        # detector. The runner does not donate, so zero-copy placement is
        # safe (unlike the SPMD/pipeline restore paths, which must copy).
        tree = jax.tree_util.tree_map(jnp.asarray, tree)
        carry = TrainCarry(params=tree["params"], state=tree["state"],
                           opt_state=tree["opt"], rng=tree["rng"])

        from distkeras_tpu.utils.prefetch import device_stager
        if sharded:
            # out-of-core: compiled scan per shard; ONE flat prefetch
            # stream spans epoch boundaries so the loader never idles
            # (Trainer._sharded_stream; reference analogue: Spark workers
            # iterate HDFS partition rows — workers.py :: Worker.train);
            # the loader thread also stages each chunk onto device
            stream = self._sharded_stream(dataset, start_epoch,
                                          place=device_stager())
        else:
            # in-memory: ONE chunk per epoch; epoch e+1's shuffle gather,
            # stacking AND device staging run while the device trains
            # epoch e. depth=1 here — a chunk is the WHOLE stacked
            # epoch, and one-ahead already gives full overlap; deeper
            # buffering would only multiply dataset copies in device
            # memory (docs/overlap.md)
            stream = (((e, 0, True), chunk) for e, chunk in Prefetcher(
                lambda e: stack_batches(X, y, self.batch_size,
                                        self._epoch_perm(e, len(X))),
                range(start_epoch, self.num_epoch), depth=1,
                place=device_stager()))

        validator = self._make_validator(model.module)
        cbs = self._cb_list(  # callback API: an explicit user-facing fetch
            lambda: jax.device_get(  # lint: allow-host-sync
                (carry.params, carry.state)))
        self.record_training_start()
        tape.train_begin()
        try:
            with self._profile_ctx():
                from distkeras_tpu.obs import timed_stream
                l_acc, m_acc = [], []
                examples = 0

                def save_now(epoch):
                    with tape.phase("checkpoint"):
                        manager.save(
                            epoch,
                            {"params": carry.params,
                             "state": carry.state,
                             "opt": carry.opt_state, "rng": carry.rng},
                            metadata={"epoch": epoch})

                from distkeras_tpu.parallel.engine import host_async
                for (epoch, _, last), (Xs, Ys, S) in timed_stream(stream,
                                                                  tape):
                    # chaos hook: a mid-training crash at an arbitrary
                    # loop iteration (tests/test_resilience.py)
                    faults.point("train.epoch")
                    with tape.phase("device"):
                        carry, outs = runner(carry, Xs, Ys)
                        # per-step loss/metric arrays STAY ON DEVICE for
                        # the whole epoch — only the D2H transfer is
                        # started here (non-blocking), so a multi-shard
                        # epoch no longer pays one blocking round trip
                        # per shard (overlap PR)
                        losses, mets = self._split_outs(outs)
                        host_async((losses, mets))
                        l_acc.append(losses)
                        m_acc.append(mets)
                    examples += int(S) * self.batch_size
                    if not last:
                        continue
                    with tape.phase("device"):
                        # ONE epoch-boundary fetch of everything the
                        # epoch accumulated (transfers already in
                        # flight); blocking here also bounds the device
                        # phase through the last dispatched program
                        l_acc, m_acc = jax.device_get(  # lint: allow-host-sync
                            (l_acc, m_acc))
                    # chaos hook: NaN-poison the epoch losses the
                    # anomaly guard watches (history/logs downstream)
                    losses = faults.corrupt(
                        "train.loss", np.concatenate(l_acc))
                    mets = {k: np.concatenate([m[k] for m in m_acc])
                            for k in (m_acc[0] if m_acc else {})}
                    l_acc, m_acc = [], []
                    extra = {}
                    if validator is not None:
                        with tape.phase("validation"):
                            extra = val_logs(validator(carry.params,
                                                       carry.state))
                    self.history.append_epoch(loss=losses, **mets, **extra)
                    saved = False
                    if manager is not None and self._should_checkpoint(epoch):
                        save_now(epoch)
                        saved = True
                    logs = self._epoch_logs(losses, mets, extra)
                    logs.update(tape.epoch_end(examples))
                    examples = 0
                    if epoch == start_epoch:
                        # first full epoch saw every legitimate shape
                        tape.mark_warm()
                    cbs.epoch_end(epoch, logs)
                    if self._epoch_exit(
                            epoch, saved,
                            save_now if manager is not None else None):
                        break
        finally:
            self.record_training_stop()
            tape.train_end()
            cbs.train_end()  # closes callback resources on exceptions too
        if manager is not None:
            manager.wait()  # async snapshots durable before return

        trained = model.replace(  # end-of-train fetch of the result
            params=jax.device_get(carry.params),  # lint: allow-host-sync
            state=jax.device_get(carry.state))    # lint: allow-host-sync
        trained = self._apply_pending_weights(trained)
        self.master_model = trained
        return trained


class EnsembleTrainer(Trainer):
    """Trains ``num_models`` independent models in parallel via ``vmap``.

    Reference: ``trainers.py :: EnsembleTrainer`` trains k independent Keras
    models on k Spark partition groups. TPU-native: the k model replicas are
    ONE stacked pytree trained by a vmapped scan — XLA batches the k small
    matmuls into bigger MXU ops. Each replica gets its own init seed, its own
    dropout stream, and its own per-epoch data permutation.
    """

    def __init__(self, keras_model: Model, num_models: int = 2, **kwargs):
        super().__init__(keras_model, **kwargs)
        self.num_models = int(num_models)
        self.models_: List[Model] = []

    def train(self, dataset: Dataset) -> List[Model]:
        self._reject_step_options()
        self._reject_callbacks()
        if self.validation_data is not None:
            raise ValueError(
                "EnsembleTrainer does not support validation_data (k "
                "independent members have no single validation score); "
                "evaluate members individually after train()")
        base = self.master_model
        X, y = self._training_arrays(dataset)
        k = self.num_models

        # independent inits: re-init the module with k different seeds
        inits = [Model.build(base.module, base.input_shape, seed=self.seed + i)
                 for i in range(k)]
        params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[m.params for m in inits])
        state = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[m.state for m in inits])
        opt_state = jax.vmap(self.worker_optimizer.init)(params)
        rngs = jax.random.split(jax.random.PRNGKey(self.seed), k)

        step = make_train_step(base.module, self.loss, self.worker_optimizer,
                               self._metric_fns(),
                               param_mask=self._param_mask(base),
                               state_mask=self._state_mask(base))

        @jax.jit
        def run_epoch(carry, Xk, Yk):
            def per_model(c, xy):
                return jax.lax.scan(step, c, xy)
            return jax.vmap(per_model)(carry, (Xk, Yk))

        carry = TrainCarry(params, state, opt_state, rngs)
        self.record_training_start()
        for epoch in range(self.num_epoch):
            stacked = [stack_batches(
                X, y, self.batch_size,
                np.random.RandomState(self.seed + 1000 * epoch + i)
                .permutation(len(X)) if self.shuffle_each_epoch else None)
                for i in range(k)]
            Xk = np.stack([s[0] for s in stacked])  # [k, steps, bs, ...]
            Yk = np.stack([s[1] for s in stacked])
            carry, outs = run_epoch(carry, Xk, Yk)
            losses, mets = self._split_outs(outs)
            # [k, steps] -> record as [steps, k]; epoch-boundary fetch
            self.history.append_epoch(
                loss=jax.device_get(losses).T,  # lint: allow-host-sync
                **{n: jax.device_get(v).T       # lint: allow-host-sync
                   for n, v in mets.items()})
        self.record_training_stop()

        # end-of-train result fetch
        params_h = jax.device_get(carry.params)  # lint: allow-host-sync
        state_h = jax.device_get(carry.state)    # lint: allow-host-sync
        self.models_ = [
            base.replace(
                params=jax.tree_util.tree_map(lambda p: p[i], params_h),
                state=jax.tree_util.tree_map(lambda s: s[i], state_h))
            for i in range(k)]
        # master model = first member (reference returns the model list; we
        # keep both: return list, stash members on .models_)
        self.master_model = self.models_[0]
        return self.models_
