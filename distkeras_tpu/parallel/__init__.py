"""Parallel layer: trainer hierarchy + device-mesh distributed engine."""

from distkeras_tpu.parallel.distributed import (  # noqa: F401
    ADAG, AEASGD, DOWNPOUR, AveragingTrainer, DistributedTrainer, DynSGD,
    EASGD)
from distkeras_tpu.parallel.mesh import make_mesh, make_mesh_2d  # noqa: F401
from distkeras_tpu.parallel.trainers import (  # noqa: F401
    EnsembleTrainer, SingleTrainer, Trainer)
