"""Parallel layer: trainer hierarchy + device-mesh distributed engine +
host-side parameter-server family (true-async / DCN fallback)."""

from distkeras_tpu.parallel.distributed import (  # noqa: F401
    ADAG, AEASGD, DOWNPOUR, AveragingTrainer, DistributedTrainer, DynSGD,
    EASGD)
from distkeras_tpu.parallel.mesh import make_mesh, make_mesh_2d  # noqa: F401
from distkeras_tpu.parallel.trainers import (  # noqa: F401
    EnsembleTrainer, SingleTrainer, Trainer)
from distkeras_tpu.parallel.async_host import HostAsyncTrainer  # noqa: F401
from distkeras_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules, named_shardings, param_specs, shard_params)
from distkeras_tpu.parallel.spmd import SPMDTrainer  # noqa: F401
from distkeras_tpu.parallel.pipeline import (  # noqa: F401
    PipelinedLM, PipelineTrainer, init_stacked_blocks, make_pipeline_fn)
from distkeras_tpu.parallel.parameter_servers import (  # noqa: F401
    ADAGParameterServer, DeltaParameterServer, DynSGDParameterServer,
    EASGDParameterServer, ParameterServer, PSClient)
