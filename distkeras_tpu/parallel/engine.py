"""The SPMD distributed-training engine: staggered-window workers + a
replicated center, all inside one jitted ``shard_map``.

This module is the TPU-native replacement for the reference's entire
distributed runtime — the Spark executor loop (``distkeras/workers.py``),
the socket parameter server (``distkeras/parameter_servers.py``) and the
pickled-TCP wire protocol (``distkeras/networking.py``) collapse into a
single compiled program over a device mesh (SURVEY §5.8: the north star is
zero socket-PS traffic, all comms via ICI collectives).

Mapping of reference concepts:

  reference (Spark + socket PS)            here (SPMD mesh)
  ---------------------------------------  --------------------------------
  Spark executor running Worker.train      mesh position along ``workers``
  per-worker minibatch loop                ``lax.scan`` over micro-steps
  PS 'pull' (TCP round-trip)               read of the replicated center
  PS 'commit' (TCP round-trip)             masked ``psum`` over ICI
  communication_window local steps         commit mask every K micro-steps
  PS mutex / commit serialization          staggered per-worker offsets so
                                           commits interleave like async
                                           arrivals (at most ~1/step)
  PS state (center weights, num_updates)   replicated pytrees in the carry

Async semantics on a synchronous mesh (SURVEY §7 "hard parts" (a)): true
async PS arrival order is modeled by giving each worker a commit *phase
offset* within its window. Worker i commits at global micro-steps t where
``(t + 1 + offset_i) % K_i == 0``. With offsets spread uniformly, commits
serialize through the (replicated) center exactly like the reference PS
serialized them through its mutex — a DynSGD worker therefore observes the
same staleness profile (center advanced by ~n-1 foreign commits per window)
as it would against the socket PS. Setting all offsets to 0 recovers the
synchronous barrier-round algorithms (EASGD, averaging).

Everything — local steps, masked collectives, server updates — runs inside
one ``lax.scan`` under ``shard_map`` under ``jit``: per epoch there is ONE
Python dispatch, and XLA overlaps the per-window psum with local compute
where the schedule allows.

Communication amortization (the whole point of ``communication_window``,
SURVEY §2.3): with a uniform window K the epoch compiles to a TWO-LEVEL
scan — outer over ``S // K`` window blocks, inner over K purely-local
steps with ZERO collectives — so a param-sized ``psum`` crosses the ICI
exactly ``ceil(S / K)`` times per epoch, not S times. Per-worker async
staggering survives the restructure: worker i snapshots its params into a
carried buffer at its phase step ``(K - 1 - offset_i) mod K`` inside each
block (a masked select, no comms), the boundary collective commits the
*snapshot*'s contribution, and a tail-carry
``params := post_commit + (params_now - snapshot)`` preserves the local
steps the worker took after its snapshot. For synchronous algorithms
(offsets = 0) the snapshot is the final step of the block, so when K
divides the epoch length the program is step-for-step equivalent to the
per-step path (tail = 0). Deliberate semantic differences from the
per-step path: window phase resets at each epoch (the per-step path's
global step counter carries it across), and a remainder block (S % K
steps) TRUNCATES the final window — every worker commits its residual at
the epoch boundary, like the reference worker committing when its
partition iterator ends. Heterogeneous per-worker windows (DynSGD's K_i
lists) and non-amortizable algorithms (DynSGD's staleness counter, ADAG's
nonlinear accumulator) fall back to the per-step masked path, where
fine-grained commit serialization is the point.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.compat import shard_map
from distkeras_tpu.ops.optimizers import Optimizer
from distkeras_tpu.parallel.worker import (  # noqa: F401  (re-export)
    TrainCarry, make_train_step, shard_epoch_data)

Pytree = Any


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def host_fetch(tree: Pytree) -> Pytree:
    """``device_get`` that also works under multi-process ``jax.distributed``
    (deploy.Job): leaves whose shards live on other hosts are allgathered to
    every process (DCN), replicated/addressable leaves fetch directly.

    This is THE sanctioned blocking fetch point of the epoch-loop
    modules (tools/lint_host_sync.py): loops route device->host reads
    through here (or ``jax.device_get`` at an allow-marked boundary
    site), never ad hoc mid-step."""
    if jax.process_count() == 1:
        return jax.device_get(tree)  # lint: allow-host-sync (the owner)
    from jax.experimental import multihost_utils

    def fetch(x):
        if not isinstance(x, jax.Array):
            return np.asarray(x)
        if x.is_fully_addressable:
            return np.asarray(jax.device_get(x))  # lint: allow-host-sync
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    return _tmap(fetch, tree)


def host_async(tree: Pytree) -> Pytree:
    """Start device->host transfers for every addressable device leaf
    WITHOUT blocking (overlap PR): the epoch loops call this on per-step
    loss/metric arrays right after dispatching the epoch program, so by
    the time the epoch-boundary ``host_fetch`` runs, the copies are
    already on (or through) the wire — the boundary fetch stops costing
    one full D2H round trip per accumulated array. Returns ``tree``
    unchanged (device leaves stay device-resident)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and leaf.is_fully_addressable:
            try:
                leaf.copy_to_host_async()
            except Exception:  # lint: allow-swallow — a backend without
                pass           # async D2H just fetches at the boundary
    return tree


def _select(mask, a, b):
    """Pytree-wise ``where(mask, a, b)`` with a scalar bool mask."""
    return _tmap(lambda x, y: jnp.where(mask, x, y), a, b)


# ---------------------------------------------------------------------------
# Algorithm plug-ins (the reference's ParameterServer subclasses, SURVEY §2.1)
# ---------------------------------------------------------------------------

class DistAlgorithm:
    """Commit/serve behavior of one distributed SGD variant.

    Roles map onto the reference's split: ``contrib``/``worker_post`` are the
    worker-side commit protocol (``workers.py :: *Worker.train`` window
    body), ``server_update`` is the PS-side handler
    (``parameter_servers.py :: *ParameterServer.handle_commit``).
    """

    #: async emulation (staggered offsets) vs synchronous barrier rounds
    staggered: bool = True
    #: whether workers track a pull-time snapshot of the center
    needs_pull: bool = False
    #: False: the algorithm's semantics need per-commit serialization
    #: through the center (e.g. DynSGD's staleness counter, which is what
    #: keeps its full-scale deltas stable) — the engine then uses the
    #: per-step masked path even for uniform windows
    amortizable: bool = True

    def init_server(self, params: Pytree) -> Dict[str, Pytree]:
        return {}

    def init_worker_extras(self, num_workers: int) -> Dict[str, jnp.ndarray]:
        return {}

    def contrib(self, w_params, pull, center, server, extras) -> Pytree:
        """Per-worker commit payload (pre-masking), e.g. a delta or an
        elastic difference."""
        raise NotImplementedError

    def server_update(self, center, server, total, n_commits
                      ) -> Tuple[Pytree, Dict]:
        """Apply the psum of masked contributions to the center."""
        raise NotImplementedError

    def worker_post(self, w_params, pull, contrib, new_center, new_server,
                    extras, mask) -> Tuple[Pytree, Pytree, Dict]:
        """Worker-side effect of its own commit (pull fresh center, subtract
        elastic term, record clock, ...). Applied only where ``mask``."""
        return w_params, pull, extras

    def finalize(self, center, workers_stacked, pulls_stacked,
                 num_workers: int) -> Pytree:
        """Host-side flush after the last epoch (uncommitted residual)."""
        return center


@dataclass
class DownpourAlgo(DistAlgorithm):
    """DOWNPOUR (Dean et al. 2012): workers accumulate K local steps, commit
    the accumulated delta, pull a fresh center.

    Reference: ``workers.py :: DOWNPOURWorker`` + ``parameter_servers.py ::
    DeltaParameterServer`` (``handle_commit``: ``center += delta``).
    ``commit_scale`` scales committed deltas (1.0 = the reference's naive
    sum; 1/n tames the effective learning rate when many workers commit).
    """
    commit_scale: float = 1.0
    staggered: bool = True
    needs_pull: bool = True

    def contrib(self, w_params, pull, center, server, extras):
        return _tmap(lambda x, p: (x - p) * self.commit_scale, w_params, pull)

    def server_update(self, center, server, total, n_commits):
        return _tmap(jnp.add, center, total), server

    def worker_post(self, w_params, pull, contrib, new_center, new_server,
                    extras, mask):
        return (_select(mask, new_center, w_params),
                _select(mask, new_center, pull), extras)

    def finalize(self, center, workers, pulls, n):
        # flush each worker's uncommitted delta into the center
        resid = _tmap(lambda w, p: (w - p).sum(axis=0) * self.commit_scale,
                      workers, pulls)
        return _tmap(jnp.add, center, resid)


@dataclass
class ElasticAlgo(DistAlgorithm):
    """EASGD family (Zhang et al. 2015). Elastic difference
    ``e_i = alpha * (x_i - center)`` pulls worker and center toward each
    other: worker does ``x_i -= e_i``, center accumulates ``+e_i``.

    Reference: ``workers.py :: EASGDWorker/AEASGDWorker`` (elastic symmetric
    force, ``alpha = learning_rate * rho``) + the EASGD parameter servers.
    ``synchronous=True`` = barrier rounds (EASGD); ``False`` = staggered
    async emulation (AEASGD).

    ``center_mode``: 'sum' is the paper/reference update
    (``center += sum_i e_i`` — requires ``n * alpha < 1`` for stability);
    'mean' divides by the number of committers that step, stable for any n.
    """
    alpha: float = 0.1
    synchronous: bool = False
    center_mode: str = "sum"
    needs_pull: bool = False

    def __post_init__(self):
        self.staggered = not self.synchronous

    def contrib(self, w_params, pull, center, server, extras):
        return _tmap(lambda x, c: self.alpha * (x - c), w_params, center)

    def server_update(self, center, server, total, n_commits):
        if self.center_mode == "mean":
            denom = jnp.maximum(n_commits, 1.0)
            total = _tmap(lambda t: t / denom, total)
        return _tmap(jnp.add, center, total), server

    def worker_post(self, w_params, pull, contrib, new_center, new_server,
                    extras, mask):
        new_params = _tmap(lambda x, e: x - jnp.where(mask, e, 0.0),
                           w_params, contrib)
        return new_params, pull, extras


@dataclass
class AdagAlgo(DistAlgorithm):
    """ADAG — adaptive per-parameter accumulation on the server.

    Reference: ``parameter_servers.py :: ADAGParameterServer`` keeps a
    per-parameter accumulator over committed deltas (SURVEY §2.1). Concrete
    server rule used here (Adagrad applied to commits; re-verify the exact
    reference formula once the mount is populated):
        acc    += delta^2
        center += adag_lr * delta / (sqrt(acc) + eps)

    Not amortizable: the accumulator is nonlinear in the commits —
    batching a window's n contributions into one server round squares the
    SUM ((Σδ)² ≠ Σδ², cross terms) and divides by sqrt(acc) once instead
    of n serialized times. Like DynSGD, the per-step path's one-at-a-time
    commit ordering IS the algorithm.
    """
    adag_lr: float = 0.05
    epsilon: float = 1e-8
    commit_scale: float = 1.0
    staggered: bool = True
    needs_pull: bool = True
    amortizable: bool = False

    def init_server(self, params):
        return {"acc": _tmap(jnp.zeros_like, params)}

    def contrib(self, w_params, pull, center, server, extras):
        return _tmap(lambda x, p: (x - p) * self.commit_scale, w_params, pull)

    def server_update(self, center, server, total, n_commits):
        acc = _tmap(lambda a, t: a + jnp.square(t), server["acc"], total)
        center = _tmap(
            lambda c, t, a: c + self.adag_lr * t /
            (jnp.sqrt(a) + self.epsilon),
            center, total, acc)
        return center, {"acc": acc}

    def worker_post(self, w_params, pull, contrib, new_center, new_server,
                    extras, mask):
        return (_select(mask, new_center, w_params),
                _select(mask, new_center, pull), extras)


@dataclass
class DynSGDAlgo(DistAlgorithm):
    """DynSGD — staleness-aware delta scaling (Hermans).

    Reference: ``parameter_servers.py :: DynSGDParameterServer`` scales each
    commit by 1/staleness, where staleness = center updates since the
    worker's last pull (SURVEY §3.3). Server clock = ``num_updates``; each
    worker carries its last-pull clock value; commit applies
    ``delta / max(1, clock - last_pull + 1)``.

    Not amortizable: batching a round's commits makes every worker's
    staleness 1 (all pulled at the same boundary), so the 1/staleness
    damping that keeps the full-scale deltas stable vanishes — staleness
    only exists when commits serialize through the center one at a time.
    """
    staggered: bool = True
    needs_pull: bool = True
    amortizable: bool = False

    def init_server(self, params):
        return {"clock": jnp.zeros((), jnp.int32)}

    def init_worker_extras(self, num_workers):
        return {"last_pull": jnp.zeros((num_workers,), jnp.int32)}

    def contrib(self, w_params, pull, center, server, extras):
        staleness = jnp.maximum(
            1, server["clock"] - extras["last_pull"] + 1).astype(jnp.float32)
        return _tmap(lambda x, p: (x - p) / staleness, w_params, pull)

    def server_update(self, center, server, total, n_commits):
        clock = server["clock"] + n_commits.astype(jnp.int32)
        return _tmap(jnp.add, center, total), {"clock": clock}

    def worker_post(self, w_params, pull, contrib, new_center, new_server,
                    extras, mask):
        extras = {"last_pull": jnp.where(mask, new_server["clock"],
                                         extras["last_pull"])}
        return (_select(mask, new_center, w_params),
                _select(mask, new_center, pull), extras)


@dataclass
class AveragingAlgo(DistAlgorithm):
    """Per-round weight averaging: center := mean of worker params; workers
    restart from the average.

    Reference: ``trainers.py :: AveragingTrainer`` (per-epoch averaging of
    independently trained replicas). Here the round length is the commit
    window (set to steps-per-epoch by the trainer for exact parity).
    """
    staggered = False
    needs_pull = False

    def contrib(self, w_params, pull, center, server, extras):
        return w_params

    def server_update(self, center, server, total, n_commits):
        denom = jnp.maximum(n_commits, 1.0)
        avg = _tmap(lambda t: t / denom, total)
        committed = n_commits > 0
        return _select(committed, avg, center), server

    def worker_post(self, w_params, pull, contrib, new_center, new_server,
                    extras, mask):
        return _select(mask, new_center, w_params), pull, extras

    def finalize(self, center, workers, pulls, n):
        return _tmap(lambda w: w.mean(axis=0), workers)


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

@dataclass
class EngineConfig:
    num_workers: int
    window: Union[int, Sequence[int]]  # K, scalar or per-worker
    axis_name: str = "workers"
    #: None = auto (two-level amortized scan when the window is uniform,
    #: per-step masked path otherwise). False forces the per-step path —
    #: kept for heterogeneous windows and for equivalence testing.
    amortized: Optional[bool] = None


class DistributedEngine:
    """Compiles and runs the per-epoch SPMD program for one algorithm."""

    def __init__(self, module, loss_fn: Callable, optimizer: Optimizer,
                 algo: DistAlgorithm, mesh: Mesh, config: EngineConfig,
                 metric_fns: Optional[Dict[str, Callable]] = None,
                 param_mask=None, state_mask=None):
        self.param_mask = param_mask  # Keras-style layer freezing
        self.state_mask = state_mask
        self.module = module
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.algo = algo
        self.mesh = mesh
        self.config = config
        self.metric_fns = metric_fns

        n = config.num_workers
        K = config.window
        Ks = np.full((n,), K, np.int32) if np.isscalar(K) \
            else np.asarray(K, np.int32)
        if Ks.shape != (n,):
            raise ValueError(f"window must be scalar or length-{n}")
        if algo.staggered:
            offsets = (np.arange(n) * Ks) // n
        else:
            offsets = np.zeros((n,), np.int32)
        self._Ks = jnp.asarray(Ks)
        self._offsets = jnp.asarray(offsets % np.maximum(Ks, 1))
        uniform = bool((Ks == Ks[0]).all())
        if config.amortized and not uniform:
            raise ValueError(
                "amortized=True requires a uniform window; per-worker "
                f"windows {Ks.tolist()} need the per-step path")
        if config.amortized and not algo.amortizable:
            raise ValueError(
                f"{type(algo).__name__} is not amortizable (needs "
                "per-commit serialization through the center)")
        self.amortized = (uniform and algo.amortizable) \
            if config.amortized is None else bool(config.amortized)
        if (config.amortized is None and self.amortized
                and bool((np.asarray(offsets) != 0).any())):
            # auto-amortization changes staggered-async trajectories:
            # in-window commits are no longer serialized — all workers
            # commit at block boundaries. Opt out with amortized=False.
            import warnings
            warnings.warn(
                "amortized two-level scan auto-enabled with nonzero "
                "stagger offsets: commit interleaving differs from the "
                "per-step path (same fixed point, different trajectory); "
                "pass amortized=False to reproduce per-step numerics",
                stacklevel=3)
        self._uniform_K = int(Ks[0]) if uniform else None
        self._epoch_fn = None  # built lazily (jitted shard_map)
        self._reset_fn = None  # built lazily (parallelism_factor > 1)
        self._recompile = None  # obs detector, bound in _build()
        self._warm_marked = False

    # -- state ------------------------------------------------------------
    def init_state(self, params: Pytree, model_state: Pytree,
                   rng: jax.Array) -> Dict:
        """Build the replicated-center + stacked-worker state pytree."""
        n = self.config.num_workers
        stack = lambda tree: _tmap(
            lambda x: jnp.broadcast_to(x, (n,) + x.shape), tree)
        worker = {
            "params": stack(params),
            "state": stack(model_state),
            "opt": jax.vmap(self.optimizer.init)(stack(params)),
            "rng": jax.random.split(rng, n),
            "pull": stack(params) if self.algo.needs_pull else {},
            "extras": self.algo.init_worker_extras(n),
        }
        server = {
            "aux": self.algo.init_server(params),
            "t": jnp.zeros((), jnp.int32),  # global micro-step counter
        }
        return {"worker": worker,
                "center": {"params": params, "state": model_state},
                "server": server}

    def reset_workers(self, state: Dict) -> Dict:
        """Re-initialize every worker from the CURRENT center: params,
        pull snapshot, optimizer state, and algorithm extras reset; the
        center, server aux, global step counter, and worker rng streams
        carry on.

        This is the reference's task boundary (``workers.py``: each Spark
        partition builds a fresh Keras model + optimizer from the
        serialized center) — used by ``parallelism_factor > 1``, where an
        epoch is ``num_workers x factor`` partitions and each worker
        consumes ``factor`` of them sequentially."""
        if self._reset_fn is None:
            n = self.config.num_workers

            @partial(jax.jit, out_shardings=self.shardings())
            def _reset(state):
                center = state["center"]["params"]
                stack = lambda tree: _tmap(
                    lambda x: jnp.broadcast_to(x, (n,) + x.shape), tree)
                worker = dict(state["worker"])
                worker["params"] = stack(center)
                worker["opt"] = jax.vmap(self.optimizer.init)(stack(center))
                if self.algo.needs_pull:
                    worker["pull"] = stack(center)
                worker["extras"] = self.algo.init_worker_extras(n)
                return {**state, "worker": worker}

            self._reset_fn = _reset
        return self._reset_fn(state)

    def shardings(self) -> Dict:
        """NamedShardings matching ``init_state`` for explicit device_put."""
        ws = NamedSharding(self.mesh, P(self.config.axis_name))
        rs = NamedSharding(self.mesh, P())
        return {"worker": ws, "center": rs, "server": rs}

    # -- compiled epoch ---------------------------------------------------
    def _build(self):
        inner = self._make_inner_amortized() if self.amortized \
            else self._make_inner_perstep()
        axis = self.config.axis_name
        state_specs = {"worker": P(axis), "center": P(), "server": P()}
        mapped = shard_map(
            inner, mesh=self.mesh,
            in_specs=(state_specs, P(None, axis), P(None, axis)),
            out_specs=(state_specs, P(None, axis)),
            check_vma=False)
        self._epoch_fn = jax.jit(mapped, donate_argnums=(0,))
        # detector bound HERE, with the function it watches — callers
        # (and tests) invoke _build() directly, so run_epoch cannot
        # assume it created the epoch fn itself
        from distkeras_tpu import obs
        self._recompile = obs.RecompileDetector()
        self._recompile.watch("engine.epoch", self._epoch_fn)

    def _make_inner_amortized(self):
        """Two-level epoch program: a param-sized collective once per
        window block (``ceil(S/K)`` per epoch), never per micro-step."""
        axis = self.config.axis_name
        train_step = make_train_step(self.module, self.loss_fn,
                                     self.optimizer, self.metric_fns,
                                     param_mask=self.param_mask,
                                     state_mask=self.state_mask)
        algo = self.algo
        K = self._uniform_K
        offsets = self._offsets

        def inner(state, X, Y):
            w = _tmap(lambda a: a[0], state["worker"])
            center = state["center"]
            server_aux = state["server"]["aux"]
            gt0 = state["server"]["t"]
            widx = lax.axis_index(axis)
            # local step within a block at which this worker's commit
            # snapshot is taken: solves (lt + 1 + offset) % K == 0
            snap_step = (K - 1 - offsets[widx]) % K

            X0, Y0 = X[:, 0], Y[:, 0]
            S = X0.shape[0]
            nblocks, rem = divmod(S, K)

            def make_local_step(target):
                def local_step(carry, batch):
                    w, snap = carry
                    xb, yb, lt = batch
                    tc = TrainCarry(w["params"], w["state"], w["opt"],
                                    w["rng"])
                    tc, outs = train_step(tc, (xb, yb))
                    w = {**w, "params": tc.params, "state": tc.state,
                         "opt": tc.opt_state, "rng": tc.rng}
                    snap = _select(lt == target, w["params"], snap)
                    return (w, snap), outs
                return local_step

            def commit(w, snap, center, server_aux):
                """One boundary exchange: psum every worker's snapshot
                contribution (all workers commit at every boundary — a
                short remainder block clamps the snapshot to its last
                step), update the center, and re-join each worker with its
                post-snapshot tail."""
                contrib = algo.contrib(snap, w["pull"], center["params"],
                                       server_aux, w["extras"])
                total = lax.psum(contrib, axis)
                n_commits = lax.psum(jnp.float32(1.0), axis)
                new_cparams, new_aux = algo.server_update(
                    center["params"], server_aux, total, n_commits)
                post, new_pull, new_extras = algo.worker_post(
                    snap, w["pull"], contrib, new_cparams, new_aux,
                    w["extras"], jnp.bool_(True))
                # tail-carry: local steps taken after the snapshot survive
                # the commit and fold into the next window's contribution
                new_params = _tmap(lambda q, s, p: q + (p - s),
                                   post, snap, w["params"])
                w = {**w, "params": new_params, "pull": new_pull,
                     "extras": new_extras}
                return w, {**center, "params": new_cparams}, new_aux

            def block(carry, block_data):
                w, center, server_aux = carry
                xb, yb = block_data  # [K, batch, ...]
                (w, snap), outs = lax.scan(
                    make_local_step(snap_step), (w, w["params"]),
                    (xb, yb, jnp.arange(K, dtype=jnp.int32)))
                w, center, server_aux = commit(w, snap, center, server_aux)
                return (w, center, server_aux), outs

            carry = (w, center, server_aux)
            outs_parts = []
            if nblocks:
                Xb = X0[:nblocks * K].reshape((nblocks, K) + X0.shape[1:])
                Yb = Y0[:nblocks * K].reshape((nblocks, K) + Y0.shape[1:])
                carry, outs_b = lax.scan(block, carry, (Xb, Yb))
                # [nblocks, K] per-step scalars -> [nblocks*K]
                outs_parts.append(_tmap(
                    lambda a: a.reshape((nblocks * K,) + a.shape[2:]),
                    outs_b))
            if rem:
                w, center, server_aux = carry
                # the final window TRUNCATES at the epoch boundary (the
                # reference's worker commits its residual when its
                # partition iterator ends): snapshot at the phase step if
                # it fits, else at the block's last step, and every worker
                # commits — a worker whose phase never arrives (K > S sync
                # cases) must not sit out the epoch entirely
                (w, snap), outs_r = lax.scan(
                    make_local_step(jnp.minimum(snap_step, rem - 1)),
                    (w, w["params"]),
                    (X0[nblocks * K:], Y0[nblocks * K:],
                     jnp.arange(rem, dtype=jnp.int32)))
                carry = commit(w, snap, center, server_aux)
                outs_parts.append(outs_r)
            w, center, server_aux = carry
            outs = outs_parts[0] if len(outs_parts) == 1 else _tmap(
                lambda *xs: jnp.concatenate(xs, axis=0), *outs_parts)

            new_state = {
                "worker": _tmap(lambda a: a[None], w),
                "center": center,
                "server": {"aux": server_aux, "t": gt0 + S},
            }
            return new_state, _tmap(lambda a: a[:, None], outs)

        return inner

    def _make_inner_perstep(self):
        """Per-micro-step masked-psum path: exact fine-grained commit
        interleaving, param-sized collective every step. Retained for
        heterogeneous per-worker windows and as the equivalence oracle for
        the amortized program."""
        axis = self.config.axis_name
        train_step = make_train_step(self.module, self.loss_fn,
                                     self.optimizer, self.metric_fns,
                                     param_mask=self.param_mask,
                                     state_mask=self.state_mask)
        algo = self.algo
        Ks, offsets = self._Ks, self._offsets

        def inner(state, X, Y):
            # per-device blocks: worker leaves [1, ...] -> [...]
            w = _tmap(lambda a: a[0], state["worker"])
            center = state["center"]
            server_aux = state["server"]["aux"]
            gt0 = state["server"]["t"]
            widx = lax.axis_index(axis)
            K = Ks[widx]
            offset = offsets[widx]

            def body(carry, batch):
                w, center, server_aux, gt = carry
                xb, yb = batch
                tc = TrainCarry(w["params"], w["state"], w["opt"], w["rng"])
                tc, outs = train_step(tc, (xb, yb))
                w = {**w, "params": tc.params, "state": tc.state,
                     "opt": tc.opt_state, "rng": tc.rng}

                mask = ((gt + 1 + offset) % jnp.maximum(K, 1)) == 0
                maskf = mask.astype(jnp.float32)
                contrib = algo.contrib(w["params"], w["pull"],
                                       center["params"], server_aux,
                                       w["extras"])
                masked = _tmap(lambda c: c * maskf, contrib)
                total = lax.psum(masked, axis)
                n_commits = lax.psum(maskf, axis)
                new_cparams, new_aux = algo.server_update(
                    center["params"], server_aux, total, n_commits)
                new_params, new_pull, new_extras = algo.worker_post(
                    w["params"], w["pull"], contrib, new_cparams, new_aux,
                    w["extras"], mask)
                w = {**w, "params": new_params, "pull": new_pull,
                     "extras": new_extras}
                center2 = {**center, "params": new_cparams}
                return (w, center2, new_aux, gt + 1), outs

            (w, center, server_aux, gt), outs = lax.scan(
                body, (w, center, server_aux, gt0), (X[:, 0], Y[:, 0]))

            new_state = {
                "worker": _tmap(lambda a: a[None], w),
                "center": center,
                "server": {"aux": server_aux, "t": gt},
            }
            # per-step scalars ([S] loss, and metric values when enabled)
            # gain the worker axis back: [S] -> [S, 1]
            return new_state, _tmap(lambda a: a[:, None], outs)

        return inner

    def run_epoch(self, state: Dict, Xs, Ys):
        """Run S micro-steps. ``Xs``/``Ys``: ``[S, W, batch, ...]``."""
        from distkeras_tpu import obs
        if self._epoch_fn is None:
            self._build()
        with obs.span("engine.epoch"):
            out = self._epoch_fn(state, Xs, Ys)
        # the epoch program compiles ONCE per engine by design (static
        # shapes): after the first call's legitimate compile, any cache
        # growth is a shape leak
        if self._warm_marked:
            self._recompile.check()
        else:
            self._recompile.mark_warm("engine.epoch")
            self._warm_marked = True
        return out

    # -- final model ------------------------------------------------------
    def extract_model(self, state: Dict) -> Tuple[Pytree, Pytree]:
        """Final (params, model_state): algorithm-flushed center params +
        worker-averaged model state (BN stats etc.)."""
        host = host_fetch(state)
        center = self.algo.finalize(
            host["center"]["params"], host["worker"]["params"],
            host["worker"]["pull"], self.config.num_workers)
        # float leaves (BN stats) average over workers; integer leaves
        # (step counters) keep worker 0's value — averaging would silently
        # turn them into float64
        mstate = _tmap(
            lambda s: s.mean(axis=0)
            if (hasattr(s, "dtype") and np.issubdtype(s.dtype, np.floating))
            else (s[0] if hasattr(s, "__getitem__") else s),
            host["worker"]["state"])
        return center, mstate


