"""Continuous-batching serving engine: iteration-level scheduling over
``generate()``'s prefill/decode machinery, on a paged KV cache.

The single-call ``generate()`` path decodes one fixed batch to
completion: a straggler request holds every batch row until
``max_new_tokens``, and new arrivals wait for the whole batch to drain.
This package is the Orca/vLLM-style fix — the missing layer between the
per-step decode kernels and an actual serving workload:

    kv_pool.py     ``PagedKVPool`` — fixed pool of per-layer KV pages,
                   per-slot page tables, refcounted on-demand
                   allocation, and (``host_pages=``) the HOST offload
                   tier: async D2H/H2D page copies that turn
                   preemption into a swap and multiply prefix-cache
                   capacity — plus ``PrefixCache`` (hash-consed
                   shared prompt prefixes, copy-on-write partial
                   pages, spill-to-host eviction) and the legacy
                   slab ``KVPool``
    scheduler.py   admission queue + per-request state machine
                   (queued -> prefilling -> decoding -> finished) with
                   slot allocation/release; ``PriorityScheduler`` adds
                   priority classes and preemption back to the queue
    engine.py      the slot-based decode loop: ONE compiled
                   ``decode_step_slots_paged`` over all slots per
                   iteration (static shapes, the page table is a
                   traced argument, jit compiled once; on TPU the
                   readout is the ``ops.paged_attention`` page-table
                   Pallas kernel — ``decode_kernel=``), chunked
                   prefill interleaved between decode iterations with
                   shared prefixes skipped, page-budget admission and
                   preemption/resume (a page SWAP through the host
                   tier when ``host_kv_pages=`` is set, a recompute
                   prefill otherwise), per-slot sampling state; MoE
                   models decode through the drop-free dispatched
                   path (optionally shard_map expert-parallel over
                   ``ep_mesh``) with expert-load telemetry and a
                   routing-concentration admission cost
    speculation.py ``DraftSource`` draft proposers for speculative
                   decoding — ``NgramDraft`` (prompt-lookup
                   self-drafting, zero extra weights) and
                   ``DraftModel`` (a small LM with its own paged KV) —
                   verified k-at-a-time by one batched target pass
                   (``models.decoding.verify_step_slots[_paged]``),
                   linearly or as per-slot token TREES
                   (``propose_tree`` + the ancestor-mask window,
                   ``ServingEngine(spec_tree=)``)
    metrics.py     TTFT, TPOT, request latency, queue depth, slot
                   occupancy, tokens/s, page-budget gauges and
                   prefix-cache hit rates — the numbers ``bench.py
                   --model serving`` records; request-level timelines,
                   the flight-recorder ring and declarative SLOs live
                   in ``distkeras_tpu.obs`` (tracing/recorder/slo) and
                   are wired through the engine
    loadgen.py     production-shaped traffic: seeded phased arrivals
                   (diurnal ramps, bursts, flash crowds), heavy-tail
                   lengths, template/tenant mixes — synthesized into a
                   replayable JSONL ``Trace`` and driven open-loop
                   through an engine or router fleet on the iteration
                   clock (deterministic; ``obs.report`` turns the
                   result into the per-phase scenario SLO report)
    router/        the horizontal tier: N engine replicas behind a
                   prefix-affinity/least-loaded ``Router`` with
                   lifecycle-managed ``EngineReplica``s, disaggregated
                   prefill/decode pools (handoff = the engine's
                   ``transfer_out``/``transfer_in`` re-entry path),
                   replica-death mass failover, the elastic
                   ``add_replica``/``remove_replica`` surface, an
                   ``SLOBurnController`` drain loop and the
                   ``AutoscaleController`` closed-loop fleet sizer

See ``docs/serving.md`` for the architecture, the paged-KV design,
the scheduling policy and the router tier.
"""

from distkeras_tpu.serving.engine import (DegradedRequest,  # noqa: F401
                                          ServingEngine)
from distkeras_tpu.serving.loadgen import (ChaosSpec,  # noqa: F401
                                           IterationClock,
                                           PhaseSpec, PhaseResult,
                                           ReplayResult, TenantSpec,
                                           Trace, TraceRequest,
                                           WorkloadSpec,
                                           diurnal_burst_scenario,
                                           flash_crowd_chaos_scenario,
                                           replay, synthesize)
from distkeras_tpu.serving.kv_pool import (KVPool,  # noqa: F401
                                           PagedKVPool, PrefixCache)
from distkeras_tpu.serving.metrics import ServingMetrics  # noqa: F401
from distkeras_tpu.serving.scheduler import (AdmissionRejected,  # noqa: F401
                                             FIFOScheduler,
                                             PriorityScheduler, Request,
                                             RequestState, TERMINAL_STATES)
from distkeras_tpu.serving.speculation import (DraftModel,  # noqa: F401
                                               DraftSource, NgramDraft)
from distkeras_tpu.serving.router import (AutoscaleController,  # noqa: F401
                                          ControllerChain,
                                          EngineReplica,
                                          LeastLoaded, PlacementPolicy,
                                          PrefixAffinity, ReplicaDead,
                                          ReplicaState,
                                          ReplicaUnavailable, Router,
                                          RouterClient,
                                          SLOBurnController)
