"""Continuous-batching serving engine (this PR): iteration-level
scheduling over ``generate()``'s prefill/decode machinery.

The single-call ``generate()`` path decodes one fixed batch to
completion: a straggler request holds every batch row until
``max_new_tokens``, and new arrivals wait for the whole batch to drain.
This package is the Orca/vLLM-style fix — the missing layer between the
per-step decode kernels and an actual serving workload:

    kv_pool.py     pooled ``[S, max_len]`` KV cache, resident across
                   requests; batch-1 prefill caches insert into a slot
    scheduler.py   FIFO admission queue + per-request state machine
                   (queued -> prefilling -> decoding -> finished) with
                   slot allocation/release
    engine.py      the slot-based decode loop: ONE compiled
                   ``decode_step_slots`` over all slots per iteration
                   (static shapes, jit compiled once), chunked prefill
                   interleaved between decode iterations, per-slot
                   sampling state
    metrics.py     TTFT, TPOT, request latency, queue depth, slot
                   occupancy, tokens/s — the numbers ``bench.py
                   --model serving`` records; request-level timelines,
                   the flight-recorder ring and declarative SLOs live
                   in ``distkeras_tpu.obs`` (tracing/recorder/slo) and
                   are wired through the engine

See ``docs/serving.md`` for the architecture and scheduling policy.
"""

from distkeras_tpu.serving.engine import (DegradedRequest,  # noqa: F401
                                          ServingEngine)
from distkeras_tpu.serving.kv_pool import KVPool  # noqa: F401
from distkeras_tpu.serving.metrics import ServingMetrics  # noqa: F401
from distkeras_tpu.serving.scheduler import (AdmissionRejected,  # noqa: F401
                                             FIFOScheduler, Request,
                                             RequestState, TERMINAL_STATES)
