"""Slot-based continuous-batching engine over the LM decode path.

The Orca/vLLM iteration-level serving pattern on this repo's
prefill/decode machinery:

  * A fixed pool of ``S`` KV-cache slots stays resident on device
    (``kv_pool.KVPool``); every iteration runs ONE compiled
    ``decode_step_slots`` over ALL slots — shapes are static, the jit
    compiles once per engine per sampler variant (argmax-only for
    all-greedy batches, the full per-slot sampler for mixed ones), and
    requests at different sequence positions coexist because ``t`` is
    a per-slot vector.
  * Requests admit FIFO into free slots; a new request's prompt
    prefills into a batch-1 staging cache — chunked
    (``prefill_chunk``), one chunk per engine iteration, interleaved
    between decode steps so a long prompt never stalls in-flight
    streams — then the filled rows INSERT into the request's pool slot
    and it joins the decode batch.
  * Per-slot sampling state (temperature / top_k / top_p / stop_token
    vectors through ``_sample_vec``, per-slot PRNG keys) lets greedy
    and sampled requests with different stop tokens share one batch.
  * ``ServingMetrics`` records TTFT, TPOT, request latency, queue
    depth, slot occupancy and the per-iteration decode rate; the
    request-level layer rides along — per-request timelines
    (``obs.tracing``, Chrome-trace exportable), a flight-recorder ring
    of recent iterations (``obs.recorder``, auto-dumped on failures)
    and declarative SLOs (``obs.slo``) reported by ``health()``.

Greedy outputs are token-identical per request to a standalone
``generate()`` call on the same prompt (the oracle contract:
``tests/test_serving.py``): prefill runs the very same ``prefill`` /
``prefill_chunk_step`` programs at batch 1, and the per-slot decode
step is the same storage-dtype einsum attention with a per-slot mask.

Deliberate scope (docs/serving.md spells out the follow-ups): the
decode loop syncs next-token ids to the host every iteration (the
scheduler needs them for stop detection) — on-device stop handling and
cache-buffer donation are TPU-latency follow-ups; weight trees support
``weights_dtype="auto"``-style pre-casting but not int8; prompts longer
than ``max_len - max_new_tokens`` are rejected at submit.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.obs.recorder import resolve_recorder
from distkeras_tpu.obs.slo import SLOEngine
from distkeras_tpu.obs.tracing import resolve_tracer
from distkeras_tpu.models.core import Model, Sequential
from distkeras_tpu.models.decoding import (_attn_compute_dtype,
                                           _resolve_head_dims,
                                           _sample_vec, _serving_params,
                                           decode_step_slots, prefill,
                                           prefill_chunk_step)
from distkeras_tpu.resilience import faults
from distkeras_tpu.serving.kv_pool import KVPool
from distkeras_tpu.serving.metrics import ServingMetrics
from distkeras_tpu.serving.scheduler import (AdmissionRejected,
                                             FIFOScheduler, Request,
                                             RequestState,
                                             TERMINAL_STATES)


class DegradedRequest(RuntimeError):
    """``run()`` drained a request that did NOT finish normally
    (TIMED_OUT / CANCELLED). Raised by default so a degraded result can
    never masquerade as a complete one in ``run()``'s plain
    ``{rid: tokens}`` return; the terminal ``Request`` (state, partial
    tokens, ``error`` cause) rides on ``.request``."""

    def __init__(self, request: Request):
        cause = (f": {request.error!r}" if request.error is not None
                 else "")
        super().__init__(
            f"request {request.rid} ended {request.state.value}{cause} "
            "— drive with step() to observe terminal states, or "
            "run(on_degraded='return') to accept partial tokens")
        self.request = request


class ServingEngine:
    """Continuous-batching serving over one ``zoo.transformer_lm``-shaped
    model. ``submit()`` enqueues requests; ``step()`` advances the world
    one scheduler iteration; ``run()`` drains to completion (the
    synchronous driver — an async transport wraps these two calls).

    ``max_len`` is the per-slot cache capacity: every request needs
    ``len(prompt) + max_new_tokens <= max_len``.
    """

    def __init__(self, model: Model, *, num_slots: int = 4,
                 max_len: int = 256,
                 prefill_chunk: Optional[int] = None,
                 cache_dtype=None, weights_dtype="auto",
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: Optional[int] = None,
                 tracer=None, slo=None):
        module = model.module
        if not isinstance(module, Sequential):
            raise TypeError("ServingEngine expects a Sequential LM "
                            f"(got {type(module).__name__})")
        self.model = model
        self.module = module
        _resolve_head_dims(module, model.params)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk

        compute_dt = _attn_compute_dtype(module)
        if cache_dtype is None:
            cache_dtype = (compute_dt if compute_dt is not None
                           else jnp.float32)
        # same "auto" weight policy as generate(): pre-cast matrix
        # weights to the compute dtype once (free for bf16 models, a
        # no-op for f32); int8 weight serving is a documented non-goal
        # of this engine revision
        if weights_dtype == "auto":
            weights_dtype = compute_dt if (
                compute_dt is not None
                and compute_dt != jnp.dtype(jnp.float32)) else None
        self._params = (model.params if weights_dtype is None
                        else _serving_params(model.params, weights_dtype))
        self._state = model.state

        self.pool = KVPool(module, self.num_slots, self.max_len,
                           cache_dtype)
        # ONE reusable batch-1 prefill staging cache: positions past the
        # current prompt hold a previous request's stale entries, which
        # is safe — insert() copies the whole row, and the occupant's
        # decode writes position t before the mask ever admits it
        self._staging = self.pool.make_request_cache()
        # bounded admission (load shedding): submits past max_queue
        # raise AdmissionRejected instead of growing the queue without
        # bound under overload; None keeps the open-queue behavior
        self.scheduler = FIFOScheduler(self.num_slots,
                                       max_queue=max_queue)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # request-level observability (obs.tracing / obs.recorder /
        # obs.slo): the tracer shares the metrics clock so timeline
        # durations and measured latencies are directly comparable;
        # the scheduler records admissions where they happen; the
        # flight recorder is the process-global ring (NULL when obs is
        # disabled); ``slo`` takes an SLOEngine or a sequence of
        # Objectives (evaluated every _SLO_EVAL_EVERY iterations and
        # reported by health())
        self.tracer = resolve_tracer(tracer, clock=self.metrics.clock)
        self.scheduler.tracer = (self.tracer if self.tracer.enabled
                                 else None)
        self.recorder = resolve_recorder()
        if slo is None or isinstance(slo, SLOEngine):
            self.slo = slo
        else:
            self.slo = SLOEngine(list(slo), clock=self.metrics.clock)
        self._requests: Dict[int, Request] = {}
        self._rid = itertools.count()

        # per-slot decode vectors (host mirrors of the traced args)
        s = self.num_slots
        self._tok = np.zeros(s, np.int32)
        #: max_len is the free-slot sentinel: the one-hot cache write
        #: misses every position and the slot's logits are discarded
        self._t = np.full(s, self.max_len, np.int32)
        self._temp = np.zeros(s, np.float32)
        self._topk = np.zeros(s, np.int32)
        self._topp = np.ones(s, np.float32)
        self._keys = np.stack(
            [np.array(jax.random.PRNGKey(0))] * s)       # [S, key]

        self._step_fns = {}                  # greedy_only -> jit
        self._prefill_fns = {}
        self._first_fn = None

        # telemetry: the CURRENT metrics window joins the unified
        # obs.telemetry_snapshot() under "serving" (weakref-bound, so a
        # dropped engine detaches itself); the decode steps — compiled
        # once per sampler variant BY DESIGN — are recompile-watched,
        # catching shape/dtype leaks that would silently recompile the
        # hot loop (checked every _RECOMPILE_CHECK_EVERY iterations)
        self._recompile = obs.RecompileDetector()
        self._warmed = set()                 # decode variants marked warm
        self._iters = 0
        # first live engine owns the plain "serving" name; further
        # engines get a unique suffix instead of silently displacing it
        # (a displaced-then-GC'd registration would otherwise leave the
        # still-alive first engine invisible in the snapshot). The bound
        # method is WeakMethod-held by attach, so the registry never
        # keeps this engine (and its KV pool) alive.
        name = "serving"
        if name in obs.components():
            name = f"serving[{id(self):x}]"
        obs.attach(name, self._telemetry_summary, owner=self)

    #: engine iterations between recompile-detector polls
    _RECOMPILE_CHECK_EVERY = 64
    #: engine iterations between SLO evaluations (when ``slo`` is set)
    _SLO_EVAL_EVERY = 32

    def _telemetry_summary(self):
        """obs.attach provider: the CURRENT metrics window's summary
        (``self.metrics`` is swapped per reporting interval), plus the
        compact per-request timelines and the latest SLO status —
        additive keys on the established component shape."""
        snap = self.metrics.summary()
        if self.tracer.enabled:
            snap["requests"] = self.tracer.summaries()
        if self.slo is not None:
            snap["slo"] = self.slo.status()
        return snap

    # --- request intake ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               stop_token: Optional[int] = None, seed: int = 0,
               deadline_s: Optional[float] = None) -> int:
        """Enqueue one request; returns its id. Sampling defaults match
        ``generate()`` (greedy); ``None`` knobs mean disabled.

        ``deadline_s`` is a submit→finish budget on the engine clock: a
        request still unfinished when it expires is terminated
        ``TIMED_OUT`` at the next ``step()`` (partial tokens kept on the
        returned request). Raises ``AdmissionRejected`` when the engine
        was built with ``max_queue`` and the wait queue is full."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slot capacity "
                f"max_len={self.max_len}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if deadline_s is not None and float(deadline_s) <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}")
        req = Request(
            rid=next(self._rid), prompt=prompt,
            max_new_tokens=max_new_tokens,
            temperature=float(temperature),
            top_k=0 if top_k is None else int(top_k),
            top_p=1.0 if top_p is None else float(top_p),
            stop_token=-1 if stop_token is None else int(stop_token),
            seed=int(seed),
            deadline_s=None if deadline_s is None else float(deadline_s))
        req.rng = jax.random.PRNGKey(req.seed)
        req.submit_t = self.metrics.clock()
        try:
            self.scheduler.submit(req)    # may shed (AdmissionRejected)
        except AdmissionRejected:
            self.metrics.record_rejected()
            self.tracer.on_reject()
            # storm detection lives in the recorder: enough sheds since
            # the last dump auto-snapshot the ring (overload forensics)
            self.recorder.note_rejection(
                rid=req.rid, queue_depth=self.scheduler.queue_depth,
                max_queue=self.scheduler.max_queue)
            raise
        self._requests[req.rid] = req
        self.metrics.record_submit(req.rid)
        self.tracer.on_submit(req.rid, self.scheduler.queue_depth)
        return req.rid

    def __getitem__(self, rid: int) -> Request:
        """IN-FLIGHT request lookup (queued/prefilling/decoding).
        Finished requests are returned by ``step()``/``run()`` and
        evicted from the engine — a long-lived server must not
        accumulate one prompt array per request ever served."""
        return self._requests[rid]

    # --- compiled programs ------------------------------------------------

    def _decode_fn(self, greedy_only: bool):
        """Two compiled step variants, chosen per iteration by the
        host: ALL-GREEDY batches (the common serving default) take a
        pure-argmax step — the vector sampler's rank/nucleus masks cost
        two [S, V] argsorts plus a sort per step that greedy never
        needs, a material tax at real vocab sizes. A mixed batch takes
        the full per-slot sampler; sampled requests only ever decode
        under the mixed variant (their temperature forces it while they
        occupy a slot), so their per-request key streams stay
        schedule-independent."""
        fn = self._step_fns.get(greedy_only)
        if fn is None:
            module = self.module

            if greedy_only:
                @jax.jit
                def fn(params, state, cache, tok, t):
                    logits, cache = decode_step_slots(
                        module, params, state, cache, tok, t)
                    return jnp.argmax(logits, axis=-1), cache
            else:
                @jax.jit
                def fn(params, state, cache, tok, t, temp, topk, topp,
                       keys):
                    logits, cache = decode_step_slots(
                        module, params, state, cache, tok, t)
                    # per-slot key streams: a request's draws depend
                    # only on its own seed, not on which neighbours
                    # share the batch
                    split = jax.vmap(jax.random.split)(keys)
                    nxt = _sample_vec(logits, temp, topk, topp,
                                      split[:, 1])
                    return nxt, cache, split[:, 0]

            self._step_fns[greedy_only] = fn
            self._recompile.watch(
                "serving.decode_greedy" if greedy_only
                else "serving.decode_sampled", fn)
        return fn

    #: prefill-program cache cap: every DISTINCT (q_len, t0, final)
    #: triple is its own XLA program (the final chunk's key differs for
    #: every prompt length, so a varied-length workload compiles one
    #: program per novel length — compilation runs inline in ``step()``
    #: and does stall in-flight streams for that iteration; production
    #: deployments should pre-warm or bucket prompt lengths,
    #: docs/serving.md follow-ups). The LRU cap bounds host memory at
    #: O(cap) retained executables instead of O(distinct lengths).
    MAX_PREFILL_PROGRAMS = 64

    def _prefill_fn(self, q_len: int, t0: int, final: bool):
        """Jitted prefill unit. A whole-prompt chunk (t0=0, final) is
        the SAME one-pass ``prefill`` program ``generate()`` runs, so
        staging caches match generate's bit-for-bit; interior chunks are
        ``prefill_chunk_step``. With a fixed ``prefill_chunk`` the
        interior chunks share ceil(max_len/chunk) programs; the ragged
        FINAL chunk is per-prompt-length (see MAX_PREFILL_PROGRAMS)."""
        key = (q_len, t0, final)
        fn = self._prefill_fns.pop(key, None)
        if fn is None:
            module = self.module
            if t0 == 0 and final:
                def f(params, state, cache, chunk):
                    return prefill(module, params, state, cache, chunk)
            else:
                def f(params, state, cache, chunk):
                    return prefill_chunk_step(module, params, state,
                                              cache, chunk, t0,
                                              final=final)
            fn = jax.jit(f)
        # re-insert at the back: dict order is the LRU order
        self._prefill_fns[key] = fn
        while len(self._prefill_fns) > self.MAX_PREFILL_PROGRAMS:
            self._prefill_fns.pop(next(iter(self._prefill_fns)))
        return fn

    def _sample_first_fn(self):
        """First-token sampler from prefill logits — mirrors generate's
        ``rng, sub = split(rng)`` order so a request's key stream does
        not depend on engine scheduling."""
        if self._first_fn is None:
            @jax.jit
            def f(logits, temp, topk, topp, rng):
                rng, sub = jax.random.split(rng)
                tok = _sample_vec(logits, temp[None], topk[None],
                                  topp[None], sub)
                return tok[0], rng

            self._first_fn = f
        return self._first_fn

    # --- the scheduler iteration ------------------------------------------

    def step(self) -> List[Request]:
        """One iteration: expire deadlines, admit, advance ONE prefill
        chunk, run one decode step over all slots. Returns requests that
        reached a terminal state during this iteration (FINISHED,
        TIMED_OUT or CANCELLED — check ``req.state``).

        Error isolation: an exception while advancing ONE request's
        prefill (a poisoned prompt, an injected ``serving.prefill``
        fault) cancels that request and recycles its slot; in-flight
        decode streams are untouched and keep emitting token-identical
        output. A decode-step error is batch-wide and not attributable
        to one request, so it propagates — but it is raised before any
        engine state mutates, so ``step()`` can simply be called again
        (the failed iteration retries wholesale)."""
        finished: List[Request] = []
        self._expire_deadlines(finished)
        admitted = self.scheduler.admit()

        # flight-recorder ring: this iteration's composition, written
        # BEFORE prefill/decode run so a mid-iteration fault dump
        # contains the failing iteration itself (field assembly gated
        # on a live recorder — the disabled path costs one check)
        if self.recorder.enabled:
            self.recorder.record(
                "serving.iteration", iter=self._iters,
                queue_depth=self.scheduler.queue_depth,
                occupied=self.scheduler.occupied,
                decoding=[r.rid for r in
                          self.scheduler.running.values()],
                prefilling=[r.rid for r in self.scheduler.prefilling],
                admitted=[r.rid for r in admitted])

        req = self.scheduler.next_prefill()
        if req is not None:
            with self.metrics.timer.phase("prefill"), \
                    obs.span("serving.prefill"):
                try:
                    self._advance_prefill(req, finished)
                except Exception as e:
                    self._poison(req, e, finished)

        running = self.scheduler.running
        if running:
            with self.metrics.timer.phase("decode"), \
                    obs.span("serving.decode"):
                self._advance_decode(finished)

        self.metrics.record_iteration(self.scheduler.queue_depth,
                                      self.scheduler.occupied,
                                      self.num_slots)
        self._iters += 1
        if self._iters % self._RECOMPILE_CHECK_EVERY == 0:
            self._recompile.check()
        if self.slo is not None \
                and self._iters % self._SLO_EVAL_EVERY == 0:
            self.slo.evaluate(self.metrics)
        return finished

    def run(self, max_steps: Optional[int] = None,
            on_degraded: str = "raise") -> Dict[int, np.ndarray]:
        """Drive ``step()`` until every submitted request reaches a
        terminal state; returns ``{rid: tokens}`` for requests drained
        during this call.

        A request that ends TIMED_OUT or CANCELLED raises
        ``DegradedRequest`` (default) — its empty/partial token array
        must not be indistinguishable from a finished one in the plain
        tokens dict. Pass ``on_degraded="return"`` to include partial
        tokens instead, or drive ``step()`` directly to observe
        per-request terminal states."""
        if on_degraded not in ("raise", "return"):
            raise ValueError(
                f"on_degraded must be 'raise' or 'return', "
                f"got {on_degraded!r}")
        out: Dict[int, np.ndarray] = {}
        steps = 0
        while self.scheduler.pending:
            for r in self.step():
                if r.state is not RequestState.FINISHED \
                        and on_degraded == "raise":
                    # crash forensics: snapshot the ring before the
                    # degraded drain surfaces to the caller
                    self.recorder.auto_dump(
                        f"degraded_request:{r.state.value}")
                    raise DegradedRequest(r)
                out[r.rid] = r.tokens
            steps += 1
            if max_steps is not None and steps >= max_steps \
                    and self.scheduler.pending:
                raise RuntimeError(
                    f"engine made no full drain in {max_steps} steps "
                    f"(queue={self.scheduler.queue_depth}, "
                    f"occupied={self.scheduler.occupied})")
        return out

    # --- degradation paths ------------------------------------------------

    def _expire_deadlines(self, finished: List[Request]) -> None:
        """Terminate every in-flight request whose ``deadline_s`` has
        expired (engine clock), freeing its slot for queued work. A
        timed-out request keeps the tokens it generated so far."""
        now_ = self.metrics.clock()
        expired = [r for r in self._requests.values()
                   if r.deadline_s is not None
                   and now_ - r.submit_t >= r.deadline_s]
        for r in expired:
            self._terminate(r, RequestState.TIMED_OUT, finished)
            self.metrics.record_timeout(r.rid)

    def _poison(self, req: Request, err: Exception,
                finished: List[Request]) -> None:
        """Per-request work failed: quarantine THIS request (CANCELLED,
        ``req.error`` holds the cause), recycle its slot, leave every
        other stream untouched."""
        if req.state in TERMINAL_STATES:
            raise err    # already terminal — nothing to isolate
        self._terminate(req, RequestState.CANCELLED, finished, error=err)
        self.metrics.record_cancelled(req.rid)

    def cancel(self, rid: int) -> Request:
        """Cancel an in-flight request by id (client disconnect etc.);
        returns the terminal Request (evicted from the engine)."""
        req = self._requests[rid]
        out: List[Request] = []
        self._terminate(req, RequestState.CANCELLED, out)
        self.metrics.record_cancelled(rid)
        return out[0]

    def _terminate(self, req: Request, state, finished: List[Request],
                   error: Optional[BaseException] = None) -> None:
        """Shared terminal transition for the degradation paths: move
        the request out of the scheduler (freeing its slot when it holds
        one), park the slot's decode vector on the inert sentinel, and
        evict the request from the engine — the caller owns it from
        here, exactly like ``_finish``."""
        had_slot = req.state in (RequestState.PREFILLING,
                                 RequestState.DECODING)
        self.scheduler.cancel(req, state)
        if had_slot:
            self._t[req.slot] = self.max_len   # sentinel: slot inert
        req.error = error
        self.tracer.on_terminal(req.rid, state.value,
                                len(req.generated))
        del self._requests[req.rid]
        finished.append(req)

    def health(self) -> Dict:
        """Readiness snapshot for load balancers / probes, built on the
        unified ``obs.telemetry_snapshot()``: is the engine accepting
        work, how deep is the queue, and the degradation tally of the
        CURRENT metrics window. ``status`` is ``"ok"`` while admission
        is open, ``"saturated"`` once the bounded queue is full (a
        probe should stop routing new traffic here until it drains),
        and ``"degraded"`` while accepting but in breach of a declared
        SLO (``slo=`` objectives; the principled load-shed/reroute
        trigger — a probe keeps the instance but weights traffic
        away). The ``slo`` key carries the freshly evaluated
        per-objective status (None without objectives)."""
        sch = self.scheduler
        accepting = (sch.max_queue is None
                     or sch.queue_depth < sch.max_queue)
        m = self.metrics
        # record=False: a probe is a READ — it must not append to the
        # SLO history, restamp gauges or count breach transitions, or
        # the numbers would depend on how often a balancer polls
        slo_status = (None if self.slo is None
                      else self.slo.evaluate(m, record=False))
        breaching = bool(slo_status) and any(
            st["breach"] for st in slo_status.values())
        status = ("saturated" if not accepting
                  else "degraded" if breaching else "ok")
        return {
            "status": status,
            "accepting": accepting,
            "slo": slo_status,
            "queue_depth": sch.queue_depth,
            "max_queue": sch.max_queue,
            "slots": {"total": self.num_slots, "occupied": sch.occupied,
                      "free": self.num_slots - sch.occupied},
            "requests": {"in_flight": len(self._requests),
                         "finished": m.requests_finished,
                         "rejected": m.requests_rejected,
                         "timed_out": m.requests_timed_out,
                         "cancelled": m.requests_cancelled},
            "telemetry": obs.telemetry_snapshot(),
        }

    # --- internals --------------------------------------------------------

    def _advance_prefill(self, req: Request, finished: List[Request]):
        # chaos hook: an injected raise here exercises the
        # poisoned-request isolation in step(); an injected stall is the
        # slow-prefill scenario (queue grows, deadlines/shedding engage)
        faults.point("serving.prefill")
        p_len = len(req.prompt)
        chunk = self.prefill_chunk
        if chunk is None or p_len <= chunk:
            t0, q_len, final = 0, p_len, True
        else:
            t0 = req.prefill_pos
            q_len = min(chunk, p_len - t0)
            final = t0 + q_len >= p_len
        fn = self._prefill_fn(q_len, t0, final)
        chunk_toks = jnp.asarray(req.prompt[None, t0:t0 + q_len])
        logits, self._staging = fn(self._params, self._state,
                                   self._staging, chunk_toks)
        req.prefill_pos = t0 + q_len
        self.metrics.record_prefill_chunk()
        self.tracer.on_prefill_chunk(req.rid, t0, q_len)
        if not final:
            return
        self.pool.insert(self._staging, req.slot)
        first, req.rng = self._sample_first_fn()(
            logits, jnp.float32(req.temperature),
            jnp.int32(req.top_k), jnp.float32(req.top_p), req.rng)
        token = int(first)
        req.generated.append(token)
        self.metrics.record_first_token(req.rid)
        self.tracer.on_first_token(req.rid)
        if req.done:
            self._finish(req, finished)
            return
        self.scheduler.to_decoding(req)
        s = req.slot
        self._tok[s] = token
        self._t[s] = p_len          # where the next decode step writes it
        self._temp[s] = req.temperature
        self._topk[s] = req.top_k
        self._topp[s] = req.top_p
        self._keys[s] = np.array(req.rng)

    def _advance_decode(self, finished: List[Request]):
        # chaos hook: fires BEFORE any state mutates, so an injected
        # decode-step error leaves the iteration wholesale-retryable
        # (see step() docstring)
        faults.point("serving.decode")
        t0 = self.metrics.clock()
        n_active = len(self.scheduler.running)
        greedy_only = all(r.temperature <= 0.0
                          for r in self.scheduler.running.values())
        if greedy_only:
            nxt, self.pool.cache = self._decode_fn(True)(
                self._params, self._state, self.pool.cache,
                self._tok, self._t)
        else:
            nxt, self.pool.cache, keys = self._decode_fn(False)(
                self._params, self._state, self.pool.cache,
                self._tok, self._t, self._temp, self._topk, self._topp,
                self._keys)
            self._keys = np.array(keys)
        # warm baseline AFTER a variant's first call (its one legitimate
        # compile); any cache growth past it is a shape leak
        if greedy_only not in self._warmed:
            self._warmed.add(greedy_only)
            self._recompile.mark_warm(
                "serving.decode_greedy" if greedy_only
                else "serving.decode_sampled")
        # the per-iteration host sync: the scheduler must see token ids
        # to detect stops and free slots (docs/serving.md, follow-ups)
        nxt = np.asarray(nxt)
        if self.tracer.enabled:
            # one aggregated decode tick per running request (the
            # tracer folds decode_agg of these into one stored event)
            self.tracer.on_decode(
                [r.rid for r in self.scheduler.running.values()])
        for slot, req in list(self.scheduler.running.items()):
            token = int(nxt[slot])
            req.generated.append(token)
            self._tok[slot] = token
            self._t[slot] += 1
            if req.done:
                self._finish(req, finished)
        self.metrics.record_decode(n_active, self.metrics.clock() - t0)

    def _finish(self, req: Request, finished: List[Request]):
        slot = req.slot
        self.scheduler.release(req)
        self._t[slot] = self.max_len          # sentinel: slot inert
        self.metrics.record_finish(req.rid, len(req.generated))
        self.tracer.on_terminal(req.rid, RequestState.FINISHED.value,
                                len(req.generated))
        # evict: the caller owns the finished Request from here —
        # otherwise every prompt ever served stays resident
        del self._requests[req.rid]
        finished.append(req)
