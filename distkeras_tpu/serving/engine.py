"""Slot-based continuous-batching engine over the LM decode path.

The Orca/vLLM iteration-level serving pattern on this repo's
prefill/decode machinery:

  * A paged KV cache (``kv_pool.PagedKVPool``, the default
    ``kv_layout="paged"``) stays resident on device: fixed-size pages
    allocated on demand per request, per-slot page tables driving one
    compiled ``decode_step_slots_paged`` over ALL slots — shapes are
    static (the table is a traced argument), the jit compiles once per
    engine per sampler variant (argmax-only for all-greedy batches,
    the full per-slot sampler for mixed ones), and requests at
    different sequence positions coexist because ``t`` is a per-slot
    vector. Admission is COST-AWARE (``PriorityScheduler``): a request
    admits when its prompt's pages fit the free-page budget (priority
    classes first, FCFS within), and a decode step that outgrows the
    pool preempts the youngest lowest-priority stream back to the
    queue — its context re-prefills on re-admission via the resumable
    ``prefill_chunk_step``, token-identically. Identical prompt
    prefixes hash-cons onto shared read-only pages
    (``kv_pool.PrefixCache``): prefill skips the shared positions, a
    partially matched page is served copy-on-write. The legacy slab
    pool (``kv_layout="slab"``: one ``[S, max_len]`` row per slot,
    FIFO admission, no preemption) remains for comparison — the paged
    data plane is benched against it at equal HBM in ``bench.py``.
  * Requests admit into free slots; a new request's prompt prefills
    into a batch-1 staging cache — chunked (``prefill_chunk``), one
    chunk per engine iteration, interleaved between decode steps so a
    long prompt never stalls in-flight streams — then the filled pages
    INSERT into the request's pool pages (only the pages the prompt
    actually fills, minus the shared-prefix pages) and it joins the
    decode batch.
  * Per-slot sampling state (temperature / top_k / top_p / stop_token
    vectors through ``_sample_vec``, per-slot PRNG keys) lets greedy
    and sampled requests with different stop tokens share one batch.
  * MoE models decode DISPATCHED (``moe_decode="dispatched"``, the
    default): drop-free by construction (``MoE.decode_apply``), so a
    stream's tokens are independent of its batch neighbours; optional
    shard_map expert parallelism (``ep_mesh``) shards expert weights
    over the mesh; expert-load/entropy telemetry and a routing-
    concentration admission cost ride along (docs/serving.md §MoE
    serving).
  * ``ServingMetrics`` records TTFT, TPOT, request latency, queue
    depth, slot occupancy and the per-iteration decode rate; the
    request-level layer rides along — per-request timelines
    (``obs.tracing``, Chrome-trace exportable), a flight-recorder ring
    of recent iterations (``obs.recorder``, auto-dumped on failures)
    and declarative SLOs (``obs.slo``) reported by ``health()``.

Greedy outputs are token-identical per request to a standalone
``generate()`` call on the same prompt (the oracle contract:
``tests/test_serving.py``): prefill runs the very same ``prefill`` /
``prefill_chunk_step`` programs at batch 1, and the per-slot decode
step is the same storage-dtype einsum attention with a per-slot mask.

Zero-bubble loop (this PR, docs/serving.md §Zero-bubble loop): the
decode path no longer blocks on next-token ids every iteration.
``overlap=True`` (the default) pipelines dispatch — iteration i+1's
step is launched with iteration i's token ids fed back DEVICE-side
(JAX async dispatch keeps the device busy) while the host consumes a
LAGGED fetch of iteration i's tokens; ``fuse_steps=K`` additionally
compiles K consecutive decode iterations as one ``lax.scan`` program
(``models.decoding.decode_fused_slots`` — in-program per-slot stop
masks, engaged only when the scheduler is quiescent), eliminating
per-iteration dispatch entirely in steady state. Host-side per-request
bookkeeping (tracer ticks, metrics, recorder-ring composition) is
batched onto a deferred per-window cadence. Outputs stay
token-identical (byte-identical for sampled streams) to the
synchronous loop (``overlap=False``) — the oracle suite pins it.

Remaining deliberate scope: cache-buffer donation is a TPU-latency
follow-up; weight trees support ``weights_dtype="auto"``-style
pre-casting but not int8; prompts longer than
``max_len - max_new_tokens`` are rejected at submit.
"""

from __future__ import annotations

import itertools
import weakref
import zlib
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.obs.recorder import resolve_recorder
from distkeras_tpu.obs.slo import SLOEngine
from distkeras_tpu.obs.timeseries import TimeSeries
from distkeras_tpu.obs.tracing import resolve_tracer
from distkeras_tpu.models.core import Model, Sequential
from distkeras_tpu.models.decoding import (_attn_compute_dtype,
                                           _decode_block_of,
                                           _resolve_head_dims,
                                           _sample_vec, _serving_params,
                                           commit_tree_path,
                                           decode_fused_slots,
                                           decode_step_slots,
                                           decode_step_slots_paged,
                                           prefill, prefill_chunk_step,
                                           tree_walk,
                                           verify_step_slots,
                                           verify_step_slots_paged)
from distkeras_tpu.models.moe import MoE
from distkeras_tpu.resilience import faults
from distkeras_tpu.serving.kv_pool import (KVPool, PagedKVPool,
                                           PrefixCache)
from distkeras_tpu.serving.speculation import (DraftSource,
                                               tree_ancestors)
from distkeras_tpu.serving.metrics import ServingMetrics
from distkeras_tpu.serving.scheduler import (AdmissionRejected,
                                             FIFOScheduler,
                                             PriorityScheduler, Request,
                                             RequestState,
                                             TERMINAL_STATES)


class DegradedRequest(RuntimeError):
    """``run()`` drained a request that did NOT finish normally
    (TIMED_OUT / CANCELLED). Raised by default so a degraded result can
    never masquerade as a complete one in ``run()``'s plain
    ``{rid: tokens}`` return; the terminal ``Request`` (state, partial
    tokens, ``error`` cause) rides on ``.request``."""

    def __init__(self, request: Request):
        cause = (f": {request.error!r}" if request.error is not None
                 else "")
        super().__init__(
            f"request {request.rid} ended {request.state.value}{cause} "
            "— drive with step() to observe terminal states, or "
            "run(on_degraded='return') to accept partial tokens")
        self.request = request


def _snap(a: np.ndarray):
    """Device snapshot of a host mirror for an ASYNC launch. The CPU
    client zero-copy aliases suitably aligned numpy buffers into device
    arguments (the round-6 checkpoint-aliasing finding, reproduced for
    jit call arguments: ~half of fresh small-int32 allocations alias),
    and the zero-bubble loop mutates mirrors while the launched program
    is still executing — so the program must read a private copy. The
    copy is a few dozen bytes per mirror per launch; the temp is owned
    by the runtime from here and never mutated."""
    return jnp.asarray(a.copy())


class _PendingStep:
    """One launched-but-unfetched decode step (the pipelined-dispatch
    in-flight record): the device futures its program returned plus the
    host snapshot needed to consume them later. ``nxt`` is the [S]
    token array of a single step or the [S, K] block of a fused
    window; ``last`` is the [S] device-side feedback array the NEXT
    launch chains from; ``slots`` pins (slot, rid) pairs at launch so a
    slot recycled in the meantime discards its stale tokens."""

    __slots__ = ("nxt", "last", "keys", "moe", "slots", "covers",
                 "count", "launch_t")

    def __init__(self, nxt, last, keys, moe, slots, count, launch_t):
        self.nxt = nxt
        self.last = last
        self.keys = keys
        self.moe = moe
        self.slots = slots                   # tuple of (slot, rid)
        self.covers = {s: r for s, r in slots}
        self.count = count                   # tokens per covered slot
        self.launch_t = launch_t


class ServingEngine:
    """Continuous-batching serving over one ``zoo.transformer_lm``-shaped
    model. ``submit()`` enqueues requests; ``step()`` advances the world
    one scheduler iteration; ``run()`` drains to completion (the
    synchronous driver — an async transport wraps these two calls).

    ``max_len`` is the per-request cache capacity: every request needs
    ``len(prompt) + max_new_tokens <= max_len``.

    Paged-cache knobs (``kv_layout="paged"``, the default):

    * ``page_len`` — positions per KV page. Smaller pages waste less
      tail (fragmentation is < ``page_len`` positions per request) and
      share prefixes at finer grain; larger pages mean fewer
      table entries and scatter/gather indices. 16 is the vLLM-era
      sweet spot for the einsum path (docs/serving.md §Paged KV).
    * ``num_pages`` — the HBM budget, in pages. Default
      ``num_slots * ceil(max_len / page_len)`` (worst-case capacity
      parity with the slab pool); size it DOWN to actual traffic and
      let cost-aware admission + preemption absorb the tail.
    * ``host_kv_pages`` — the HOST page pool (offload tier, docs/
      serving.md §Host KV offload). When > 0, preemption victims swap
      their pages out D2H (resume = H2D copy + table restore, token-
      identical, no re-prefill — an order of magnitude cheaper, which
      is what makes sizing ``num_pages`` aggressively down safe) and
      cold prefix-cache chains spill to host before LRU-evicting
      outright (effective prefix capacity = device + host pages).
      0 (default) disables; size it to spare host RAM — pages cost
      ``2 * Hkv * page_len * Dh * dtype_bytes`` per layer.
    * ``decode_kernel`` — the paged decode readout: ``"auto"``
      (default) runs the Pallas paged-attention kernel on TPU (K/V
      gathered HBM->VMEM through the page table IN-KERNEL — no
      materialized logical view, docs/serving.md §Paged-attention
      kernel) and the ``_gather_pages`` reference elsewhere;
      ``"paged"`` forces the kernel (interpreter mode off-TPU — the
      oracle hook tier-1 uses); ``"off"`` forces the gather path
      (the A/B baseline). Pools whose ``page_len`` breaks the
      kernel's tiling rule (% 8 float, % 32 int8) silently keep the
      gather path.
    * ``prefix_cache`` — hash-cons identical prompt prefixes onto
      shared pages (on by default; sharing is exact up to
      chunked-prefill fp reassociation — see ``kv_pool.PrefixCache``).
    * ``prefix_granularity`` — round PARTIAL-page (copy-on-write)
      matches down to a multiple of this many tokens (full-page
      matches are unaffected). The default 1 shares maximally, but
      every distinct matched length makes the residual prefill chunk a
      novel ragged shape — an inline XLA compile the first time it
      appears (the same hazard as novel prompt lengths,
      docs/serving.md follow-ups). Set to ``page_len`` to keep
      sharing page-granular and the program set bounded.

    Speculative-decoding knobs (docs/serving.md §Speculative decoding):

    * ``draft`` — a ``DraftSource`` (``NgramDraft()`` for zero-weight
      prompt-lookup self-drafting, ``DraftModel(small_lm)`` for a
      learned drafter). None (default) disables speculation.
    * ``spec_k`` — drafts proposed per slot per iteration (STATIC: one
      compiled ``[S, k+1]`` verify program per sampler variant). Each
      verify emits 1..k+1 tokens per slot; the sweet spot tracks the
      workload's acceptance rate (≈2-4 for mixed traffic, higher for
      templated/repetitive streams).
    * ``spec_disable_below`` / ``spec_warmup`` — per-request acceptance
      EMA floor: after ``spec_warmup`` verifies, a stream whose EMA
      acceptance is below the floor stops speculating (the verify
      window costs a (k+1)-wide forward; on a never-accepting stream
      that is pure overhead). Sticky per request.
    * ``spec_tree`` / ``spec_width`` — TREE speculation (docs/
      serving.md §Tree speculation): drafts arrive as a per-slot token
      TREE (``DraftSource.propose_tree`` — branching n-gram
      continuations or a beam-style draft-model tree) and ONE
      tree-masked verify window scores every branch; the in-program
      walk accepts the longest root path (exact multi-draft rejection
      sampling for sampled streams — byte-identical to plain decode)
      and the cache commits only the accepted path. The window is
      ``1 + spec_k * spec_width`` columns (STATIC); an adaptive
      per-stream controller sizes each request's actual depth/width
      inside it from the acceptance EMA (hot streams widen toward the
      caps, cold streams narrow toward a plain chain and ultimately
      the existing EMA kill switch). ``spec_tree=False`` (default)
      keeps the landed linear verify path byte-for-byte; with
      ``spec_width=1`` the tree path IS the linear chain (oracle
      tests pin the identity).

    Zero-bubble knobs (docs/serving.md §Zero-bubble loop):

    * ``overlap`` — pipelined dispatch (default True): each decode
      step's token ids feed back into the NEXT step device-side and
      the host consumes a lagged fetch one iteration behind, so the
      device never waits on per-iteration Python. Host-visible state
      (``req.generated``, metrics, timelines) lags by at most one
      iteration while a stream decodes; outputs are token-identical
      (byte-identical sampled) to ``overlap=False``, the synchronous
      loop kept as the A/B baseline (``bench.py --model
      serving_overlap`` prices the gap). Host bookkeeping batches onto
      a deferred per-``_HOST_WINDOW`` cadence (counts stay exact).
    * ``fuse_steps`` — fused multi-step decode: when >= 2, a QUIESCENT
      iteration (no queued or prefilling requests, no speculating
      slot, no slot within ``fuse_steps`` of its budget, no deadline
      in the batch) runs ``fuse_steps`` plain decode iterations as ONE
      compiled ``lax.scan`` program with in-program per-slot stop
      masks — zero per-iteration dispatch in steady state. Pages for
      the whole window are pre-grown; if that growth preempts a
      stream, the iteration falls back to single-step and fused decode
      rejoins when quiescence returns. 0 (default) disables. Pick K so
      a window is a few ms of device time (4-8 typical): larger K
      amortizes more dispatch but coarsens deadline/SLO checks and
      admission latency to K-step granularity.

    MoE knobs (docs/serving.md §MoE serving):

    * ``moe_decode`` — how the decode/verify steps run MoE MLPs:
      ``"dispatched"`` (default) takes the decode-specialized
      dispatched path (``MoE.decode_apply`` — capacity = the
      slot-token count, DROP-FREE by construction, fused Pallas kernel
      on TPU, tokens path elsewhere), regardless of each layer's
      configured training ``dispatch``; ``"dense"`` opts back into the
      layers' own ``apply`` (the dense-routing baseline the
      ``serving_moe`` bench prices the dispatch against). Either way
      greedy outputs are token-identical to the dense-routing
      ``generate()`` oracle — the drop-free capacity is what makes a
      slot's tokens independent of its batch neighbours.
    * ``ep_mesh`` — expert-parallel decode: REQUIRED when the model's
      MoE layers were built with ``expert_axis_name`` (they cannot run
      outside a shard_map). Every compiled serving program is wrapped
      in ``shard_map`` over this mesh with the stacked expert weights
      sharded on the expert axis (everything else replicated), so
      per-chip expert-weight traffic shrinks with mesh size; the MoE
      combine psums over the axis inside the program.

    A dispatched-MoE engine also feeds MoE telemetry: per-expert load
    and router-entropy gauges (``ServingMetrics.record_moe_route``), a
    ``moe_route`` tracer event on the decode cadence, and a smoothed
    routing-concentration estimate the paged admission consults
    (concentrated routing makes the marginal stream more expensive, so
    admission demands spare-page headroom proportional to it —
    ``_moe_admit_extra``).
    """

    def __init__(self, model: Model, *, num_slots: int = 4,
                 max_len: int = 256,
                 prefill_chunk: Optional[int] = None,
                 cache_dtype=None, weights_dtype="auto",
                 weight_quant: Optional[str] = None,
                 hbm_budget: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: Optional[int] = None,
                 tracer=None, slo=None,
                 kv_layout: str = "paged", page_len: int = 16,
                 num_pages: Optional[int] = None,
                 host_kv_pages: int = 0,
                 decode_kernel: str = "auto",
                 prefix_cache: bool = True,
                 prefix_granularity: int = 1,
                 draft: Optional[DraftSource] = None, spec_k: int = 4,
                 spec_disable_below: float = 0.1,
                 spec_warmup: int = 8,
                 spec_reprobe: Optional[int] = None,
                 spec_tree: bool = False, spec_width: int = 1,
                 timeseries=None,
                 moe_decode: str = "dispatched",
                 ep_mesh=None,
                 overlap: bool = True, fuse_steps: int = 0,
                 fused_sampling: bool = False,
                 engine_id: Optional[str] = None):
        module = model.module
        if not isinstance(module, Sequential):
            raise TypeError("ServingEngine expects a Sequential LM "
                            f"(got {type(module).__name__})")
        self.model = model
        self.module = module
        _resolve_head_dims(module, model.params)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk

        compute_dt = _attn_compute_dtype(module)
        if cache_dtype is None:
            cache_dtype = (compute_dt if compute_dt is not None
                           else jnp.float32)
        # same "auto" weight policy as generate(): pre-cast matrix
        # weights to the compute dtype once (free for bf16 models, a
        # no-op for f32)
        if weights_dtype == "auto":
            weights_dtype = compute_dt if (
                compute_dt is not None
                and compute_dt != jnp.dtype(jnp.float32)) else None

        # --- quantized decode-GEMM weights (quantized-decode PR) --------
        # weight_quant replaces the float weight tree with per-channel
        # int8/int4 qdicts (``ops.quant_matmul``): every compiled
        # serving program dequantizes IN-GRAPH as its first op (the
        # int bytes are what lives in HBM; XLA fuses the dequant into
        # each consumer), and the decode/fused programs additionally
        # keep the attention projections quantized for the fused
        # dequant-matmul kernel when the backend gate is open.
        if weight_quant not in (None, "int8", "int4"):
            raise ValueError(
                f"weight_quant must be None, 'int8' or 'int4', "
                f"got {weight_quant!r}")
        if weight_quant is not None and ep_mesh is not None:
            raise ValueError(
                "weight_quant does not compose with expert parallelism "
                "(the per-leaf expert shardings assume float leaves, "
                "not qdicts) — serve EP models unquantized")
        self.weight_quant = weight_quant
        #: path-keyed per-leaf quantization error (max_abs_err /
        #: rel_rms) — ``obs.report.weight_quant_report`` renders it
        self.weight_quant_error = None
        self._wq_keep_attn = False
        self._wq_dequant_dt = (compute_dt if compute_dt is not None
                               else jnp.float32)
        if weight_quant is not None:
            from distkeras_tpu.ops import quant_matmul as _qm
            qtree = _qm.quantize_params_tree(
                model.params, bits=4 if weight_quant == "int4" else 8)
            self.weight_quant_error = _qm.tree_quant_errors(
                model.params, qtree)
            self._params = qtree
            # shape misalignments degrade per-leaf to the XLA
            # reference inside quant_matmul, so the keep-attn decision
            # only needs the backend gate (TPU, or a test forcing
            # interpreter mode at construction+trace time)
            self._wq_keep_attn = _qm.kernel_enabled()
        else:
            self._params = (model.params if weights_dtype is None
                            else _serving_params(model.params,
                                                 weights_dtype))
        self._state = model.state

        # --- MoE serving (MoE-serving PR) -------------------------------
        if moe_decode not in ("dispatched", "dense"):
            raise ValueError(
                f"moe_decode must be 'dispatched' or 'dense', "
                f"got {moe_decode!r}")
        self.moe_decode = moe_decode
        #: the model's MoE MLPs (inside TransformerBlocks), in layer order
        self._moe = [blk.mlp for blk in
                     (_decode_block_of(layer) for layer in module.layers)
                     if blk is not None and isinstance(blk.mlp, MoE)]
        self._moe_dispatched = bool(self._moe) and \
            moe_decode == "dispatched"
        # expert telemetry rides only on the dispatched path (the dense
        # baseline keeps generate()'s exact program shape)
        self._moe_stats_on = self._moe_dispatched
        self._moe_conc: Optional[float] = None   # routing-concentration EMA
        self._moe_iter = 0                       # stats-throttle counter
        self._setup_expert_parallel(ep_mesh)

        if kv_layout not in ("paged", "slab"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'slab', got {kv_layout!r}")
        self.kv_layout = kv_layout
        # paged-attention decode kernel (decode-kernel PR): "auto" =
        # the Pallas page-table kernel on TPU, the _gather_pages
        # reference elsewhere; "paged" forces the kernel (interpreter
        # mode off-TPU — the oracle/test hook); "off" forces the
        # gather path (the A/B baseline the bench rider prices)
        if decode_kernel not in ("auto", "paged", "off"):
            raise ValueError(
                f"decode_kernel must be 'auto', 'paged' or 'off', "
                f"got {decode_kernel!r}")
        self.decode_kernel = decode_kernel
        self._paged_kernel = {"auto": None, "paged": True,
                              "off": False}[decode_kernel]
        if kv_layout == "slab":
            # loud-validation convention: paged-only options must not
            # silently no-op on a slab engine
            if host_kv_pages:
                raise ValueError(
                    "host_kv_pages needs kv_layout='paged' (the slab "
                    "pool has no page-granular offload)")
            if decode_kernel != "auto":
                raise ValueError(
                    "decode_kernel applies to the paged readout only; "
                    "a slab engine always uses the einsum path")
            if hbm_budget is not None:
                raise ValueError(
                    "hbm_budget needs kv_layout='paged' (the slab pool "
                    "has no page budget to size)")
        if kv_layout == "paged":
            # hbm_budget sizes the page pool from a device-memory
            # envelope: the resident WEIGHT bytes (quantized or not —
            # this is where int4 weights + int4 KV pages compound into
            # more admitted streams) are reserved off the top and the
            # remainder becomes whole pages
            reserve = (sum(np.asarray(l).nbytes for l in
                           jax.tree_util.tree_leaves(self._params))
                       if hbm_budget is not None else 0)
            self.pool = PagedKVPool(module, self.num_slots, self.max_len,
                                    page_len=page_len,
                                    num_pages=num_pages,
                                    host_pages=host_kv_pages,
                                    dtype=cache_dtype,
                                    hbm_budget=hbm_budget,
                                    reserve_bytes=reserve)
            self.page_len = self.pool.page_len
            self.prefix = PrefixCache(self.pool) if prefix_cache else None
            if prefix_granularity < 1:
                raise ValueError(
                    f"prefix_granularity must be >= 1, "
                    f"got {prefix_granularity}")
            self._prefix_granularity = int(prefix_granularity)
            # cost-aware scheduling: priority classes + preemption; the
            # engine gates admission on the free-page budget below
            scheduler = PriorityScheduler(self.num_slots,
                                          max_queue=max_queue)
        else:
            self.pool = KVPool(module, self.num_slots, self.max_len,
                               cache_dtype)
            self.page_len = None
            self.prefix = None
            scheduler = FIFOScheduler(self.num_slots,
                                      max_queue=max_queue)
        # ONE reusable batch-1 prefill staging cache: positions past the
        # current prompt hold a previous request's stale entries, which
        # is safe — insert copies only the pages/rows the prompt filled,
        # and the occupant's decode writes position t before the mask
        # ever admits it
        self._staging = self.pool.make_request_cache()
        #: host-offload odometer snapshot (pool counts cumulatively;
        #: _flush_host_window publishes per-window deltas)
        self._off_seen = (0, 0, 0)
        # bounded admission (load shedding): submits past max_queue
        # raise AdmissionRejected instead of growing the queue without
        # bound under overload; None keeps the open-queue behavior
        self.scheduler = scheduler

        # --- zero-bubble loop state (zero-bubble PR) --------------------
        self.overlap = bool(overlap)
        fuse_steps = int(fuse_steps)
        if fuse_steps < 0:
            raise ValueError(
                f"fuse_steps must be >= 0, got {fuse_steps}")
        #: fused multi-step decode window (engaged when >= 2)
        self.fuse_steps = fuse_steps
        #: fused sampling epilogue (quantized-decode PR): sampled
        #: decode steps draw through ``ops.sampling.sample_tokens`` —
        #: the in-kernel mask+gumbel epilogue on TPU, the
        #: byte-identical reference factorization elsewhere (either
        #: way the token streams match the unfused sampler exactly)
        self.fused_sampling = bool(fused_sampling)
        self._fused_fns = {}                 # greedy_only -> jit scan
        #: the launched-but-unfetched decode step (lag-1 pipeline)
        self._pending: Optional[_PendingStep] = None
        #: slots whose next input token the HOST owns (True) vs the
        #: in-flight step's device output (False)
        self._chain_dirty = np.ones(int(num_slots), bool)
        #: terminal requests produced by out-of-band pipeline flushes
        #: (preemption, cancel); drained by the next step()
        self._finish_buf: List[Request] = []
        #: cumulative seconds blocked in the sanctioned lagged fetch —
        #: the bench's host_loop_us_per_iter rider subtracts this
        self.fetch_seconds = 0.0
        # deferred host work (flushed every _HOST_WINDOW iterations and
        # at every composition change — counts are exact, only their
        # recording is batched off the critical path)
        self._host_window = self._HOST_WINDOW if self.overlap else 1
        self._decode_buf: List = []          # (n_slots, dt, n_tokens)
        self._iter_buf: List = []            # (queue_depth, occupied)
        self._spec_buf: List = []            # (k, accepted) replay
        self._trace_decode: Dict[int, int] = {}   # rid -> decode ticks
        self._trace_decode_t0: Optional[float] = None
        self._trace_spec: Dict[int, List[int]] = {}  # rid -> [prop, acc]
        #: batch-composition version: bumped on admit / to-decoding /
        #: finish / preempt / terminate so steady-state iterations skip
        #: rebuilding the recorder's per-iteration rid lists
        self._comp_ver = 0
        self._rec_cache = (-1, None)

        # --- engine identity (serving-router PR) ------------------------
        # ``engine_id`` tags every process-global record this engine
        # emits — flight-recorder ring entries, tracer timelines — and
        # names its telemetry_snapshot() component: with N live engines
        # behind a router the records would otherwise interleave
        # indistinguishably. Default keeps the single-engine contract:
        # the first live engine is plain "serving", later ones get a
        # unique suffix.
        if engine_id is None:
            name = "serving"
            if name in obs.components():
                name = f"serving[{id(self):x}]"
            self.engine_id = name
        else:
            self.engine_id = str(engine_id)
            name = f"serving[{self.engine_id}]"
            if name in obs.components():
                # an alive engine already owns this id: disambiguate
                # the id ITSELF (not just the component name) — two
                # engines sharing a record tag is exactly the
                # indistinguishable interleaving engine_id exists to
                # prevent
                self.engine_id = f"{self.engine_id}#{id(self):x}"
                name = f"serving[{self.engine_id}]"
        self._component_name = name

        self.metrics = metrics if metrics is not None else ServingMetrics()
        # request-level observability (obs.tracing / obs.recorder /
        # obs.slo): the tracer shares the metrics clock so timeline
        # durations and measured latencies are directly comparable;
        # the scheduler records admissions where they happen; the
        # flight recorder is the process-global ring (NULL when obs is
        # disabled); ``slo`` takes an SLOEngine or a sequence of
        # Objectives (evaluated every _SLO_EVAL_EVERY iterations and
        # reported by health())
        self.tracer = resolve_tracer(tracer, clock=self.metrics.clock,
                                     engine=self.engine_id)
        self.scheduler.tracer = (self.tracer if self.tracer.enabled
                                 else None)
        self.recorder = resolve_recorder()
        if slo is None or isinstance(slo, SLOEngine):
            self.slo = slo
        else:
            self.slo = SLOEngine(list(slo), clock=self.metrics.clock)
        # windowed time-series telemetry (obs.timeseries): scraped on
        # the existing deferred host-window cadence in step() — pure
        # host-side Python over the live registry, zero new device
        # syncs. ``timeseries=None`` (default) builds a scraper that
        # follows the CURRENT metrics window across per-interval swaps
        # (the weakref provider — the scraper must not keep the engine
        # alive); ``False`` disables; a ``TimeSeries`` instance is used
        # as-is (the replay harness installs one on a virtual clock).
        if timeseries is False:
            self.timeseries = None
        elif isinstance(timeseries, TimeSeries):
            self.timeseries = timeseries
        else:
            _wref = weakref.ref(self)

            def _live_registry():
                eng = _wref()
                return None if eng is None else eng._metrics.registry

            self.timeseries = TimeSeries(
                _live_registry, clock=self.metrics.clock,
                interval_s=0.0 if timeseries is None else float(timeseries),
                tags={"engine": self.engine_id})
        self._requests: Dict[int, Request] = {}
        self._rid = itertools.count()

        # per-slot decode vectors (host mirrors of the traced args)
        s = self.num_slots
        self._tok = np.zeros(s, np.int32)
        #: max_len is the free-slot sentinel: the one-hot cache write
        #: misses every position and the slot's logits are discarded
        self._t = np.full(s, self.max_len, np.int32)
        self._temp = np.zeros(s, np.float32)
        self._topk = np.zeros(s, np.int32)
        self._topp = np.ones(s, np.float32)
        #: per-slot stop tokens (-1 = never): the fused window's
        #: in-program done masks read these
        self._stop = np.full(s, -1, np.int32)
        self._keys = np.stack(
            [np.array(jax.random.PRNGKey(0))] * s)       # [S, key]

        self._step_fns = {}                  # greedy_only -> jit
        self._prefill_fns = {}
        self._first_fn = None

        # speculative decoding (spec-decode PR): a DraftSource proposes
        # k candidate tokens per slot; ONE compiled verify step scores
        # the whole [S, k+1] window (fixed k — static shapes, one
        # program per sampler variant). A per-request acceptance EMA
        # (spec_disable_below / spec_warmup) kicks streams the draft
        # cannot predict back to plain decode — speculation is an
        # accelerator, never a correctness or admission dependency.
        if draft is not None and not isinstance(draft, DraftSource):
            raise TypeError(
                f"draft must be a DraftSource (NgramDraft / DraftModel "
                f"/ custom), got {type(draft).__name__}")
        self._draft = draft
        self.spec_k = int(spec_k)
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if not 0.0 <= float(spec_disable_below) <= 1.0:
            raise ValueError(
                f"spec_disable_below must be in [0, 1], "
                f"got {spec_disable_below}")
        self.spec_disable_below = float(spec_disable_below)
        self.spec_warmup = int(spec_warmup)
        # adaptive re-enable: the EMA kill switch above is sticky by
        # default (the adversarial-stream contract several tests pin);
        # with ``spec_reprobe=N`` a demoted stream gets a probabilistic
        # re-probe after generating N more tokens, so a workload shift
        # (the draft starts predicting again) can win speculation back
        if spec_reprobe is not None:
            spec_reprobe = int(spec_reprobe)
            if spec_reprobe < 1:
                raise ValueError(
                    f"spec_reprobe must be >= 1, got {spec_reprobe}")
        self.spec_reprobe = spec_reprobe
        self._spec_fns = {}                  # greedy_only -> jit verify
        # tree speculation (tree-speculation PR): the verify window
        # widens to 1 + spec_k * spec_width TREE nodes; per-stream
        # depth/width adapt inside the static window
        self.spec_tree = bool(spec_tree)
        self.spec_width = int(spec_width)
        if self.spec_width < 1:
            raise ValueError(
                f"spec_width must be >= 1, got {spec_width}")
        if self.spec_width > 1 and not self.spec_tree:
            raise ValueError(
                "spec_width > 1 needs spec_tree=True (the linear "
                "verify window has no branch columns)")
        if self.spec_tree and draft is None:
            raise ValueError(
                "spec_tree=True needs a draft source "
                "(ServingEngine(draft=...))")
        #: verify-window width: tree windows hold the full node budget
        self.spec_window = (1 + self.spec_k * self.spec_width
                            if self.spec_tree else self.spec_k + 1)
        self._tree_fns = {}                  # greedy_only -> jit tree fn
        self._spec_tree_buf: List = []       # (tree_width, path_len)
        if draft is not None:
            draft.bind(self)

        # telemetry: the CURRENT metrics window joins the unified
        # obs.telemetry_snapshot() under "serving" (weakref-bound, so a
        # dropped engine detaches itself); the decode steps — compiled
        # once per sampler variant BY DESIGN — are recompile-watched,
        # catching shape/dtype leaks that would silently recompile the
        # hot loop (checked every _RECOMPILE_CHECK_EVERY iterations)
        self._recompile = obs.RecompileDetector()
        self._warmed = set()                 # decode variants marked warm
        self._iters = 0
        # component name resolved in the engine-identity block above
        # (first live engine owns plain "serving"; explicit engine_id
        # attaches as "serving[<id>]"). The bound method is
        # WeakMethod-held by attach, so the registry never keeps this
        # engine (and its KV pool) alive.
        obs.attach(self._component_name, self._telemetry_summary,
                   owner=self)

    #: engine iterations between recompile-detector polls
    _RECOMPILE_CHECK_EVERY = 64
    #: engine iterations between deferred host-work flushes (tracer
    #: ticks, metrics samples, spec counters) in overlap mode; 1 (the
    #: synchronous loop) flushes every iteration. Composition changes
    #: (finish/preempt/terminal) always flush immediately, so counts
    #: are exact — only their RECORDING is batched off the hot loop.
    _HOST_WINDOW = 8
    #: engine iterations between SLO evaluations (when ``slo`` is set)
    _SLO_EVAL_EVERY = 32
    #: EMA smoothing for the router-concentration estimate
    _MOE_CONC_ALPHA = 0.25
    #: decode iterations between MoE routing-stats reads. The stats are
    #: computed IN-PROGRAM every step (negligible), but pulling them to
    #: the host costs extra device syncs per iteration — measured 4x on
    #: the CPU smoke step when done every iteration. Sampling every
    #: 16th step keeps the gauges/EMA fresh at decode-agg cadence while
    #: the hot loop pays one sync set per 16 steps. The FIRST decode
    #: step always reports (tests and short runs see the picture).
    _MOE_STATS_EVERY = 16
    #: admission headroom per unit concentration (pages, as a fraction
    #: of the request's context pages) — see ``_moe_admit_extra``
    _MOE_ADMIT_ALPHA = 0.5

    # --- expert-parallel decode (MoE-serving PR) -------------------------

    def _setup_expert_parallel(self, ep_mesh) -> None:
        """Wire shard_map expert parallelism: models whose MoE layers
        carry ``expert_axis_name`` must run inside a shard_map, so the
        engine wraps every compiled program over ``ep_mesh`` with the
        stacked expert weights sharded on that axis (pre-placed here —
        each chip holds its E/A experts; everything else replicated).
        Outputs are replicated: the MoE combine psums over the axis
        in-program, exactly the layer's existing EP contract."""
        axes = {m.expert_axis_name for m in self._moe
                if m.expert_axis_name is not None}
        self._ep_mesh = self._ep_axis = self._ep_pspec = None
        if not axes:
            if ep_mesh is not None:
                raise ValueError(
                    "ep_mesh given but no MoE layer carries "
                    "expert_axis_name — build the model with "
                    "moe_expert_axis=<axis> to serve expert-parallel")
            return
        if len(axes) > 1:
            raise ValueError(
                f"MoE layers disagree on expert_axis_name: {axes}")
        axis = axes.pop()
        if ep_mesh is None:
            raise ValueError(
                f"MoE layers carry expert_axis_name={axis!r}: they can "
                "only run inside a shard_map — pass "
                "ServingEngine(ep_mesh=Mesh(...)) carrying that axis")
        if axis not in ep_mesh.axis_names:
            raise ValueError(
                f"ep_mesh axes {ep_mesh.axis_names} do not include the "
                f"model's expert axis {axis!r}")
        n_dev = ep_mesh.shape[axis]
        for m in self._moe:
            if m.num_experts % n_dev:
                raise ValueError(
                    f"num_experts {m.num_experts} not divisible by the "
                    f"{axis!r} mesh axis size {n_dev}")
        from jax.sharding import NamedSharding, PartitionSpec as P
        pspec = jax.tree_util.tree_map(lambda _: P(), self._params)
        shardings = jax.tree_util.tree_map(
            lambda _: NamedSharding(ep_mesh, P()), self._params)
        for i, layer in enumerate(self.module.layers):
            blk = _decode_block_of(layer)
            if blk is None or not isinstance(blk.mlp, MoE) \
                    or blk.mlp.expert_axis_name is None:
                continue
            for kk in ("w1", "b1", "w2", "b2"):
                pspec[i]["mlp"][kk] = P(axis)
                shardings[i]["mlp"][kk] = NamedSharding(ep_mesh, P(axis))
        self._ep_mesh, self._ep_axis, self._ep_pspec = ep_mesh, axis, pspec
        # pre-slice the expert weights onto their chips once — the
        # whole point: per-chip weight traffic shrinks with the mesh
        self._params = jax.device_put(self._params, shardings)

    def _jit_serving(self, f, n_args: int, keep_attn: bool = False):
        """Compile one serving program: plain ``jax.jit``, or — under
        expert parallelism — ``jit(shard_map(f))`` with the params
        (always argument 0) split by the expert specs and every other
        argument/output replicated (the MoE psum makes outputs agree
        across the axis). Under ``weight_quant`` every program first
        dequantizes the qdict tree in-graph; ``keep_attn`` (the
        decode/fused programs, whose only attention-weight consumers
        are ``_project_qkv`` / ``_attn_out``) leaves the attention
        projections quantized for the fused dequant-matmul kernel."""
        if self.weight_quant is not None:
            from distkeras_tpu.ops.quant_matmul import dequant_params_tree
            inner, dt = f, self._wq_dequant_dt
            keep = keep_attn and self._wq_keep_attn

            def f(params, *rest):
                return inner(
                    dequant_params_tree(params, dt, keep_attn=keep),
                    *rest)
        if self._ep_mesh is None:
            return jax.jit(f)
        from jax.sharding import PartitionSpec as P
        from distkeras_tpu.compat import shard_map
        return jax.jit(shard_map(
            f, mesh=self._ep_mesh,
            in_specs=(self._ep_pspec,) + (P(),) * (n_args - 1),
            out_specs=P()))

    # --- MoE routing telemetry / admission cost ---------------------------

    def _note_moe_route(self, stats) -> None:
        """Host-side sink for one step's MoE routing stats (the extra
        output of the dispatched decode/verify programs): update the
        expert-load/entropy gauges, the concentration EMA the paged
        admission reads, and the per-request ``moe_route`` tracer
        aggregation (decode cadence). THROTTLED to every
        ``_MOE_STATS_EVERY``-th decode iteration — reading the device
        stats costs host syncs the hot loop must not pay per step."""
        if stats is None:
            return
        n = self._moe_iter
        self._moe_iter = n + 1
        if n % self._MOE_STATS_EVERY:
            return                       # unread device arrays just drop
        load = np.asarray(stats["expert_load"], np.float64)
        entropy = float(np.asarray(stats["router_entropy"]))
        total = float(load.sum())
        e = len(load)
        share = float(load.max()) / total if total > 0 else 0.0
        if total > 0 and e > 1:
            # normalize against uniform routing: 0 = balanced, 1 = all
            # assignments on one expert
            conc = max(0.0, (share - 1.0 / e) / (1.0 - 1.0 / e))
            a = self._MOE_CONC_ALPHA
            self._moe_conc = (conc if self._moe_conc is None
                              else (1.0 - a) * self._moe_conc + a * conc)
        self.metrics.record_moe_route(load, entropy,
                                      self._moe_conc or 0.0)
        if self.tracer.enabled:
            self.tracer.on_moe_route(
                [r.rid for r in self.scheduler.running.values()],
                entropy, share)

    def _moe_admit_extra(self, req: Request, n_logical: int) -> int:
        """MoE-aware admission cost: pages of HEADROOM (beyond the
        request's own context pages) the free-page budget must show
        before this admission, proportional to the smoothed router
        concentration. Rationale: under concentrated routing the
        dispatched decode's per-expert rows pile onto few experts (and,
        expert-parallel, onto few CHIPS), so the marginal stream buys
        less throughput — admitting to the last page then forces the
        preemption churn the budget exists to avoid. Capped so a
        feasible request can ALWAYS admit into an idle pool: worst-case
        context + headroom never exceeds the pool (no starvation)."""
        if not self._moe_stats_on or not self._moe_conc:
            return 0
        import math
        extra = int(math.ceil(
            self._MOE_ADMIT_ALPHA * self._moe_conc * n_logical))
        worst = self.pool.pages_for(len(req.prompt) + req.max_new_tokens)
        return max(0, min(extra, self.pool.num_pages - worst))

    def _telemetry_summary(self):
        """obs.attach provider: the CURRENT metrics window's summary
        (``self.metrics`` is swapped per reporting interval), plus the
        compact per-request timelines and the latest SLO status —
        additive keys on the established component shape."""
        self._flush_host_window()    # deferred samples land first
        snap = self.metrics.summary()
        if self.tracer.enabled:
            snap["requests"] = self.tracer.summaries()
        if self.slo is not None:
            snap["slo"] = self.slo.status()
        if self.timeseries is not None:
            snap["timeseries"] = self.timeseries.summary()
        return snap

    # --- zero-bubble loop: pipelined dispatch + deferred host work --------

    @property
    def metrics(self) -> ServingMetrics:
        return self._metrics

    @metrics.setter
    def metrics(self, value: ServingMetrics) -> None:
        """Swapping the metrics window (the per-reporting-interval
        pattern) first drains the pipeline and the deferred host-work
        buffers into the OLD window, so no sample leaks across."""
        old = getattr(self, "_metrics", None)
        if old is not None:
            self._flush_pending(self._finish_buf)
            self._flush_host_window()
        self._metrics = value

    def _fetch(self, *arrays):
        """THE serving loop's single sanctioned device->host sync: the
        lagged fetch of a completed decode/verify step's outputs (and
        the spec path's in-iteration verify fetch). Every other sync in
        the step/decode path is a lint finding
        (``tools/lint_host_sync.py``). Accumulates blocking time in
        ``fetch_seconds`` for the bench's host-loop rider."""
        t0 = self._metrics.clock()
        out = [np.asarray(a) for a in arrays]  # lint: allow-host-sync (the lagged fetch)
        self.fetch_seconds += self._metrics.clock() - t0
        return out

    def _flush_pending(self, out: Optional[List[Request]] = None) -> None:
        """Consume the in-flight decode step (if any): fetch its
        tokens, append them to their requests, finish what completed.
        After this the HOST owns every slot's next input token."""
        p = self._pending
        if p is None:
            return
        self._pending = None
        self._process_step(p, out if out is not None
                           else self._finish_buf)
        self._chain_dirty[:] = True

    def _process_step(self, p: _PendingStep, finished: List[Request],
                      t0: Optional[float] = None) -> None:
        """Consume one launched step's outputs. Slots whose request
        changed since launch (finished by an earlier flush, preempted,
        recycled) discard their tokens — the overshoot contract: at
        lag 1 a stream is stepped at most once past its stop token,
        and the extra token/KV write is never consumed.

        ``t0`` is the CONSUMING iteration's decode-phase start: the
        recorded decode sample spans this phase (dispatch + lagged
        fetch + consume), matching the synchronous loop's attribution.
        Without it (out-of-band flushes: preempt, cancel, metrics
        swap) the sample falls back to launch-to-consume wall, which
        overstates dt by whatever ran in between — rare enough not to
        skew the steady-state rate."""
        running = self.scheduler.running
        if not any(running.get(s) is not None and running[s].rid == r
                   for s, r in p.slots):
            return      # every covered stream retired: drop wholesale
        fetched = self._fetch(*((p.nxt,) if p.keys is None
                                else (p.nxt, p.keys)))
        nxt = fetched[0]
        if p.keys is not None:
            # chain-live slots take the program's post-split keys; a
            # slot the host overrode since launch (fresh admission)
            # keeps its host mirror — the launch never consumed it
            live = ~self._chain_dirty
            self._keys[live] = fetched[1][live]
        toks = nxt if nxt.ndim == 2 else nxt[:, None]    # [S, count]
        self._note_moe_route(p.moe)
        now_ = self._metrics.clock()
        trace_on = self.tracer.enabled
        done_reqs: List[Request] = []
        n_emitted = 0
        for slot, rid in p.slots:
            req = running.get(slot)
            if req is None or req.rid != rid:
                continue                     # recycled slot: discard
            n_app = 0
            for j in range(p.count):
                req.generated.append(int(toks[slot, j]))
                n_app += 1
                if req.done:
                    break                    # stop / budget mid-window
            n_emitted += n_app
            self._tok[slot] = req.generated[-1]
            if trace_on and n_app:
                self._trace_decode[rid] = \
                    self._trace_decode.get(rid, 0) + n_app
                if self._trace_decode_t0 is None:
                    self._trace_decode_t0 = now_
            if req.done:
                done_reqs.append(req)
        self._decode_buf.append(
            (len(p.slots),
             now_ - (p.launch_t if t0 is None else t0), n_emitted))
        if done_reqs:
            self._flush_host_window()        # ticks precede terminals
            for req in done_reqs:
                self._finish(req, finished)

    def _flush_host_window(self) -> None:
        """Apply the deferred host-work buffers to the live metrics
        window and tracer: per-iteration queue/occupancy samples, exact
        decode token/time aggregation, spec verify counters, and the
        batched per-request decode ticks. Runs every ``_HOST_WINDOW``
        iterations, before every terminal transition, and on
        metrics-window swaps — so every count is exact, just recorded
        off the per-iteration critical path."""
        m = self._metrics
        if self._iter_buf:
            for qd, occ in self._iter_buf:
                m.record_iteration(qd, occ, self.num_slots)
            self._iter_buf.clear()
            if self.kv_layout == "paged":
                m.record_pages(self.pool.free_pages,
                               self.pool.shared_pages,
                               self._fragmentation())
                # host-tier odometers: the pool counts cumulatively;
                # the metrics WINDOW gets deltas so window swaps stay
                # honest (the record_pages gauge discipline)
                po, pr, ob = (self.pool.pages_offloaded,
                              self.pool.pages_restored,
                              self.pool.offload_bytes)
                so, sr, sb = self._off_seen
                if po > so or pr > sr:
                    m.record_offload(po - so, pr - sr, ob - sb)
                    self._off_seen = (po, pr, ob)
        if self._decode_buf:
            for n, dt, toks in self._decode_buf:
                m.record_decode(n, dt, n_tokens=toks)
            self._decode_buf.clear()
        if self._spec_buf:
            for k, acc in self._spec_buf:
                m.record_spec_verify(k, acc)
            self._spec_buf.clear()
        if self._spec_tree_buf:
            for width, path_len in self._spec_tree_buf:
                m.record_spec_tree(width, path_len)
            self._spec_tree_buf.clear()
        if self._trace_decode:
            if self.tracer.enabled:
                self.tracer.on_decode_batch(self._trace_decode,
                                            t0=self._trace_decode_t0)
            self._trace_decode = {}
            self._trace_decode_t0 = None
        if self._trace_spec:
            if self.tracer.enabled:
                # linear entries are [proposed, accepted]; tree entries
                # append [tree_width, accepted_path_len]
                self.tracer.on_spec_verify(
                    [(rid, *pa)
                     for rid, pa in self._trace_spec.items()])
            self._trace_spec = {}

    def _inflight(self) -> Dict[int, int]:
        """slot -> tokens in flight for the slot's CURRENT request (0
        when the pending step predates the occupant)."""
        p = self._pending
        if p is None:
            return {}
        running = self.scheduler.running
        out = {}
        for slot, rid in p.slots:
            req = running.get(slot)
            if req is not None and req.rid == rid:
                out[slot] = p.count
        return out

    def _merge_keys(self, prev: Optional[_PendingStep], dirty):
        """Per-slot PRNG keys for the next launch: the in-flight
        step's post-split keys wherever the chain is live, the host
        mirror where the host overrode the slot since. Mirrors are
        snapshotted (``_snap``) — the launched program reads them
        after dispatch returns."""
        if prev is None or prev.keys is None:
            return _snap(self._keys)
        if dirty.any():
            return jnp.where(_snap(dirty)[:, None],
                             _snap(self._keys), prev.keys)
        return prev.keys

    def _fuse_window(self) -> int:
        """Fused-window size for THIS iteration: ``fuse_steps`` when
        the scheduler is quiescent, else 0 (single-step). Quiescent =
        nothing queued or prefilling (admission latency would coarsen
        to K steps), no deadline in the batch (expiry checks are
        per-iteration), and every stream's remaining budget — net of
        in-flight tokens — covers a whole window (the in-program stop
        masks handle stop tokens; the budget has no in-program
        analogue, so the window must fit under it)."""
        k = self.fuse_steps
        if k < 2:
            return 0
        sch = self.scheduler
        if sch.queue_depth or sch.prefilling:
            return 0
        running = sch.running
        if not running:
            return 0
        infl = self._inflight()
        for slot, r in running.items():
            if r.deadline_s is not None:
                return 0
            if r.max_new_tokens - len(r.generated) \
                    - infl.get(slot, 0) < k:
                return 0
        return k

    def _launch_step(self, greedy_only: bool, tables, fuse: int,
                     prev: Optional[_PendingStep],
                     t0: float) -> _PendingStep:
        """Dispatch one decode unit — a single step, or a ``fuse``-wide
        fused window — WITHOUT waiting on its outputs. The input token
        vector chains device-side from the in-flight step's feedback
        (``prev.last``) wherever the chain is live, falling back to the
        host mirror for slots the host overrode since (fresh
        admissions, post-flush iterations). Host mirrors advance
        eagerly: ``_t`` moves past the positions this launch writes, so
        page growth and the next launch see the true frontier."""
        running = self.scheduler.running
        dirty = self._chain_dirty
        # every host mirror crossing the device boundary here is
        # snapshotted (_snap): dispatch returns while the program still
        # READS its arguments, and the CPU client zero-copy aliases
        # aligned numpy buffers — the eager mirror updates below (and
        # later iterations' bookkeeping) must not race the in-flight
        # read. The synchronous loop never saw this: it blocked on the
        # step's outputs before touching any mirror.
        t_dev = _snap(self._t)
        if prev is None:
            tok = _snap(self._tok)
        elif dirty.any():
            tok = jnp.where(_snap(dirty), _snap(self._tok), prev.last)
        else:
            tok = prev.last
        keys = None
        if fuse:
            if greedy_only:
                nxt, cache, moe = self._fused_fn(True)(
                    self._params, self._state, self.pool.cache, tok,
                    t_dev, _snap(self._stop), *tables)
            else:
                nxt, cache, keys, moe = self._fused_fn(False)(
                    self._params, self._state, self.pool.cache, tok,
                    t_dev, _snap(self._stop), _snap(self._temp),
                    _snap(self._topk), _snap(self._topp),
                    self._merge_keys(prev, dirty), *tables)
            last, count = nxt[:, -1], fuse
            warm = ("serving.decode_fused_greedy" if greedy_only
                    else "serving.decode_fused_sampled")
        else:
            if greedy_only:
                nxt, cache, moe = self._decode_fn(True)(
                    self._params, self._state, self.pool.cache, tok,
                    t_dev, *tables)
            else:
                nxt, cache, keys, moe = self._decode_fn(False)(
                    self._params, self._state, self.pool.cache, tok,
                    t_dev, _snap(self._temp), _snap(self._topk),
                    _snap(self._topp),
                    self._merge_keys(prev, dirty), *tables)
            last, count = nxt, 1
            warm = ("serving.decode_greedy" if greedy_only
                    else "serving.decode_sampled")
        self.pool.cache = cache
        # warm baseline AFTER a variant's first call (its one
        # legitimate compile); cache growth past it is a shape leak
        if warm not in self._warmed:
            self._warmed.add(warm)
            self._recompile.mark_warm(warm)
        slots = tuple((slot, r.rid) for slot, r in running.items())
        for slot, _ in slots:
            self._t[slot] += count
            dirty[slot] = False          # chain live until overridden
        return _PendingStep(nxt, last, keys, moe, slots, count, t0)

    def _record_iteration(self, admitted: List[Request]) -> None:
        """Flight-recorder iteration entry, written BEFORE
        prefill/decode run so a mid-iteration fault dump contains the
        failing iteration itself. The per-iteration rid lists rebuild
        only when the batch composition changed (``_comp_ver``);
        steady-state iterations reuse the cached lists and, in overlap
        mode, only write a ring entry on the host-window cadence."""
        if not self.recorder.enabled:
            return
        sch = self.scheduler
        ver = self._rec_cache[0]
        if self._comp_ver != ver:
            self._rec_cache = (self._comp_ver, (
                [r.rid for r in sch.running.values()],
                [r.rid for r in sch.prefilling]))
        elif self._iters % self._host_window:
            return                      # steady state: window cadence
        decoding, prefilling = self._rec_cache[1]
        extra = ({"pages_free": self.pool.free_pages}
                 if self.kv_layout == "paged" else {})
        if self.kv_layout == "paged" \
                and self.pool.host_cache is not None:
            # host-pool occupancy in the flight-recorder ring: a
            # post-mortem distinguishes "swaps stopped because the
            # host tier filled" from "preemptions stopped"
            extra["host_pages_free"] = self.pool.host_free_pages
        self.recorder.record(
            "serving.iteration", engine=self.engine_id,
            iter=self._iters,
            queue_depth=sch.queue_depth, occupied=sch.occupied,
            decoding=decoding, prefilling=prefilling,
            admitted=[r.rid for r in admitted], **extra)

    # --- request intake ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               stop_token: Optional[int] = None, seed: int = 0,
               deadline_s: Optional[float] = None,
               priority: int = 1,
               speculate: Optional[bool] = None) -> int:
        """Enqueue one request; returns its id. Sampling defaults match
        ``generate()`` (greedy); ``None`` knobs mean disabled.

        ``deadline_s`` is a submit→finish budget on the engine clock: a
        request still unfinished when it expires is terminated
        ``TIMED_OUT`` at the next ``step()`` (partial tokens kept on the
        returned request). Raises ``AdmissionRejected`` when the engine
        was built with ``max_queue`` and the wait queue is full.

        ``priority`` (paged engine): lower admits first — 0
        interactive, 1 standard (default), 2 batch. A queued priority-0
        request may PREEMPT lower-priority decoding streams when the
        page budget is short; ignored by the slab engine's FCFS.

        ``speculate`` (engines built with ``draft=``): whether this
        request joins draft-and-verify decode iterations. ``None``
        (default) means yes whenever the engine has a draft source;
        ``False`` opts out; ``True`` on a draftless engine raises.
        Greedy speculative output is token-identical to plain decode
        (and to ``generate()``); sampled streams keep their exact
        per-request key stream either way."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the slot capacity "
                f"max_len={self.max_len}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if deadline_s is not None and float(deadline_s) <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}")
        if self.kv_layout == "paged":
            # a request whose worst case exceeds the whole pool could
            # never finish — even after preempting everything else
            worst = self.pool.pages_for(prompt.size + max_new_tokens)
            if worst > self.pool.num_pages:
                raise ValueError(
                    f"request needs up to {worst} pages but the pool "
                    f"holds {self.pool.num_pages}; raise num_pages or "
                    "lower max_new_tokens")
        if speculate and self._draft is None:
            raise ValueError(
                "speculate=True needs an engine built with a draft "
                "source (ServingEngine(draft=NgramDraft()) or "
                "DraftModel(...))")
        req = Request(
            rid=next(self._rid), prompt=prompt,
            max_new_tokens=max_new_tokens,
            temperature=float(temperature),
            top_k=0 if top_k is None else int(top_k),
            top_p=1.0 if top_p is None else float(top_p),
            stop_token=-1 if stop_token is None else int(stop_token),
            seed=int(seed), priority=int(priority),
            deadline_s=None if deadline_s is None else float(deadline_s),
            speculate=(self._draft is not None if speculate is None
                       else bool(speculate)))
        req.rng = jax.random.PRNGKey(req.seed)
        req.submit_t = self.metrics.clock()
        try:
            self.scheduler.submit(req)    # may shed (AdmissionRejected)
        except AdmissionRejected:
            self.metrics.record_rejected()
            self.tracer.on_reject()
            # storm detection lives in the recorder: enough sheds since
            # the last dump auto-snapshot the ring (overload forensics)
            self.recorder.note_rejection(
                rid=req.rid, engine=self.engine_id,
                queue_depth=self.scheduler.queue_depth,
                max_queue=self.scheduler.max_queue)
            raise
        self._requests[req.rid] = req
        self.metrics.record_submit(req.rid)
        self.tracer.on_submit(req.rid, self.scheduler.queue_depth)
        return req.rid

    def __getitem__(self, rid: int) -> Request:
        """IN-FLIGHT request lookup (queued/prefilling/decoding).
        Finished requests are returned by ``step()``/``run()`` and
        evicted from the engine — a long-lived server must not
        accumulate one prompt array per request ever served."""
        return self._requests[rid]

    # --- compiled programs ------------------------------------------------

    def _decode_fn(self, greedy_only: bool):
        """Two compiled step variants, chosen per iteration by the
        host: ALL-GREEDY batches (the common serving default) take a
        pure-argmax step — the vector sampler's rank/nucleus masks cost
        two [S, V] argsorts plus a sort per step that greedy never
        needs, a material tax at real vocab sizes. A mixed batch takes
        the full per-slot sampler; sampled requests only ever decode
        under the mixed variant (their temperature forces it while they
        occupy a slot), so their per-request key streams stay
        schedule-independent."""
        fn = self._step_fns.get(greedy_only)
        if fn is None:
            module = self.module
            paged = self.kv_layout == "paged"
            page_len = self.page_len
            moe_kw = dict(
                moe_dispatched=self._moe_dispatched,
                moe_stats=self.max_len if self._moe_stats_on else None)
            stats_on = self._moe_stats_on
            pk = self._paged_kernel

            def step(params, state, cache, tok, t, tables):
                if paged:
                    out = decode_step_slots_paged(
                        module, params, state, cache, tok, t, tables,
                        page_len, paged_kernel=pk, **moe_kw)
                else:
                    out = decode_step_slots(
                        module, params, state, cache, tok, t, **moe_kw)
                # every variant returns a routing-stats slot (None on
                # MoE-free / dense-baseline engines) so call sites
                # unpack one shape
                return out if stats_on else (out + (None,))

            if greedy_only:
                if paged:
                    def fn(params, state, cache, tok, t, tables):
                        logits, cache, moe = step(params, state, cache,
                                                  tok, t, tables)
                        return jnp.argmax(logits, axis=-1), cache, moe
                    n_args = 6
                else:
                    def fn(params, state, cache, tok, t):
                        logits, cache, moe = step(params, state, cache,
                                                  tok, t, None)
                        return jnp.argmax(logits, axis=-1), cache, moe
                    n_args = 5
            else:
                if self.fused_sampling:
                    from distkeras_tpu.ops.sampling import sample_tokens
                    sampler = sample_tokens
                else:
                    sampler = _sample_vec

                def body(params, state, cache, tok, t, temp, topk, topp,
                         keys, tables):
                    logits, cache, moe = step(params, state, cache,
                                              tok, t, tables)
                    # per-slot key streams: a request's draws depend
                    # only on its own seed, not on which neighbours
                    # share the batch
                    split = jax.vmap(jax.random.split)(keys)
                    nxt = sampler(logits, temp, topk, topp,
                                  split[:, 1])
                    return nxt, cache, split[:, 0], moe

                if paged:
                    fn, n_args = body, 10
                else:
                    def fn(params, state, cache, tok, t, temp, topk,
                           topp, keys):
                        return body(params, state, cache, tok, t, temp,
                                    topk, topp, keys, None)
                    n_args = 9

            fn = self._jit_serving(fn, n_args, keep_attn=True)
            self._step_fns[greedy_only] = fn
            self._recompile.watch(
                "serving.decode_greedy" if greedy_only
                else "serving.decode_sampled", fn)
        return fn

    def _fused_fn(self, greedy_only: bool):
        """The fused multi-step window: ``fuse_steps`` plain decode
        iterations as ONE compiled ``lax.scan``
        (``decoding.decode_fused_slots``), mirroring ``_decode_fn``'s
        greedy/sampled split. Returns ``(toks [S, K], cache, keys?,
        moe?)`` with the same routing-stats slot convention."""
        fn = self._fused_fns.get(greedy_only)
        if fn is None:
            module = self.module
            paged = self.kv_layout == "paged"
            page_len = self.page_len
            k = self.fuse_steps
            moe_kw = dict(
                moe_dispatched=self._moe_dispatched,
                moe_stats=self.max_len if self._moe_stats_on else None,
                paged_kernel=self._paged_kernel)
            stats_on = self._moe_stats_on

            if greedy_only:
                def body(params, state, cache, tok, t, stop, tables):
                    toks, cache, _, moe = decode_fused_slots(
                        module, params, state, cache, tok, t, stop, k,
                        table=tables, page_len=page_len or 0, **moe_kw)
                    return toks, cache, (moe if stats_on else None)

                if paged:
                    def fn(params, state, cache, tok, t, stop, tables):
                        return body(params, state, cache, tok, t, stop,
                                    tables)
                    n_args = 7
                else:
                    def fn(params, state, cache, tok, t, stop):
                        return body(params, state, cache, tok, t, stop,
                                    None)
                    n_args = 6
            else:
                if self.fused_sampling:
                    from distkeras_tpu.ops.sampling import sample_tokens
                    moe_kw = dict(moe_kw, sampler=sample_tokens)

                def body(params, state, cache, tok, t, stop, temp,
                         topk, topp, keys, tables):
                    toks, cache, keys, moe = decode_fused_slots(
                        module, params, state, cache, tok, t, stop, k,
                        table=tables, page_len=page_len or 0,
                        temperature=temp, top_k=topk, top_p=topp,
                        keys=keys, **moe_kw)
                    return toks, cache, keys, \
                        (moe if stats_on else None)

                if paged:
                    fn, n_args = body, 11
                else:
                    def fn(params, state, cache, tok, t, stop, temp,
                           topk, topp, keys):
                        return body(params, state, cache, tok, t, stop,
                                    temp, topk, topp, keys, None)
                    n_args = 10

            fn = self._jit_serving(fn, n_args, keep_attn=True)
            self._fused_fns[greedy_only] = fn
            self._recompile.watch(
                "serving.decode_fused_greedy" if greedy_only
                else "serving.decode_fused_sampled", fn)
        return fn

    def _verify_fn(self, greedy_only: bool):
        """Two compiled speculative-verify variants, mirroring
        ``_decode_fn``'s greedy/sampled split. Each scores the whole
        ``[S, k+1]`` window ``[tok, d_1 .. d_k]`` in one target
        forward and computes acceptance IN-PROGRAM:

        * greedy — candidates are per-position argmaxes; accepted
          count = the longest prefix where the target's own choice
          equals the draft (exact match, so the emitted stream is the
          plain greedy stream by construction);
        * sampled — one PRNG split per potentially emitted token, in
          the exact order plain decode would split (one per emitted
          token), with the slot's post-step key selected by the
          accepted count. Sampling from the target and accepting while
          it equals the (deterministic) draft IS exact rejection
          sampling for a point-mass draft distribution — and, unlike
          the general-q rule, keeps sampled streams byte-identical to
          plain decode, not merely distribution-equivalent.

        ``active`` force-rejects rows (accepted = 0), which makes a
        verify step exactly a plain decode step for opted-out /
        EMA-disabled slots — one program serves mixed batches."""
        fn = self._spec_fns.get(greedy_only)
        if fn is None:
            module = self.module
            paged = self.kv_layout == "paged"
            page_len = self.page_len
            k = self.spec_k
            moe_kw = dict(
                moe_dispatched=self._moe_dispatched,
                moe_stats=self.max_len if self._moe_stats_on else None)
            stats_on = self._moe_stats_on
            pk = self._paged_kernel

            def vstep(params, state, cache, toks, t, tables):
                if paged:
                    out = verify_step_slots_paged(
                        module, params, state, cache, toks, t, tables,
                        page_len, paged_kernel=pk, **moe_kw)
                else:
                    out = verify_step_slots(
                        module, params, state, cache, toks, t, **moe_kw)
                return out if stats_on else (out + (None,))

            def accept(cand, toks, active):
                # longest prefix of drafts matching the target's own
                # choices: cand[:, j] continues window position j, so
                # draft toks[:, j+1] is accepted iff it equals cand[:, j]
                match = (cand[:, :-1] == toks[:, 1:]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                return jnp.where(active, n_acc, 0)

            if greedy_only:
                def body(params, state, cache, toks, t, active, tables):
                    logits, cache, moe = vstep(params, state, cache,
                                               toks, t, tables)
                    cand = jnp.argmax(logits, axis=-1)     # [S, k+1]
                    return cand, accept(cand, toks, active), cache, moe

                if paged:
                    fn, n_args = body, 7
                else:
                    def fn(params, state, cache, toks, t, active):
                        return body(params, state, cache, toks, t,
                                    active, None)
                    n_args = 6
            else:
                def body(params, state, cache, toks, t, active, temp,
                         topk, topp, keys, tables):
                    logits, cache, moe = vstep(params, state, cache,
                                               toks, t, tables)
                    cands, carries = [], []
                    cur = keys
                    for j in range(k + 1):
                        split = jax.vmap(jax.random.split)(cur)
                        cur = split[:, 0]
                        cands.append(_sample_vec(
                            logits[:, j], temp, topk, topp,
                            split[:, 1]))
                        carries.append(cur)
                    cand = jnp.stack(cands, axis=1)        # [S, k+1]
                    n_acc = accept(cand, toks, active)
                    # the slot emitted n_acc + 1 tokens, so its key
                    # advanced n_acc + 1 splits — exactly what n_acc+1
                    # plain decode iterations would have done
                    new_keys = jnp.stack(carries, axis=1)[
                        jnp.arange(cand.shape[0]), n_acc]
                    return cand, n_acc, cache, new_keys, moe

                if paged:
                    fn, n_args = body, 11
                else:
                    def fn(params, state, cache, toks, t, active, temp,
                           topk, topp, keys):
                        return body(params, state, cache, toks, t,
                                    active, temp, topk, topp, keys,
                                    None)
                    n_args = 10

            fn = self._jit_serving(fn, n_args)
            self._spec_fns[greedy_only] = fn
            self._recompile.watch(
                "serving.verify_greedy" if greedy_only
                else "serving.verify_sampled", fn)
        return fn

    def _verify_tree_fn(self, greedy_only: bool):
        """The TREE counterparts of ``_verify_fn``'s two variants: one
        program runs the tree-masked verify forward
        (``verify_step_slots[_paged]`` with the ancestor mask), the
        in-program acceptance walk (``tree_walk`` — greedy argmax
        descent, or the exact point-mass rejection-sampling walk with
        one PRNG split per emitted token), and the accepted-path cache
        commit (``commit_tree_path``) — returning ``(emitted, n_emit,
        cache[, keys], moe)``. Slots whose tree has no draft nodes
        (opted out, EMA-disabled, clamped to depth 0) walk exactly one
        root step — a plain decode step — so mixed batches share the
        program, the linear path's ``active`` contract re-expressed as
        tree shape."""
        fn = self._tree_fns.get(greedy_only)
        if fn is None:
            module = self.module
            paged = self.kv_layout == "paged"
            page_len = self.page_len
            moe_kw = dict(
                moe_dispatched=self._moe_dispatched,
                moe_stats=self.max_len if self._moe_stats_on else None)
            stats_on = self._moe_stats_on
            pk = self._paged_kernel

            def vstep(params, state, cache, toks, t, depth, anc,
                      tables):
                tree = {"depth": depth, "anc": anc}
                if paged:
                    out = verify_step_slots_paged(
                        module, params, state, cache, toks, t, tables,
                        page_len, tree=tree, paged_kernel=pk, **moe_kw)
                else:
                    out = verify_step_slots(
                        module, params, state, cache, toks, t,
                        tree=tree, **moe_kw)
                if stats_on:
                    logits, cache, kvw, moe = out
                else:
                    (logits, cache, kvw), moe = out, None
                return logits, cache, kvw, moe

            if greedy_only:
                def body(params, state, cache, toks, t, parents, depth,
                         anc, tables):
                    logits, cache, kvw, moe = vstep(
                        params, state, cache, toks, t, depth, anc,
                        tables)
                    emitted, n_emit, path, _ = tree_walk(
                        logits, toks, parents)
                    cache = commit_tree_path(
                        cache, kvw, path, t, n_emit, table=tables,
                        page_len=page_len or 0)
                    return emitted, n_emit, cache, moe

                if paged:
                    fn, n_args = body, 9
                else:
                    def fn(params, state, cache, toks, t, parents,
                           depth, anc):
                        return body(params, state, cache, toks, t,
                                    parents, depth, anc, None)
                    n_args = 8
            else:
                def body(params, state, cache, toks, t, parents, depth,
                         anc, temp, topk, topp, keys, tables):
                    logits, cache, kvw, moe = vstep(
                        params, state, cache, toks, t, depth, anc,
                        tables)
                    emitted, n_emit, path, new_keys = tree_walk(
                        logits, toks, parents, temperature=temp,
                        top_k=topk, top_p=topp, keys=keys)
                    cache = commit_tree_path(
                        cache, kvw, path, t, n_emit, table=tables,
                        page_len=page_len or 0)
                    return emitted, n_emit, cache, new_keys, moe

                if paged:
                    fn, n_args = body, 13
                else:
                    def fn(params, state, cache, toks, t, parents,
                           depth, anc, temp, topk, topp, keys):
                        return body(params, state, cache, toks, t,
                                    parents, depth, anc, temp, topk,
                                    topp, keys, None)
                    n_args = 12
            fn = self._jit_serving(fn, n_args)
            self._tree_fns[greedy_only] = fn
            self._recompile.watch(
                "serving.verify_tree_greedy" if greedy_only
                else "serving.verify_tree_sampled", fn)
        return fn

    # --- speculation bookkeeping ------------------------------------------

    def _spec_eligible(self, req: Request) -> bool:
        """Could this request speculate (knob on, EMA has not killed
        it)? Slot-independent — used at begin_slot time too."""
        return (self._draft is not None and req.speculate
                and not req.spec_disabled)

    def _spec_slots(self):
        """Decoding slots that speculate THIS iteration. Demoted
        streams get their re-probe chance here (``spec_reprobe``) —
        the one place every decode iteration already inspects them."""
        out = []
        for slot, r in self.scheduler.running.items():
            if r.spec_disabled and self.spec_reprobe is not None:
                self._maybe_reprobe(r)
            if self._spec_eligible(r):
                out.append(slot)
        return out

    def _spec_disable(self, req: Request) -> None:
        """Per-request kill switch (adversarial-stream escape hatch):
        the stream decodes plainly from here on — sticky unless the
        engine was built with ``spec_reprobe``."""
        req.spec_disabled = True
        req.spec_disabled_at = len(req.generated)
        self.metrics.record_spec_disabled()
        if self._draft is not None and req.slot is not None:
            self._draft.end_slot(req.slot)

    #: re-probe coin odds: one in this many eligible positions fires
    #: (deterministic — a crc32 of (seed, rid, position), not an RNG
    #: draw, so replays reproduce the exact re-enable points)
    _SPEC_REPROBE_ONE_IN = 8

    def _maybe_reprobe(self, req: Request) -> None:
        """Probabilistic speculation re-enable (``spec_reprobe``): once
        a demoted stream has generated ``spec_reprobe`` further tokens,
        each position flips a deterministic coin; on success the stream
        rejoins speculation with a FRESH warm-up (EMA and check count
        reset — the kill switch gets a clean window to re-judge). If
        the draft cannot re-adopt the slot the stream re-demotes and
        the cooldown restarts. Token identity is untouched either way:
        verify accepts only target-matching tokens."""
        if (self._draft is None or not req.speculate
                or req.slot is None):
            return
        since = len(req.generated) - (req.spec_disabled_at or 0)
        if since < self.spec_reprobe:
            return
        coin = zlib.crc32(
            f"{req.seed}:{req.rid}:{len(req.generated)}".encode())
        if coin % self._SPEC_REPROBE_ONE_IN:
            return
        req.spec_disabled = False
        req.spec_disabled_at = None
        req.spec_ema = None
        req.spec_checks = 0
        if self._draft.begin_slot(req.slot, req.context_tokens):
            self.metrics.record_spec_reenabled()
        else:
            self._spec_disable(req)

    def _observe_acceptance(self, req: Request, rate: float) -> None:
        """Update the per-request acceptance EMA; below the floor after
        warm-up, speculation stops paying for itself (every verify
        step costs a (k+1)-wide forward to emit ~1 token) and the
        stream is kicked back to plain decode."""
        a = self._SPEC_EMA_ALPHA
        req.spec_ema = (rate if req.spec_ema is None
                        else (1.0 - a) * req.spec_ema + a * rate)
        req.spec_checks += 1
        if req.spec_checks >= self.spec_warmup \
                and req.spec_ema < self.spec_disable_below:
            self._spec_disable(req)

    #: EMA smoothing for per-request draft acceptance
    _SPEC_EMA_ALPHA = 0.25
    #: adaptive tree controller (spec_tree): EMA at-or-above widens a
    #: stream toward (spec_k, spec_width); below the demote line it
    #: narrows toward a depth-1 chain — full demotion to plain decode
    #: stays the existing spec_disable_below kill switch's job
    _TREE_PROMOTE_EMA = 0.6
    _TREE_DEMOTE_EMA = 0.25

    def _tree_shape(self, req: Request):
        """This request's tree shape for the NEXT verify: the adaptive
        controller's (depth, width), depth clamped so no accepted path
        can outrun the remaining token budget (``remaining - 1`` — the
        final emitted token is always the free bonus). Depth < 1 means
        the stream rides the window as a plain decode step this
        iteration."""
        if req.tree_depth is None:
            req.tree_depth = self.spec_k
            req.tree_width = self.spec_width
        remaining = req.max_new_tokens - len(req.generated)
        return min(req.tree_depth, remaining - 1), req.tree_width

    def _adapt_tree(self, req: Request) -> None:
        """Resize a stream's tree from its acceptance EMA: hot streams
        (EMA >= ``_TREE_PROMOTE_EMA``) deepen first, then widen — depth
        compounds on a well-predicted stream, width only pays at
        divergence points; cold streams (< ``_TREE_DEMOTE_EMA``) shed
        width first (side branches are the cheapest columns to stop
        wasting), then depth, demoting toward a 1-deep chain — the
        sticky EMA floor (``_observe_acceptance``) handles the final
        drop to plain decode. Gated on the SAME ``spec_warmup`` as the
        kill switch: a fresh stream's first verifies routinely miss
        (its n-gram history is still forming), and resizing off that
        transient collapsed trees the steady state would have kept
        wide."""
        ema = req.spec_ema
        if ema is None or req.spec_checks < self.spec_warmup:
            return
        if ema >= self._TREE_PROMOTE_EMA:
            if req.tree_depth < self.spec_k:
                req.tree_depth += 1
            elif req.tree_width < self.spec_width:
                req.tree_width += 1
        elif ema < self._TREE_DEMOTE_EMA:
            if req.tree_width > 1:
                req.tree_width -= 1
            elif req.tree_depth > 1:
                req.tree_depth -= 1

    #: prefill-program cache cap: every DISTINCT (q_len, t0, final)
    #: triple is its own XLA program (the final chunk's key differs for
    #: every prompt length, so a varied-length workload compiles one
    #: program per novel length — compilation runs inline in ``step()``
    #: and does stall in-flight streams for that iteration; production
    #: deployments should pre-warm or bucket prompt lengths,
    #: docs/serving.md follow-ups). The LRU cap bounds host memory at
    #: O(cap) retained executables instead of O(distinct lengths).
    MAX_PREFILL_PROGRAMS = 64

    def _prefill_fn(self, q_len: int, t0: int, final: bool):
        """Jitted prefill unit. A whole-prompt chunk (t0=0, final) is
        the SAME one-pass ``prefill`` program ``generate()`` runs, so
        staging caches match generate's bit-for-bit; interior chunks are
        ``prefill_chunk_step``. With a fixed ``prefill_chunk`` the
        interior chunks share ceil(max_len/chunk) programs; the ragged
        FINAL chunk is per-prompt-length (see MAX_PREFILL_PROGRAMS)."""
        key = (q_len, t0, final)
        fn = self._prefill_fns.pop(key, None)
        if fn is None:
            module = self.module
            if t0 == 0 and final:
                def f(params, state, cache, chunk):
                    return prefill(module, params, state, cache, chunk)
            else:
                def f(params, state, cache, chunk):
                    return prefill_chunk_step(module, params, state,
                                              cache, chunk, t0,
                                              final=final)
            # EP models shard_map-wrap here too: prefill runs the MoE
            # layers' own apply, which psums over the expert axis
            fn = self._jit_serving(f, 4)
        # re-insert at the back: dict order is the LRU order
        self._prefill_fns[key] = fn
        while len(self._prefill_fns) > self.MAX_PREFILL_PROGRAMS:
            self._prefill_fns.pop(next(iter(self._prefill_fns)))
        return fn

    def _sample_first_fn(self):
        """First-token sampler from prefill logits — mirrors generate's
        ``rng, sub = split(rng)`` order so a request's key stream does
        not depend on engine scheduling."""
        if self._first_fn is None:
            @jax.jit
            def f(logits, temp, topk, topp, rng):
                rng, sub = jax.random.split(rng)
                tok = _sample_vec(logits, temp[None], topk[None],
                                  topp[None], sub)
                return tok[0], rng

            self._first_fn = f
        return self._first_fn

    # --- paged admission / page budget ------------------------------------

    def _admit(self) -> List[Request]:
        """Admission for this iteration. Slab: FCFS into free slots.
        Paged: cost-aware — the highest-priority queued request admits
        while a slot AND its context's page budget are available
        (prefix-cache hits cost nothing: shared pages are reused, not
        allocated); when the budget is short, a strictly-higher-
        priority arrival preempts lower-priority decoding streams."""
        if self.kv_layout != "paged":
            admitted = self.scheduler.admit()
            if admitted:
                self._comp_ver += 1
            return admitted
        admitted: List[Request] = []
        sch = self.scheduler
        while sch.free_slots:
            req = sch.peek()
            if req is None:
                break
            plan = self._page_plan(req)
            if plan is not None:
                sch.admit_one(req)
                self._comp_ver += 1
                self._apply_page_plan(req, plan)
                admitted.append(req)
                continue
            if not self._preempt_victim(beneficiary=req,
                                        strict_priority=True):
                break
        return admitted

    def _page_plan(self, req: Request) -> Optional[Dict]:
        """Fund ``req``'s (re)admission from the page budget: prefix-
        match its context, reclaim cache-only pages if the private
        remainder does not fit, allocate. None when it cannot be
        funded (the caller may preempt and retry next iteration).

        Matched pages are incref'd HERE, before any reclaim — the
        reclaim sweep frees cache-only (ref == 1) pages and must never
        eat the chain this very plan is about to use.

        A preemption victim whose pages were SWAPPED OUT (offload PR)
        is funded differently: it needs exactly its swapped page
        count back (no prefix match, no +1 growth page — the snapshot
        already covers the next write), and its resume is an H2D copy
        instead of a re-prefill."""
        pool = self.pool
        swap = getattr(req, "_swap", None)
        if swap is not None:
            n = len(swap["host"])
            need = n + self._moe_admit_extra(req, n)
            if pool.free_pages < need and self.prefix is not None:
                deficit = need - pool.free_pages
                if self.prefix.evictable_pages() >= deficit:
                    self.prefix.reclaim(deficit)
            if pool.free_pages < need:
                return None
            priv = [pool.alloc_page() for _ in range(n)]
            return {"restore": True, "full": [], "priv": priv,
                    "shared_len": 0, "donor": None}
        toks = req.context_tokens
        # context + 1: the first decode write (position len(toks))
        # must land on an allocated page
        n_logical = pool.pages_for(len(toks) + 1)
        if self.prefix is not None:
            full, shared_len, donor = self._match_prefix(toks)
        else:
            full, shared_len, donor = [], 0, None
        for pid in full:
            pool.incref(pid)             # the slot's hold, owned early
        if donor is not None:
            pool.incref(donor)           # held until loaded to staging
        n_private = n_logical - len(full)
        # MoE-aware admission cost: under concentrated routing the
        # free-page budget must also show headroom pages (never
        # allocated — just required free) before this stream admits
        need = n_private + self._moe_admit_extra(req, n_logical)
        if pool.free_pages < need and self.prefix is not None:
            deficit = need - pool.free_pages
            # reclaim ONLY when it can actually close the gap: an
            # unfundable admission must not drain the reusable prefix
            # cache for nothing (it would strip sharing from every
            # later same-template request while the head stays queued)
            if self.prefix.evictable_pages() >= deficit:
                self.prefix.reclaim(deficit)
        if pool.free_pages < need:
            for pid in full:
                pool.decref(pid)
            if donor is not None:
                pool.decref(donor)
            return None
        priv = [pool.alloc_page() for _ in range(n_private)]
        return {"full": full, "priv": priv, "shared_len": shared_len,
                "donor": donor}

    def _match_prefix(self, toks):
        """``PrefixCache.match`` with the engine's partial-match
        granularity applied: the copy-on-write match length rounds
        down to a multiple of ``prefix_granularity`` (0 drops the
        donor), bounding how many distinct residual-chunk shapes —
        each an inline compile on first sight — sharing can mint."""
        full, shared_len, donor = self.prefix.match(toks)
        g = self._prefix_granularity
        if donor is not None and g > 1:
            base = len(full) * self.pool.page_len
            m = ((shared_len - base) // g) * g
            shared_len = base + m
            if m == 0:
                donor = None
        return full, shared_len, donor

    def _rematch_at_prefill(self, req: Request) -> None:
        """Prefix pages REGISTERED between this request's admission and
        its prefill turn (requests ahead of it in the single prefill
        stream — the common case in a burst of same-template arrivals)
        are adopted late: re-match, swap the private pages the longer
        chain now covers for the shared ones, return the privates to
        the budget."""
        pool = self.pool
        toks = req.context_tokens
        full, shared_len, donor = self._match_prefix(toks)
        if shared_len <= getattr(req, "_shared_len", 0):
            return
        old_full = getattr(req, "_n_shared_full", 0)
        slot = req.slot
        for j in range(old_full, len(full)):
            old = int(pool.tables[slot, j])
            pool.incref(full[j])
            pool.assign(slot, j, full[j])
            pool.decref(old)                  # private page, freed
        old_donor = getattr(req, "_donor_ref", None)
        if old_donor is not None:
            pool.decref(old_donor)
            req._donor_ref = None
        if donor is not None:
            pool.incref(donor)
            req._donor_ref = donor
        req._shared_len = shared_len
        req._n_shared_full = len(full)
        req._load_pages = list(full) + (
            [donor] if donor is not None else [])

    def _apply_page_plan(self, req: Request, plan: Dict) -> None:
        slot = req.slot
        pool = self.pool
        if plan.get("restore"):
            # swap resume: the fresh pages land on the SAME logical
            # indices the snapshot captured — the table restore half
            # of the swap-in (the H2D payload copy runs at the
            # request's prefill turn, _advance_prefill). Prefix-
            # resident pages re-link in place: the snapshot's refcount
            # hold becomes the slot's table hold (released like any
            # slot page at the next release_slot)
            for lp, pid in zip(req._swap["logical"], plan["priv"]):
                pool.assign(slot, int(lp), pid)
            for lp, pid in req._swap.get("shared", ()):
                pool.assign(slot, int(lp), int(pid))
            req._shared_len = 0
            req._n_shared_full = 0
            req._load_pages = []
            req._donor_ref = None
            return
        for j, pid in enumerate(plan["full"]):
            pool.assign(slot, j, pid)    # ref taken in _page_plan
        for i, pid in enumerate(plan["priv"]):
            pool.assign(slot, len(plan["full"]) + i, pid)
        req._shared_len = plan["shared_len"]
        req._n_shared_full = len(plan["full"])
        # pages to materialize into the staging cache before prefill:
        # the full shared chain plus the copy-on-write donor (whose
        # temporary ref drops once the load has happened)
        req._donor_ref = plan["donor"]
        req._load_pages = list(plan["full"]) + (
            [plan["donor"]] if plan["donor"] is not None else [])

    def _preempt_victim(self, beneficiary: Request,
                        strict_priority: bool) -> bool:
        """Preempt ONE admitted request (decoding or mid-prefill —
        both hold budget pages) to free pages for ``beneficiary``:
        the lowest-priority, youngest victim. ``strict_priority``
        (admission path) only sacrifices strictly lower-priority
        streams. The decode-growth path also preempts within the
        class (youngest first) and INCLUDES the beneficiary itself:
        when the beneficiary is the worst-ranked stream alive, it is
        the one evicted — growing it at a higher-priority neighbour's
        expense would invert the priority the scheduler promises.
        Either way the best-ranked stream is never a victim, so it
        runs to completion — the progress guarantee that makes
        preemption deadlock-free."""
        victim = None
        candidates = list(self.scheduler.running.values()) \
            + list(self.scheduler.prefilling)
        for r in candidates:
            if strict_priority and (
                    r is beneficiary
                    or r.priority <= beneficiary.priority):
                continue
            if victim is None \
                    or (r.priority, r.rid) > (victim.priority, victim.rid):
                victim = r
        if victim is None:
            return False
        self._preempt(victim)
        return True

    def _preempt(self, victim: Request) -> None:
        """Evict an admitted request's pages back to the queue. Its
        generated tokens stay (the re-prefill context); a decoding
        victim's per-slot sampling key is snapshotted so a sampled
        stream resumes EXACTLY where it left off (schedule-independent
        draws); a prefilling victim keeps its submit-time key (its
        first token has not been sampled yet)."""
        # the snapshot below (generated tokens, sampling key) must see
        # the in-flight step's outputs — drain the pipeline first
        self._flush_pending()
        if victim.state in TERMINAL_STATES:
            return               # the flush finished (or expired) it
        slot = victim.slot
        if victim.state is RequestState.DECODING:
            victim.rng = np.array(self._keys[slot])
        # host KV offload (offload PR): a DECODING victim's pages swap
        # out D2H before release, so resume is an H2D page copy +
        # table restore instead of a full context re-prefill — byte-
        # identical (the pages move, nothing recomputes). Prefilling
        # victims hold no written pool pages (prefill writes staging);
        # they keep the re-prefill path. Falls through silently when
        # the host tier is off or full — the swap is an accelerator,
        # never a correctness dependency.
        #
        # PREFIX-AWARE snapshot (tree-speculation PR satellite,
        # closing the PR-17 trade-off): pages still RESIDENT in the
        # prefix cache are not copied at all — the snapshot takes a
        # refcount hold instead (pinning them against spill/drop: both
        # need ref == 1) and resume re-links them into the table, the
        # hold becoming the slot's. Only the private remainder moves
        # D2H, so a shared-prefix-heavy victim swaps a fraction of its
        # context and duplicates nothing on resume.
        swapped = 0
        if victim.state is RequestState.DECODING \
                and self.kv_layout == "paged" \
                and self.pool.host_cache is not None:
            row = self.pool.tables[slot]
            logical = np.where(row < self.pool.num_pages)[0]
            shared, priv = [], []
            for lp in logical.tolist():
                pid = int(row[lp])
                if self.prefix is not None and self.prefix.resident(pid):
                    shared.append((lp, pid))
                else:
                    priv.append(lp)
            hids = (self.pool.offload_pages(row[priv].tolist())
                    if priv else [])
            if hids is not None:
                for _lp, pid in shared:
                    self.pool.incref(pid)       # the snapshot's hold
                victim._swap = {"host": hids, "logical": priv,
                                "shared": shared,
                                "t": int(self._t[slot])}
                swapped = len(hids)
                self.tracer.on_swap_out(victim.rid, swapped)
        self.scheduler.preempt(victim)
        self._comp_ver += 1
        self._chain_dirty[slot] = True
        if self._draft is not None:
            self._draft.end_slot(slot)   # draft KV freed with the slot
        freed = self.pool.release_slot(slot)
        self._t[slot] = self.max_len          # sentinel: slot inert
        if getattr(victim, "_donor_ref", None) is not None:
            # admitted with a copy-on-write donor hold that prefill
            # never consumed
            self.pool.decref(victim._donor_ref)
            victim._donor_ref = None
        victim._shared_len = 0
        victim._n_shared_full = 0
        victim._load_pages = []
        self.metrics.record_preemption(victim.rid)
        self.tracer.on_preempt(victim.rid, len(victim.generated))
        if self.recorder.enabled:
            self.recorder.record(
                "serving.preempted", engine=self.engine_id,
                rid=victim.rid, slot=slot,
                n_generated=len(victim.generated), pages_freed=freed,
                pages_free=self.pool.free_pages,
                pages_swapped=swapped)

    def _ensure_decode_pages(self, lookahead=None) -> None:
        """Before a decode step: every running slot whose next write
        position crosses into an unallocated logical page gets one —
        from the free list, then by reclaiming cache-only prefix
        pages, then by preempting the youngest lowest-priority OTHER
        stream. Serviced oldest-highest-priority first, so pressure
        lands on the back of the line.

        ``lookahead`` ([S] ints, speculative iterations): the verify
        step also writes positions ``t+1 .. t+lookahead[slot]``, so
        every logical page under that span must be allocated — a
        dropped write there would silently corrupt an ACCEPTED draft's
        KV. The engine passes ``min(spec_k, remaining_budget - 1)``
        per speculating slot: pages are only ever demanded for
        positions the slot could actually consume (verify writes
        beyond that may drop — their candidates are discarded
        host-side)."""
        pool = self.pool
        running = self.scheduler.running
        if not running:
            return
        # steady-state fast path (zero-bubble PR): ONE vectorized scan
        # over the numpy table/position mirrors decides "no growth
        # needed" — the common case — without the per-slot int() loop
        # that used to cost O(num_slots) Python per iteration
        slots = np.fromiter(running.keys(), np.int64, len(running))
        t = self._t[slots].astype(np.int64)
        hi = t if lookahead is None else t + lookahead[slots]
        hi = np.minimum(hi, pool.pages_per_slot * pool.page_len - 1)
        lp = pool.page_index
        span = (lp >= (t // pool.page_len)[:, None]) \
            & (lp <= (hi // pool.page_len)[:, None])
        if not (span & (pool.tables[slots] >= pool.num_pages)).any():
            return
        by_rank = sorted(running.values(),
                         key=lambda r: (r.priority, r.rid))
        for req in by_rank:
            if req.state is not RequestState.DECODING:
                continue                      # preempted this pass
            slot = req.slot
            t = int(self._t[slot])
            hi = t if lookahead is None else t + int(lookahead[slot])
            hi = min(hi, pool.pages_per_slot * pool.page_len - 1)
            for lp in range(t // pool.page_len,
                            hi // pool.page_len + 1):
                if req.state is not RequestState.DECODING:
                    break                     # self-preempted below
                if pool.tables[slot, lp] < pool.num_pages:
                    continue                  # page already allocated
                while True:
                    pid = pool.alloc_page()
                    if pid is not None:
                        pool.assign(slot, lp, pid)
                        break
                    if self.prefix is not None \
                            and self.prefix.evict_one():
                        continue
                    if not self._preempt_victim(beneficiary=req,
                                                strict_priority=False):
                        raise RuntimeError(
                            "page pool exhausted: no free page, nothing "
                            "evictable, no preemptable stream (submit "
                            "validation should have prevented this)")
                    if req.state is not RequestState.DECODING:
                        break    # the beneficiary was the worst-ranked
                        #          stream and preempted ITSELF; its
                        #          pages are back in the budget

    def _fragmentation(self) -> float:
        """Wasted tail positions across live slots: 1 - used/allocated
        (an allocated page holds ``page_len`` positions; the slot uses
        ``t`` of them so far). 0 = perfectly packed."""
        pool = self.pool
        sch = self.scheduler
        used = alloc = 0
        if sch.running:
            # vector numpy over the table/position mirrors — no
            # per-slot python loop (zero-bubble PR)
            slots = np.fromiter(sch.running.keys(), np.int64,
                                len(sch.running))
            alloc += int((pool.tables[slots] < pool.num_pages).sum())
            used += int(self._t[slots].sum())
        if sch.prefilling:
            pslots = np.fromiter((r.slot for r in sch.prefilling),
                                 np.int64, len(sch.prefilling))
            alloc += int((pool.tables[pslots] < pool.num_pages).sum())
            used += sum(r.prefill_pos for r in sch.prefilling)
        if alloc == 0:
            return 0.0
        return max(0.0, 1.0 - used / (alloc * pool.page_len))

    # --- the scheduler iteration ------------------------------------------

    def step(self) -> List[Request]:
        """One iteration: expire deadlines, admit, advance ONE prefill
        chunk, run one decode step over all slots. Returns requests that
        reached a terminal state during this iteration (FINISHED,
        TIMED_OUT or CANCELLED — check ``req.state``).

        Error isolation: an exception while advancing ONE request's
        prefill (a poisoned prompt, an injected ``serving.prefill``
        fault) cancels that request and recycles its slot; in-flight
        decode streams are untouched and keep emitting token-identical
        output. A decode-step error is batch-wide and not attributable
        to one request, so it propagates — but it is raised before any
        engine state mutates, so ``step()`` can simply be called again
        (the failed iteration retries wholesale)."""
        finished: List[Request] = []
        if self._finish_buf:
            # terminals produced by out-of-band pipeline flushes
            # (cancel, preemption, metrics swap) since the last step
            finished.extend(self._finish_buf)
            self._finish_buf.clear()
        self._expire_deadlines(finished)
        admitted = self._admit()
        # flight-recorder ring entry (composition-cached, window
        # cadence in steady state — see _record_iteration). Paged
        # engines add the free-page count: an admission stall in a
        # post-mortem dump reads directly as "queue grew while pages
        # sat at N" (budget starvation) vs "pages free, slots full"
        self._record_iteration(admitted)

        req = self.scheduler.next_prefill()
        if req is not None:
            with self.metrics.timer.phase("prefill"), \
                    obs.span("serving.prefill"):
                try:
                    self._advance_prefill(req, finished)
                except Exception as e:
                    self._poison(req, e, finished)

        running = self.scheduler.running
        if running:
            with self.metrics.timer.phase("decode"), \
                    obs.span("serving.decode"):
                self._advance_decode(finished)

        # per-iteration samples land in the deferred buffers; the live
        # window sees them on the host-window cadence (every iteration
        # when overlap is off) and whenever the engine drains idle
        self._iter_buf.append((self.scheduler.queue_depth,
                               self.scheduler.occupied))
        self._iters += 1
        if self._iters % self._host_window == 0 \
                or not self.scheduler.pending:
            self._flush_host_window()
            if self.timeseries is not None:
                # piggybacks on the flush cadence just paid: pure
                # host-side registry reads, zero added device syncs
                self.timeseries.maybe_sample(iteration=self._iters)
        if self._iters % self._RECOMPILE_CHECK_EVERY == 0:
            self._recompile.check()
        if self.slo is not None \
                and self._iters % self._SLO_EVAL_EVERY == 0:
            self._flush_host_window()
            self.slo.evaluate(self.metrics)
        if self._finish_buf:
            # a mid-iteration flush (preemption funding, deadline
            # sweep) finished requests: return them from THIS step
            finished.extend(self._finish_buf)
            self._finish_buf.clear()
        return finished

    def run(self, max_steps: Optional[int] = None,
            on_degraded: str = "raise") -> Dict[int, np.ndarray]:
        """Drive ``step()`` until every submitted request reaches a
        terminal state; returns ``{rid: tokens}`` for requests drained
        during this call.

        A request that ends TIMED_OUT or CANCELLED raises
        ``DegradedRequest`` (default) — its empty/partial token array
        must not be indistinguishable from a finished one in the plain
        tokens dict. Pass ``on_degraded="return"`` to include partial
        tokens instead, or drive ``step()`` directly to observe
        per-request terminal states."""
        if on_degraded not in ("raise", "return"):
            raise ValueError(
                f"on_degraded must be 'raise' or 'return', "
                f"got {on_degraded!r}")
        out: Dict[int, np.ndarray] = {}
        steps = 0
        while self.scheduler.pending:
            for r in self.step():
                if r.state is not RequestState.FINISHED \
                        and on_degraded == "raise":
                    # crash forensics: snapshot the ring before the
                    # degraded drain surfaces to the caller
                    self.recorder.auto_dump(
                        f"degraded_request:{r.state.value}")
                    raise DegradedRequest(r)
                out[r.rid] = r.tokens
            steps += 1
            if max_steps is not None and steps >= max_steps \
                    and self.scheduler.pending:
                raise RuntimeError(
                    f"engine made no full drain in {max_steps} steps "
                    f"(queue={self.scheduler.queue_depth}, "
                    f"occupied={self.scheduler.occupied})")
        return out

    # --- degradation paths ------------------------------------------------

    def _expire_deadlines(self, finished: List[Request]) -> None:
        """Terminate every in-flight request whose ``deadline_s`` has
        expired (engine clock), freeing its slot for queued work. A
        timed-out request keeps the tokens it generated so far."""
        now_ = self.metrics.clock()
        expired = [r for r in self._requests.values()
                   if r.deadline_s is not None
                   and now_ - r.submit_t >= r.deadline_s]
        if not expired:
            return
        # the expiring requests' in-flight tokens must land first (a
        # timed-out request keeps everything it generated) — and the
        # flush may FINISH one of them, beating the deadline
        self._flush_pending(finished)
        for r in expired:
            if r.rid not in self._requests:
                continue                 # finished during the flush
            self._terminate(r, RequestState.TIMED_OUT, finished)
            self.metrics.record_timeout(r.rid)

    def _poison(self, req: Request, err: Exception,
                finished: List[Request]) -> None:
        """Per-request work failed: quarantine THIS request (CANCELLED,
        ``req.error`` holds the cause), recycle its slot, leave every
        other stream untouched."""
        if req.state in TERMINAL_STATES:
            raise err    # already terminal — nothing to isolate
        self._terminate(req, RequestState.CANCELLED, finished, error=err)
        self.metrics.record_cancelled(req.rid)

    def cancel(self, rid: int) -> Request:
        """Cancel an in-flight request by id (client disconnect etc.);
        returns the terminal Request (evicted from the engine)."""
        req = self._requests[rid]
        # land the in-flight tokens first (partial output is part of
        # the cancel contract); the flush may FINISH the request, in
        # which case the terminal FINISHED record wins
        self._flush_pending()
        if rid not in self._requests:
            for i, r in enumerate(self._finish_buf):
                if r.rid == rid:
                    return self._finish_buf.pop(i)
            raise KeyError(rid)          # unreachable: flush owns it
        out: List[Request] = []
        self._terminate(req, RequestState.CANCELLED, out)
        self.metrics.record_cancelled(rid)
        return out[0]

    # --- replica handoff (serving-router PR) ------------------------------

    def transfer_out(self, rid: int) -> Optional[Request]:
        """Detach a LIVE request from this engine so another engine can
        ``transfer_in`` it — the serving router's handoff primitive
        (prefill→decode disaggregation, drain rebalancing). An admitted
        request first leaves through the proven preempt path (pipeline
        drained, pages freed, sampling key snapshotted on ``req.rng``),
        then exits the queue and the engine entirely; a queued request
        just leaves the queue. Returns the detached ``Request``
        (QUEUED, slotless — ready for ``transfer_in``), or None when
        draining the pipeline FINISHED the request instead (it will be
        returned by this engine's next ``step()`` like any terminal)."""
        req = self._requests[rid]
        if req.state in (RequestState.PREFILLING,
                         RequestState.DECODING):
            if self.kv_layout != "paged":
                raise RuntimeError(
                    "transfer_out of an admitted request needs the "
                    "paged engine (the resumable re-prefill path)")
            self._preempt(req)
            if req.state in TERMINAL_STATES:
                return None          # the pipeline flush finished it
        # any swap record — from the preempt above OR from an
        # earlier preemption while the request sat QUEUED — holds
        # pages in THIS engine's host pool (and refcount holds on
        # prefix-resident pages), which a foreign engine cannot use:
        # drop them so the handoff rides the re-prefill resume (page
        # SHIPPING over a transport is the router follow-up this
        # machinery is built for; docs/serving.md §Router)
        self._drop_swap(req)
        if req.state is not RequestState.QUEUED:
            raise RuntimeError(
                f"cannot transfer request {rid} in state "
                f"{req.state.value!r}")
        self.scheduler.waiting.remove(req)
        del self._requests[rid]
        self.metrics.record_transfer(rid)
        # ticks precede terminals (the _finish rule): the deferred
        # host-window buffers may hold this request's decode ticks,
        # and on_terminal retires its timeline — flush first or the
        # transferred timeline undercounts decode_iters
        self._flush_host_window()
        self.tracer.on_terminal(rid, "transferred", len(req.generated))
        if self.recorder.enabled:
            self.recorder.record(
                "serving.transferred", engine=self.engine_id, rid=rid,
                n_generated=len(req.generated))
        return req

    def transfer_in(self, req: Request) -> int:
        """Admit a request detached from another engine
        (``transfer_out``) or reconstructed by the router after a
        replica death. Re-entry is exactly the preemption-resume
        contract: the context (``prompt + generated[:-1]``) re-prefills
        HEAD-LESS here and decode continues from ``req.rng`` — token-
        identically (byte-identically for sampled streams) to an
        uninterrupted single-engine run. Mints a fresh LOCAL rid
        (returned; the router keeps the stable fleet-wide id). A
        ``deadline_s`` restarts on this engine's clock — cross-replica
        deadline budgets are the router's concern. Raises
        ``AdmissionRejected`` when this engine's bounded queue is full
        (the router then tries the next replica)."""
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("request prompt is empty")
        if prompt.size + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the slot capacity "
                f"max_len={self.max_len}")
        if req.generated and self.kv_layout != "paged":
            raise ValueError(
                "transfer_in of a decode-progress request needs the "
                "paged engine (the resumable re-prefill path)")
        if self.kv_layout == "paged":
            worst = self.pool.pages_for(prompt.size + req.max_new_tokens)
            if worst > self.pool.num_pages:
                raise ValueError(
                    f"request needs up to {worst} pages but the pool "
                    f"holds {self.pool.num_pages}")
        req.prompt = prompt
        req.rid = next(self._rid)
        req.slot = None
        req.prefill_pos = 0
        req.error = None
        # scrub SOURCE-engine-local bookkeeping: shared-prefix lengths
        # and page ids refer to the other engine's pool — stale values
        # here would make this engine's prefill load foreign page ids
        req._shared_len = 0
        req._n_shared_full = 0
        req._load_pages = []
        req._donor_ref = None
        # a swap record refers to the SOURCE engine's host pool
        # (transfer_out frees it; a router death-failover request may
        # still carry one from its dead engine) — restoring it here
        # would read THIS pool's unrelated host rows
        req._swap = None
        if req.rng is None:
            req.rng = jax.random.PRNGKey(req.seed)
        try:
            self.scheduler.submit(req)
        except AdmissionRejected:
            self.metrics.record_rejected()
            self.tracer.on_reject()
            self.recorder.note_rejection(
                rid=req.rid, engine=self.engine_id,
                queue_depth=self.scheduler.queue_depth,
                max_queue=self.scheduler.max_queue)
            raise
        self._requests[req.rid] = req
        req.submit_t = self.metrics.clock()
        self.metrics.record_submit(req.rid)
        self.tracer.on_submit(req.rid, self.scheduler.queue_depth)
        return req.rid

    def _terminate(self, req: Request, state, finished: List[Request],
                   error: Optional[BaseException] = None) -> None:
        """Shared terminal transition for the degradation paths: move
        the request out of the scheduler (freeing its slot when it holds
        one), park the slot's decode vector on the inert sentinel, and
        evict the request from the engine — the caller owns it from
        here, exactly like ``_finish``."""
        had_slot = req.state in (RequestState.PREFILLING,
                                 RequestState.DECODING)
        self.scheduler.cancel(req, state)
        self._comp_ver += 1
        if had_slot:
            self._t[req.slot] = self.max_len   # sentinel: slot inert
            self._chain_dirty[req.slot] = True
            if self._draft is not None:
                self._draft.end_slot(req.slot)
            if self.kv_layout == "paged":
                self.pool.release_slot(req.slot)
        if getattr(req, "_donor_ref", None) is not None:
            # admitted with a copy-on-write donor hold but terminated
            # before its prefill turn consumed it
            self.pool.decref(req._donor_ref)
            req._donor_ref = None
        # preempted-and-swapped but terminated (deadline, cancel)
        # before the swap-in consumed the host copy / shared holds
        self._drop_swap(req)
        req.error = error
        self.tracer.on_terminal(req.rid, state.value,
                                len(req.generated))
        del self._requests[req.rid]
        finished.append(req)

    def health(self) -> Dict:
        """Readiness snapshot for load balancers / probes, built on the
        unified ``obs.telemetry_snapshot()``: is the engine accepting
        work, how deep is the queue, and the degradation tally of the
        CURRENT metrics window. ``status`` is ``"ok"`` while admission
        is open, ``"saturated"`` once the bounded queue is full (a
        probe should stop routing new traffic here until it drains),
        and ``"degraded"`` while accepting but in breach of a declared
        SLO (``slo=`` objectives; the principled load-shed/reroute
        trigger — a probe keeps the instance but weights traffic
        away). The ``slo`` key carries the freshly evaluated
        per-objective status (None without objectives)."""
        self._flush_host_window()    # deferred samples land first
        sch = self.scheduler
        accepting = (sch.max_queue is None
                     or sch.queue_depth < sch.max_queue)
        m = self.metrics
        # record=False: a probe is a READ — it must not append to the
        # SLO history, restamp gauges or count breach transitions, or
        # the numbers would depend on how often a balancer polls
        slo_status = (None if self.slo is None
                      else self.slo.evaluate(m, record=False))
        breaching = bool(slo_status) and any(
            st["breach"] for st in slo_status.values())
        status = ("saturated" if not accepting
                  else "degraded" if breaching else "ok")
        out = {
            "status": status,
            "accepting": accepting,
            "slo": slo_status,
            "queue_depth": sch.queue_depth,
            "max_queue": sch.max_queue,
            "slots": {"total": self.num_slots, "occupied": sch.occupied,
                      "free": self.num_slots - sch.occupied},
            "requests": {"in_flight": len(self._requests),
                         "finished": m.requests_finished,
                         "rejected": m.requests_rejected,
                         "timed_out": m.requests_timed_out,
                         "cancelled": m.requests_cancelled,
                         "preempted": m.requests_preempted},
            "telemetry": obs.telemetry_snapshot(),
        }
        if self._moe:
            out["moe"] = {
                "decode": self.moe_decode,
                "layers": len(self._moe),
                "concentration": (None if self._moe_conc is None
                                  else round(self._moe_conc, 4)),
                "expert_parallel": (None if self._ep_mesh is None
                                    else int(self._ep_mesh.shape[
                                        self._ep_axis]))}
        if self.kv_layout == "paged":
            pool = self.pool
            out["pages"] = {
                "total": pool.num_pages, "free": pool.free_pages,
                "shared": pool.shared_pages,
                "page_len": pool.page_len,
                "fragmentation": round(self._fragmentation(), 4),
                # host offload tier (additive key): None when off
                "host": (None if pool.host_cache is None else {
                    "total": pool.host_pages,
                    "free": pool.host_free_pages,
                    "offloaded": pool.pages_offloaded,
                    "restored": pool.pages_restored})}
            out["prefix_cache"] = (
                None if self.prefix is None else {
                    "nodes": len(self.prefix),
                    "hit_rate": m.prefix_hit_rate})
        return out

    # --- internals --------------------------------------------------------

    def _advance_prefill(self, req: Request, finished: List[Request]):
        # chaos hook: an injected raise here exercises the
        # poisoned-request isolation in step(); an injected stall is the
        # slow-prefill scenario (queue grows, deadlines/shedding engage)
        faults.point("serving.prefill")
        paged = self.kv_layout == "paged"
        swap = getattr(req, "_swap", None) if paged else None
        if swap is not None:
            # swap-in resume (offload PR): the preemption snapshot
            # copies H2D into the pages _apply_page_plan already wired
            # into the table — token-identical BY CONSTRUCTION (the
            # exact cache bytes return; nothing is recomputed), where
            # the re-prefill path below is token-identical by the
            # chunked-prefill oracle. No prefill chunk ever runs: the
            # whole resume is this one copy + the vector restores.
            t0_ = self.metrics.clock()
            row = self.pool.tables[req.slot]
            dev = [int(row[int(lp)]) for lp in swap["logical"]]
            self.pool.restore_pages(swap["host"], dev)
            self.pool.free_host(swap["host"])
            req._swap = None
            s = req.slot
            self.scheduler.to_decoding(req)
            self._comp_ver += 1
            self._tok[s] = req.generated[-1]
            self._t[s] = swap["t"]
            self._temp[s] = req.temperature
            self._topk[s] = req.top_k
            self._topp[s] = req.top_p
            self._stop[s] = req.stop_token
            self._keys[s] = np.array(req.rng)
            self._chain_dirty[s] = True    # host owns the next input
            self._begin_draft(req, req.context_tokens)
            self.metrics.record_swap_resume(
                self.metrics.clock() - t0_, len(req.context_tokens))
            self.tracer.on_swap_in(req.rid, len(dev))
            self.tracer.on_resume(req.rid)
            return
        # paged context = prompt, or prompt + generated[:-1] after a
        # preemption (the resumable-prefill recompute path)
        toks = req.context_tokens if paged else req.prompt
        p_len = len(toks)
        resume = paged and bool(req.generated)
        if resume and req.prefill_pos == 0 \
                and getattr(req, "_resume_t0", None) is None:
            # re-prefill resume clock: first recompute chunk ->
            # rejoining the decode batch (the number the offload
            # bench's resume-latency rider compares against swap-in)
            req._resume_t0 = self.metrics.clock()
        if paged and req.prefill_pos == 0:
            if self.prefix is not None:
                # pages registered since this request's admission plan
                # (by requests ahead of it in the prefill stream) are
                # adopted here — the burst-of-identical-prompts case
                self._rematch_at_prefill(req)
                self.metrics.record_prefix_lookup(
                    getattr(req, "_shared_len", 0), p_len)
            if getattr(req, "_shared_len", 0):
                # prefix-cache hit: materialize the shared pages (and
                # the copy-on-write donor) into the staging cache once,
                # then skip straight to the first non-shared position —
                # the shared tokens' prefill compute never runs
                self._staging = self.pool.load_prefix(
                    self._staging, req._load_pages, req._shared_len)
                req.prefill_pos = req._shared_len
                self.tracer.on_prefix_hit(req.rid, req._shared_len)
            if getattr(req, "_donor_ref", None) is not None:
                # the donor's content is in staging now; its hold
                # (taken at planning so reclaim/eviction could not
                # free it first) is no longer needed
                self.pool.decref(req._donor_ref)
                req._donor_ref = None
        t0 = req.prefill_pos
        chunk = self.prefill_chunk
        if chunk is None:
            q_len, final = p_len - t0, True
        else:
            q_len = min(chunk, p_len - t0)
            final = t0 + q_len >= p_len
        # a resume re-prefill never needs logits (its tokens are
        # already decided), so every chunk runs head-less
        fn = self._prefill_fn(q_len, t0, final and not resume)
        chunk_toks = jnp.asarray(toks[None, t0:t0 + q_len])
        logits, self._staging = fn(self._params, self._state,
                                   self._staging, chunk_toks)
        req.prefill_pos = t0 + q_len
        self.metrics.record_prefill_chunk()
        self.tracer.on_prefill_chunk(req.rid, t0, q_len)
        if not final:
            return
        if paged:
            # write ONLY the pages the context fills, minus the shared
            # prefix pages that already hold identical data (the
            # copy-on-write donor's logical page IS written — into the
            # request's private copy)
            self.pool.insert_pages(self._staging, req.slot,
                                   getattr(req, "_n_shared_full", 0),
                                   p_len)
            if self.prefix is not None:
                # full context pages are immutable from here (decode
                # writes start at p_len): share them forward
                self.prefix.register(toks, self.pool.tables[req.slot])
        else:
            self.pool.insert(self._staging, req.slot, n_pos=p_len)
        s = req.slot
        if resume:
            # re-admission after preemption: skip first-token sampling
            # (TTFT fired long ago), restore the decode vectors and the
            # snapshotted sampling key, rejoin the batch
            self.scheduler.to_decoding(req)
            self._comp_ver += 1
            self._tok[s] = req.generated[-1]
            self._t[s] = p_len
            self._temp[s] = req.temperature
            self._topk[s] = req.top_k
            self._topp[s] = req.top_p
            self._stop[s] = req.stop_token
            self._keys[s] = np.array(req.rng)
            self._chain_dirty[s] = True    # host owns the next input
            self._begin_draft(req, toks)
            t0_ = getattr(req, "_resume_t0", None)
            if t0_ is not None:
                self.metrics.record_reprefill_resume(
                    self.metrics.clock() - t0_,
                    p_len - getattr(req, "_shared_len", 0))
                req._resume_t0 = None
            self.tracer.on_resume(req.rid)
            return
        first, req.rng = self._sample_first_fn()(
            logits, jnp.float32(req.temperature),
            jnp.int32(req.top_k), jnp.float32(req.top_p), req.rng)
        token = int(first)
        req.generated.append(token)
        self.metrics.record_first_token(req.rid)
        self.tracer.on_first_token(req.rid)
        if req.done:
            self._finish(req, finished)
            return
        self.scheduler.to_decoding(req)
        self._comp_ver += 1
        self._tok[s] = token
        self._t[s] = p_len          # where the next decode step writes it
        self._temp[s] = req.temperature
        self._topk[s] = req.top_k
        self._topp[s] = req.top_p
        self._stop[s] = req.stop_token
        self._keys[s] = np.array(req.rng)
        self._chain_dirty[s] = True        # host owns the next input
        self._begin_draft(req, toks)

    def _begin_draft(self, req: Request, context) -> None:
        """Hand the draft source this request's context the moment it
        joins decode. A source that cannot serve the slot (its own
        pool is dry) disables speculation for THIS request only —
        admission and decode proceed untouched."""
        if not self._spec_eligible(req):
            return
        if not self._draft.begin_slot(req.slot, context):
            self._spec_disable(req)

    def _advance_decode(self, finished: List[Request]):
        # chaos hook: fires BEFORE any state mutates THIS iteration
        # (the in-flight step, if any, was launched by a prior
        # iteration and stays consumable), so an injected decode-step
        # error leaves the iteration wholesale-retryable (see step()
        # docstring)
        faults.point("serving.decode")
        paged = self.kv_layout == "paged"
        spec = bool(self._spec_slots())
        if spec:
            # draft proposals read host-side token state, so a
            # speculative iteration is synchronous: drain the pipeline
            # first, then the verify fetch below is the sanctioned
            # in-iteration sync
            self._flush_pending(finished)
            if not self.scheduler.running:
                return                  # the flush drained the batch
        fuse = 0 if spec else self._fuse_window()
        if spec and self.spec_tree:
            # tree speculation: the page lookahead depends on the
            # PROPOSED node span, so proposal must precede page growth
            # — the whole iteration lives in _spec_tree_step
            self._spec_tree_step(finished)
            return
        if paged:
            # page growth happens BEFORE the step (a write with no page
            # would silently drop); may preempt streams out of
            # ``running``, so the batch composition reads after it.
            # Speculating slots demand pages for their whole verify
            # window up front (only as far as their budget can
            # consume); a fused window demands pages for all
            # ``fuse_steps`` write positions
            look = None
            if spec:
                look = np.zeros(self.num_slots, np.int64)
                for slot, r in self.scheduler.running.items():
                    if self._spec_eligible(r):
                        look[slot] = min(
                            self.spec_k,
                            r.max_new_tokens - len(r.generated) - 1)
            elif fuse:
                look = np.zeros(self.num_slots, np.int64)
                for slot in self.scheduler.running:
                    look[slot] = fuse - 1
            self._ensure_decode_pages(look)
            if not self.scheduler.running:
                return
            if spec:
                spec = bool(self._spec_slots())  # preemption may have
                #                                  evicted speculators
            elif fuse and self.scheduler.queue_depth:
                # funding the window preempted a stream: quiescence is
                # gone, fall back to single-step and rejoin later (the
                # pre-grown pages stay — they are legitimate write
                # positions)
                fuse = 0
        t0 = self.metrics.clock()
        greedy_only = all(r.temperature <= 0.0
                          for r in self.scheduler.running.values())
        tables = (self.pool.device_tables(),) if paged else ()
        if spec:
            self._spec_step(greedy_only, tables, finished, t0)
            return
        prev = self._pending
        pend = self._launch_step(greedy_only, tables, fuse, prev, t0)
        if self.overlap:
            # pipelined dispatch: the new step runs on device while the
            # host consumes the LAGGED fetch of the previous one (its
            # decode sample covers THIS phase, t0 onward)
            self._pending = pend
            if prev is not None:
                self._process_step(prev, finished, t0)
        else:
            # the synchronous A/B baseline: launch-and-wait, exactly
            # the pre-zero-bubble loop
            self._process_step(pend, finished, t0)

    def _spec_step(self, greedy_only: bool, tables,
                   finished: List[Request], t0: float) -> None:
        """One speculative draft-and-verify iteration over the decode
        batch. Non-speculating slots ride the same program with their
        drafts force-rejected — for them the verify step IS a plain
        decode step. Host bookkeeping (metrics, tracer items, the
        acceptance EMA) defers onto the host-window buffers."""
        k = self.spec_k
        running = self.scheduler.running
        active = np.zeros(self.num_slots, bool)
        for slot, r in running.items():
            if self._spec_eligible(r):
                active[slot] = True
        drafts = np.zeros((self.num_slots, k), np.int32)
        self._draft.propose(dict(running), self._tok, self._t, drafts,
                            active)
        toks = np.concatenate([self._tok[:, None], drafts],
                              axis=1).astype(np.int32)
        active_dev = jnp.asarray(active)
        if greedy_only:
            cand, n_acc, self.pool.cache, moe = self._verify_fn(True)(
                self._params, self._state, self.pool.cache, toks,
                self._t, active_dev, *tables)
            cand, n_acc = self._fetch(cand, n_acc)
        else:
            (cand, n_acc, self.pool.cache, keys,
             moe) = self._verify_fn(False)(
                self._params, self._state, self.pool.cache, toks,
                self._t, active_dev, self._temp, self._topk,
                self._topp, self._keys, *tables)
            cand, n_acc, new_keys = self._fetch(cand, n_acc, keys)
            # the fetch hands back read-only views of device memory;
            # the key mirror stays host-writable (per-slot restores on
            # admission/resume write into it)
            self._keys = new_keys.copy()
        name = ("serving.verify_greedy" if greedy_only
                else "serving.verify_sampled")
        if name not in self._warmed:
            self._warmed.add(name)
            self._recompile.mark_warm(name)
        self._note_moe_route(moe)

        def note(slot, req, trace_on):
            m = int(n_acc[slot])
            self._spec_buf.append((k, m))
            # the EMA updates INLINE (not on the host-window
            # cadence): a spec iteration is already synchronous —
            # the verify fetch above paid the sync — and the
            # warm-up/kill-switch contract (spec_warmup checks,
            # then disable) is exact-count, not windowed
            self._observe_acceptance(req, m / k)
            if trace_on:
                pa = self._trace_spec.setdefault(req.rid, [0, 0])
                pa[0] += k
                pa[1] += m

        self._consume_spec(running, cand, n_acc + 1, active, note,
                           finished, t0)

    def _consume_spec(self, running, emitted, n_emit, active, note,
                      finished: List[Request], t0: float) -> None:
        """Shared host-consume loop of the linear and tree spec steps:
        append ``emitted[slot, :n_emit[slot]]`` until each request's
        stop/budget, advance the ``_tok``/``_t`` mirrors, batch the
        trace-decode ticks, run ``note(slot, req, trace_on)`` for each
        ACTIVE slot's speculation bookkeeping, and flush deferred host
        work BEFORE any terminal transition (on_terminal retires the
        timeline, and the final verify's outcome belongs on it). One
        copy of these contracts — the two call sites diverge only in
        their ``note`` closures."""
        now_ = self._metrics.clock()
        trace_on = self.tracer.enabled
        n_emitted = 0
        done_reqs = []
        for slot, req in list(running.items()):
            ne = int(n_emit[slot])
            appended = 0
            for token in emitted[slot, :ne]:
                req.generated.append(int(token))
                appended += 1
                if req.done:
                    break           # stop token / budget mid-window
            n_emitted += appended
            self._tok[slot] = req.generated[-1]
            self._t[slot] += appended
            if trace_on:
                self._trace_decode[req.rid] = \
                    self._trace_decode.get(req.rid, 0) + appended
                if self._trace_decode_t0 is None:
                    self._trace_decode_t0 = now_
            if active[slot]:
                note(slot, req, trace_on)
            if req.done:
                done_reqs.append(req)
        self._decode_buf.append((len(running), now_ - t0, n_emitted))
        if done_reqs:
            self._flush_host_window()
            for req in done_reqs:
                self._finish(req, finished)

    def _spec_tree_step(self, finished: List[Request]) -> None:
        """One TREE draft-and-verify iteration (tree-speculation PR).

        Order matters: (1) build each eligible stream's tree via
        ``DraftSource.propose_tree`` under its adaptive (depth, width)
        and a node budget capped by slot capacity; (2) derive
        depth/ancestor arrays and grow pages for the PROPOSED node
        span — the verify forward writes window columns ``t ..
        t + n_nodes - 1`` and an accepted node's missing page would
        silently corrupt its KV, so the lookahead is the worst-case
        tree width, not the chain depth; (3) one compiled
        verify-walk-commit program; (4) host consume: append
        ``emitted[:n_emit]``, update the acceptance EMA (on the
        longest-chain basis ``path_len / depth`` so the kill switch
        threshold means the same thing as the linear path's), resize
        the stream's tree (``_adapt_tree``). Streams whose tree ends
        up empty ride the program as plain decode steps."""
        W = self.spec_window
        paged = self.kv_layout == "paged"
        running = self.scheduler.running
        s_n = self.num_slots
        toks = np.zeros((s_n, W), np.int32)
        toks[:, 0] = self._tok
        parents = np.full((s_n, W), -1, np.int32)
        active = np.zeros(s_n, bool)
        depth_v = np.zeros(s_n, np.int32)
        width_v = np.ones(s_n, np.int32)
        budget_v = np.zeros(s_n, np.int32)
        for slot, r in running.items():
            if not self._spec_eligible(r):
                continue
            d, w = self._tree_shape(r)
            if d < 1:
                continue
            active[slot] = True
            depth_v[slot] = d
            width_v[slot] = w
            # every node writes its own window column: the span must
            # fit the slot's capacity (>= d always — a chain fits)
            budget_v[slot] = min(d * w,
                                 self.max_len - 1 - int(self._t[slot]))
        if active.any():
            self._draft.propose_tree(dict(running), self._tok, self._t,
                                     toks, parents, active, depth_v,
                                     width_v, budget_v)
        depth, anc, n_nodes = tree_ancestors(parents)
        if paged:
            look = np.where(active, n_nodes - 1, 0).astype(np.int64)
            self._ensure_decode_pages(look)
            if not self.scheduler.running:
                return
        t0 = self.metrics.clock()
        running = self.scheduler.running
        greedy_only = all(r.temperature <= 0.0
                          for r in running.values())
        tables = (self.pool.device_tables(),) if paged else ()
        targs = (toks, self._t, parents, depth, anc)
        if greedy_only:
            emitted, n_emit, self.pool.cache, moe = \
                self._verify_tree_fn(True)(
                    self._params, self._state, self.pool.cache, *targs,
                    *tables)
            emitted, n_emit = self._fetch(emitted, n_emit)
        else:
            (emitted, n_emit, self.pool.cache, keys, moe) = \
                self._verify_tree_fn(False)(
                    self._params, self._state, self.pool.cache, *targs,
                    self._temp, self._topk, self._topp, self._keys,
                    *tables)
            emitted, n_emit, new_keys = self._fetch(emitted, n_emit,
                                                    keys)
            self._keys = new_keys.copy()
        name = ("serving.verify_tree_greedy" if greedy_only
                else "serving.verify_tree_sampled")
        if name not in self._warmed:
            self._warmed.add(name)
            self._recompile.mark_warm(name)
        self._note_moe_route(moe)

        def note(slot, req, trace_on):
            nd = int(n_nodes[slot]) - 1         # draft nodes offered
            m = int(n_emit[slot]) - 1           # accepted path length
            self._spec_buf.append((nd, m))
            self._spec_tree_buf.append((int(width_v[slot]), m))
            # EMA on the longest-chain basis: m / depth means the
            # same thing the linear path's m / k did, so the
            # warm-up/kill-switch thresholds carry over unchanged
            self._observe_acceptance(
                req, m / max(1, int(depth_v[slot])))
            self._adapt_tree(req)
            if trace_on:
                pa = self._trace_spec.setdefault(req.rid, [0, 0, 0, 0])
                pa[0] += nd
                pa[1] += m
                pa[2] = max(pa[2], int(width_v[slot]))
                pa[3] = max(pa[3], m)

        self._consume_spec(running, emitted, n_emit, active, note,
                           finished, t0)

    def _drop_swap(self, req: Request) -> None:
        """Release an orphaned swap snapshot: free its host pages
        (pending async batches fully covered just drop — never read,
        never fenced) and release the refcount holds on the prefix-
        resident pages the snapshot pinned instead of copying."""
        swap = getattr(req, "_swap", None)
        if swap is None:
            return
        self.pool.free_host(swap["host"])
        for _lp, pid in swap.get("shared", ()):
            self.pool.decref(int(pid))
        req._swap = None

    def _finish(self, req: Request, finished: List[Request]):
        slot = req.slot
        self.scheduler.release(req)
        self._comp_ver += 1
        self._t[slot] = self.max_len          # sentinel: slot inert
        self._chain_dirty[slot] = True
        if self._draft is not None:
            self._draft.end_slot(slot)
        if self.kv_layout == "paged":
            # pages return to the budget; registered prompt-prefix
            # pages survive under the prefix cache's own refcount
            self.pool.release_slot(slot)
        self.metrics.record_finish(req.rid, len(req.generated))
        self.tracer.on_terminal(req.rid, RequestState.FINISHED.value,
                                len(req.generated))
        # evict: the caller owns the finished Request from here —
        # otherwise every prompt ever served stays resident
        del self._requests[req.rid]
        finished.append(req)
