"""SLO-burn drain controller: the pressure loop that turns per-replica
SLO burn rates (``obs.slo``) into fleet actions.

The single-engine degradation story ends at ``health() ==
"degraded"`` — a probe's hint. With a fleet there is a real action to
take: a replica burning its error budget faster than
``drain_above`` stops taking traffic (``drain()`` — in-flight streams
finish, queued work is rebalanced onto the rest of the fleet through
the token-identical transfer path) and returns to service once its
burn has recovered below ``resume_below`` (hysteresis, so a replica
hovering at the threshold does not flap). ``min_serving`` replicas are
always left serving — draining the whole fleet is worse than serving
degraded.

Wire it with ``router.attach_controller(ctl)`` (ticked every
``Router._CTL_EVERY`` steps) or call ``tick()`` on your own cadence.
Burn rates come from each replica's own ``SLOEngine``
(``ServingEngine(slo=[...])``); replicas without objectives are left
alone.
"""

from __future__ import annotations

from typing import Dict, Optional

from distkeras_tpu import obs
from distkeras_tpu.obs.recorder import resolve_recorder
from distkeras_tpu.serving.router.replica import ReplicaState

__all__ = ["SLOBurnController"]


class SLOBurnController:
    """Drain replicas whose max SLO burn rate exceeds ``drain_above``;
    resume them below ``resume_below`` (must be <= ``drain_above``).
    A burn rate of 1.0 means the error budget spends exactly as fast
    as it accrues, so the default 2.0 drains a replica burning at
    twice budget — the SRE-workbook "fast burn" alert shape."""

    def __init__(self, router, *, drain_above: float = 2.0,
                 resume_below: float = 1.0, min_serving: int = 1,
                 rebalance: bool = True):
        if drain_above <= 0:
            raise ValueError(
                f"drain_above must be > 0, got {drain_above}")
        if not 0 <= resume_below <= drain_above:
            raise ValueError(
                f"resume_below must be in [0, drain_above], got "
                f"{resume_below}")
        if min_serving < 1:
            raise ValueError(
                f"min_serving must be >= 1, got {min_serving}")
        self.router = router
        self.drain_above = float(drain_above)
        self.resume_below = float(resume_below)
        self.min_serving = int(min_serving)
        self.rebalance = bool(rebalance)
        self.recorder = resolve_recorder()
        reg = obs.get_registry()
        self._c_drain = reg.counter("router.slo_drains")
        self._c_resume = reg.counter("router.slo_resumes")
        #: replicas THIS controller drained (only these are auto-resumed
        #: — an operator's manual drain() is never overridden)
        self._drained: Dict[str, bool] = {}

    def tick(self) -> Dict[str, str]:
        """One control pass; returns ``{replica name: action}`` for the
        replicas acted on (``"drain"`` / ``"resume"``)."""
        actions: Dict[str, str] = {}
        # prune stale drain ownership: a replica an operator manually
        # resumed (or that died) is no longer "ours" — a LATER manual
        # drain() must stand instead of being auto-resumed against the
        # documented contract
        for name in list(self._drained):
            rep = next((r for r in self.router.replicas
                        if r.name == name), None)
            if rep is None or rep.state is not ReplicaState.DRAINING:
                self._drained.pop(name, None)
        serving = [r for r in self.router.replicas
                   if r.state is ReplicaState.SERVING]
        for r in list(serving):
            burn = r.slo_burn()
            if burn is None or burn <= self.drain_above:
                continue
            if len(serving) - 1 < self.min_serving:
                break                 # never drain below the floor
            r.drain()
            serving.remove(r)
            self._drained[r.name] = True
            self._c_drain.inc(replica=r.name)
            actions[r.name] = "drain"
            if self.recorder.enabled:
                self.recorder.record(
                    "router.slo_drain", replica=r.name,
                    burn_rate=round(burn, 4),
                    threshold=self.drain_above)
            if self.rebalance:
                self.router.rebalance_queued(r)
        for r in self.router.replicas:
            if r.state is not ReplicaState.DRAINING \
                    or not self._drained.get(r.name):
                continue
            burn = self._recovered_burn(r)
            if burn is not None and burn > self.resume_below:
                continue
            r.resume()
            self._drained.pop(r.name, None)
            self._c_resume.inc(replica=r.name)
            actions[r.name] = "resume"
            if self.recorder.enabled:
                self.recorder.record(
                    "router.slo_resume", replica=r.name,
                    burn_rate=None if burn is None else round(burn, 4))
        return actions

    def _recovered_burn(self, replica) -> Optional[float]:
        """Burn rate used for the resume decision. The metrics window
        that breached keeps its bad samples forever (reservoirs are
        windowless), so operators typically swap a fresh
        ``ServingMetrics`` window per reporting interval — with the old
        window still attached the replica simply resumes once the
        breach samples age out of a swapped window or the burn math
        recovers."""
        return replica.slo_burn()
