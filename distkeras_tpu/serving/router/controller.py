"""Fleet controllers: the pressure loops that turn live telemetry into
fleet actions.

The single-engine degradation story ends at ``health() ==
"degraded"`` — a probe's hint. With a fleet there are real actions to
take, and this module holds both loops:

* ``SLOBurnController`` — *quality* pressure: a replica burning its
  error budget faster than ``drain_above`` stops taking traffic
  (``drain()`` — in-flight streams finish, queued work is rebalanced
  onto the rest of the fleet through the token-identical transfer
  path) and returns to service once its burn has recovered below
  ``resume_below`` (hysteresis, so a replica hovering at the threshold
  does not flap). ``min_serving`` replicas are always left serving —
  draining the whole fleet is worse than serving degraded.

* ``AutoscaleController`` — *capacity* pressure: sustained SLO burn,
  monotone queue growth or shed onset grows the fleet
  (``Router.add_replica``); sustained whole-fleet idleness shrinks it
  (``remove_replica`` → drain → retire). Cool-downs and sustain
  windows keep it from flapping; every decision is counted and
  ring-recorded. See the class docstring for the state machine.

Wire one with ``router.attach_controller(ctl)`` (ticked every
``Router._CTL_EVERY`` steps), both with ``ControllerChain`` (burn
first — drain-for-burn beats scale-down), or call ``tick()`` on your
own cadence. Burn rates come from each replica's own ``SLOEngine``
(``ServingEngine(slo=[...])``); replicas without objectives are left
alone.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from distkeras_tpu import obs
from distkeras_tpu.obs.recorder import resolve_recorder
from distkeras_tpu.obs.report import _detect_growth
from distkeras_tpu.serving.router.replica import ReplicaState

__all__ = ["AutoscaleController", "ControllerChain", "SLOBurnController"]


class ControllerChain:
    """Drive several controllers from the router's single
    ``attach_controller`` slot, in construction order. Put the
    ``SLOBurnController`` before the ``AutoscaleController``: its
    drains land first, and the autoscaler's same-tick ``draining``
    guard then defers scale-down — drain-for-burn beats scale-down by
    construction."""

    def __init__(self, *controllers):
        self.controllers = list(controllers)

    def tick(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for c in self.controllers:
            out.update(c.tick() or {})
        return out


class SLOBurnController:
    """Drain replicas whose max SLO burn rate exceeds ``drain_above``;
    resume them below ``resume_below`` (must be <= ``drain_above``).
    A burn rate of 1.0 means the error budget spends exactly as fast
    as it accrues, so the default 2.0 drains a replica burning at
    twice budget — the SRE-workbook "fast burn" alert shape."""

    def __init__(self, router, *, drain_above: float = 2.0,
                 resume_below: float = 1.0, min_serving: int = 1,
                 rebalance: bool = True):
        if drain_above <= 0:
            raise ValueError(
                f"drain_above must be > 0, got {drain_above}")
        if not 0 <= resume_below <= drain_above:
            raise ValueError(
                f"resume_below must be in [0, drain_above], got "
                f"{resume_below}")
        if min_serving < 1:
            raise ValueError(
                f"min_serving must be >= 1, got {min_serving}")
        self.router = router
        self.drain_above = float(drain_above)
        self.resume_below = float(resume_below)
        self.min_serving = int(min_serving)
        self.rebalance = bool(rebalance)
        self.recorder = resolve_recorder()
        reg = obs.get_registry()
        self._c_drain = reg.counter("router.slo_drains")
        self._c_resume = reg.counter("router.slo_resumes")
        #: replicas THIS controller drained (only these are auto-resumed
        #: — an operator's manual drain() is never overridden)
        self._drained: Dict[str, bool] = {}

    def tick(self) -> Dict[str, str]:
        """One control pass; returns ``{replica name: action}`` for the
        replicas acted on (``"drain"`` / ``"resume"``)."""
        actions: Dict[str, str] = {}
        # prune stale drain ownership: a replica an operator manually
        # resumed (or that died) is no longer "ours" — a LATER manual
        # drain() must stand instead of being auto-resumed against the
        # documented contract
        for name in list(self._drained):
            rep = next((r for r in self.router.replicas
                        if r.name == name), None)
            if rep is None or rep.state is not ReplicaState.DRAINING:
                self._drained.pop(name, None)
        serving = [r for r in self.router.replicas
                   if r.state is ReplicaState.SERVING]
        for r in list(serving):
            burn = r.slo_burn()
            if burn is None or burn <= self.drain_above:
                continue
            if len(serving) - 1 < self.min_serving:
                break                 # never drain below the floor
            r.drain()
            serving.remove(r)
            self._drained[r.name] = True
            self._c_drain.inc(replica=r.name)
            actions[r.name] = "drain"
            if self.recorder.enabled:
                self.recorder.record(
                    "router.slo_drain", replica=r.name,
                    burn_rate=round(burn, 4),
                    threshold=self.drain_above)
            if self.rebalance:
                self.router.rebalance_queued(r)
        for r in self.router.replicas:
            if r.state is not ReplicaState.DRAINING \
                    or not self._drained.get(r.name) \
                    or r.retiring:
                # a retiring replica is leaving the fleet (scale-down /
                # remove_replica): resuming it would race the retire
                # sweep — one replica cannot be both drained and retired
                continue
            burn = self._recovered_burn(r)
            if burn is not None and burn > self.resume_below:
                continue
            r.resume()
            self._drained.pop(r.name, None)
            self._c_resume.inc(replica=r.name)
            actions[r.name] = "resume"
            if self.recorder.enabled:
                self.recorder.record(
                    "router.slo_resume", replica=r.name,
                    burn_rate=None if burn is None else round(burn, 4))
        return actions

    def _recovered_burn(self, replica) -> Optional[float]:
        """Burn rate used for the resume decision. The metrics window
        that breached keeps its bad samples forever (reservoirs are
        windowless), so operators typically swap a fresh
        ``ServingMetrics`` window per reporting interval — with the old
        window still attached the replica simply resumes once the
        breach samples age out of a swapped window or the burn math
        recovers."""
        return replica.slo_burn()


class AutoscaleController:
    """Closed-loop fleet sizing: live saturation signals in,
    ``Router.add_replica``/``remove_replica`` out.

    One ``tick()`` (wire with ``router.attach_controller`` or compose
    under a multiplexer with ``SLOBurnController``) evaluates three
    scale-up signals over the SERVING, non-retiring fleet —

    * **SLO burn**: any replica's live max burn rate (side-effect-free
      ``slo_burn()``) above ``scale_up_burn``;
    * **queue growth**: the fleet-total queue depth sampled every tick
      shows a sustained monotone rise (the exact
      ``obs.report._detect_growth`` predicate the post-hoc saturation
      panel uses, evaluated live over the controller's own window);
    * **shed onset**: the router rejected a request since the last tick
      (fleet-wide shed — every replica refused).

    A signal must persist for ``up_sustain`` consecutive ticks before a
    scale-up fires (``factory()`` → ``add_replica``); a whole-fleet
    idle reading (zero queued, zero occupied) must persist for
    ``idle_sustain`` ticks before a scale-down retires one replica,
    preferring the replicas this controller added (LIFO) so the fleet
    relaxes back to its seed shape. After any action the controller
    holds for ``cooldown`` ticks. ``min_serving``/``max_replicas``
    bound the fleet; an action wanted but denied (bounds, cooldown, or
    a drain-for-burn in progress — drain beats scale-down, one replica
    is never both drained and retired) is counted and ring-recorded as
    ``blocked``. DEAD replicas are garbage-collected through
    ``remove_replica`` every tick.

    Determinism: decisions depend only on tick-ordered fleet state —
    no wall clock — and each one is appended to ``decisions`` stamped
    with the router step, so a seeded replay reproduces the decision
    log byte-identically. Counters: ``autoscale.scale_up`` /
    ``autoscale.scale_down`` / ``autoscale.blocked``.
    """

    #: queue-depth samples kept for the growth predicate
    _QWINDOW = 16

    def __init__(self, router, factory, *, min_serving: int = 1,
                 max_replicas: int = 4, scale_up_burn: float = 2.0,
                 up_sustain: int = 2, idle_sustain: int = 4,
                 cooldown: int = 4, growth_min_run: int = 3,
                 growth_min_rise: float = 1.0,
                 burn_controller: Optional[SLOBurnController] = None,
                 gc_dead: bool = True):
        if min_serving < 1:
            raise ValueError(
                f"min_serving must be >= 1, got {min_serving}")
        if max_replicas < min_serving:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"min_serving ({min_serving})")
        if up_sustain < 1 or idle_sustain < 1:
            raise ValueError("sustain windows must be >= 1")
        self.router = router
        self.factory = factory
        self.min_serving = int(min_serving)
        self.max_replicas = int(max_replicas)
        self.scale_up_burn = float(scale_up_burn)
        self.up_sustain = int(up_sustain)
        self.idle_sustain = int(idle_sustain)
        self.cooldown = int(cooldown)
        self.growth_min_run = int(growth_min_run)
        self.growth_min_rise = float(growth_min_rise)
        self.burn_controller = burn_controller
        self.gc_dead = bool(gc_dead)
        self.recorder = resolve_recorder()
        reg = obs.get_registry()
        self._c_up = reg.counter("autoscale.scale_up")
        self._c_down = reg.counter("autoscale.scale_down")
        self._c_blocked = reg.counter("autoscale.blocked")
        #: decision log: dicts with step/action/replica/reason —
        #: deterministic under the virtual clock (replay's oracle)
        self.decisions: List[Dict] = []
        self._qhist: List[float] = []
        self._ticks = 0
        self._cool_until = 0
        self._up_streak = 0
        self._idle_streak = 0
        self._last_shed = router.counters().get("rejected", 0)
        #: names this controller added, LIFO scale-down preference
        self._added: List[str] = []

    # -- signal plumbing ---------------------------------------------------

    def _serving(self):
        return [r for r in self.router.replicas
                if r.state is ReplicaState.SERVING and not r.retiring]

    def _live_size(self) -> int:
        """Replicas that count against ``max_replicas``: everything
        not dead and not on its way out."""
        return sum(1 for r in self.router.replicas
                   if r.state is not ReplicaState.DEAD
                   and not r.retiring)

    def signals(self) -> Dict:
        """The live saturation read (also handy for dashboards): burn,
        queue-growth and shed-onset inputs plus the raw numbers they
        came from. Pure observation — no fleet mutation."""
        serving = self._serving()
        burns = [b for b in (r.slo_burn() for r in serving)
                 if b is not None]
        burn = max(burns, default=None)
        qd = float(sum(r.queue_depth for r in serving))
        occ = sum(r.occupied for r in serving)
        shed_now = self.router.counters().get("rejected", 0)
        shed_delta = shed_now - self._last_shed
        growth = _detect_growth(self._qhist + [qd],
                                min_run=self.growth_min_run,
                                min_rise=self.growth_min_rise)
        return {
            "burn": burn, "queue_depth": qd, "occupied": occ,
            "shed_delta": shed_delta, "queue_growth": growth,
            "overload": ((burn is not None and burn > self.scale_up_burn)
                         or shed_delta > 0 or growth),
            "idle": qd == 0 and occ == 0,
        }

    # -- the control pass --------------------------------------------------

    def tick(self) -> Dict[str, str]:
        """One control pass; returns ``{replica name: action}`` for
        fleet mutations made (``"add"`` / ``"remove"`` / ``"gc"``)."""
        actions: Dict[str, str] = {}
        router = self.router
        if self.gc_dead:
            for rep in list(router.replicas):
                if rep.state is ReplicaState.DEAD and not rep.retiring:
                    router.remove_replica(rep.name)
                    self._decide("gc", rep.name, "dead")
                    actions[rep.name] = "gc"
        sig = self.signals()
        self._last_shed = router.counters().get("rejected", 0)
        self._qhist.append(sig["queue_depth"])
        if len(self._qhist) > self._QWINDOW:
            del self._qhist[:len(self._qhist) - self._QWINDOW]
        self._up_streak = self._up_streak + 1 if sig["overload"] else 0
        self._idle_streak = self._idle_streak + 1 if sig["idle"] else 0
        self._ticks += 1
        if self._up_streak >= self.up_sustain:
            self._scale_up(sig, actions)
        elif self._idle_streak >= self.idle_sustain:
            self._scale_down(sig, actions)
        return actions

    def _reason(self, sig: Dict) -> str:
        if sig["burn"] is not None and sig["burn"] > self.scale_up_burn:
            return f"burn:{sig['burn']:.2f}"
        if sig["shed_delta"] > 0:
            return f"shed:{sig['shed_delta']}"
        if sig["queue_growth"]:
            return "queue_growth"
        return "idle"

    def _decide(self, action: str, replica: Optional[str],
                reason: str) -> None:
        self.decisions.append({
            "step": self.router._steps, "tick": self._ticks,
            "action": action, "replica": replica, "reason": reason})
        if self.recorder.enabled:
            self.recorder.record(
                "autoscale.decision", action=action, replica=replica,
                reason=reason, fleet=len(self.router.replicas))

    def _blocked(self, wanted: str, reason: str) -> None:
        self._c_blocked.inc()
        self._decide("blocked", None, f"{wanted}:{reason}")
        # re-arm: the sustain window must refill before the next
        # attempt, so a standing blocker yields a bounded decision log
        # instead of one blocked entry per tick
        self._up_streak = 0
        self._idle_streak = 0

    def _scale_up(self, sig: Dict, actions: Dict[str, str]) -> None:
        reason = self._reason(sig)
        if self._ticks < self._cool_until:
            self._blocked("scale_up", "cooldown")
            return
        if self._live_size() >= self.max_replicas:
            self._blocked("scale_up", "max_replicas")
            return
        rep = self.router.add_replica(self.factory)
        self._added.append(rep.name)
        self._c_up.inc(replica=rep.name)
        self._decide("scale_up", rep.name, reason)
        actions[rep.name] = "add"
        self._up_streak = 0
        self._idle_streak = 0
        self._cool_until = self._ticks + self.cooldown

    def _scale_down(self, sig: Dict, actions: Dict[str, str]) -> None:
        if self._ticks < self._cool_until:
            self._blocked("scale_down", "cooldown")
            return
        serving = self._serving()
        if len(serving) <= self.min_serving:
            self._blocked("scale_down", "min_serving")
            return
        if any(r.state is ReplicaState.DRAINING and not r.retiring
               for r in self.router.replicas):
            # drain-for-burn in progress: the burn controller owns that
            # replica's fate (resume or operator removal) — shrinking
            # the serving pool underneath it double-counts the same
            # pressure relief
            self._blocked("scale_down", "draining")
            return
        victim = None
        names = {r.name: r for r in serving}
        for name in reversed(self._added):        # LIFO: newest first
            if name in names:
                victim = names[name]
                break
        if victim is None:
            # no controller-added replica left: deterministic fallback,
            # lexicographically last name (stable across replays)
            victim = max(serving, key=lambda r: r.name)
        self.router.remove_replica(victim.name)
        if victim.name in self._added:
            self._added.remove(victim.name)
        self._c_down.inc(replica=victim.name)
        self._decide("scale_down", victim.name, "idle")
        actions[victim.name] = "remove"
        self._up_streak = 0
        self._idle_streak = 0
        self._cool_until = self._ticks + self.cooldown

    def counts(self) -> Dict[str, int]:
        """Plain decision totals for bench JSON (the registry carries
        the same series for exporters)."""
        out = {"scale_up": 0, "scale_down": 0, "blocked": 0, "gc": 0}
        for d in self.decisions:
            out[d["action"]] = out.get(d["action"], 0) + 1
        return out
