"""Engine replicas: one ``ServingEngine`` behind a lifecycle state
machine, the unit the router places work on.

A replica is STARTING until the router (or the caller) ``start()``s it,
SERVING while it accepts work, DRAINING once ``drain()`` closed
admission (in-flight streams finish; new submits shed with
``AdmissionRejected`` so the shedding semantics the engine already has
compose unchanged), and DEAD after a failure — the router treats any
exception escaping ``step()`` as replica death and mass-fails-over the
replica's in-flight requests (``router.Router._on_replica_death``).

``role`` partitions the fleet for disaggregated prefill/decode
serving: a ``"prefill"`` replica takes fresh admissions, runs the
chunked prefill and the first sampled token, and the router then hands
the stream to a ``"decode"`` replica through the engine's
``transfer_out``/``transfer_in`` re-entry path; ``"both"`` (default)
replicas do everything. See ``docs/serving.md`` §Router.

Chaos hook: ``resilience.faults`` point ``replica.die`` fires at the
top of every ``step()`` — arming it (``faults.inject("replica.die",
nth=K)``) kills whichever replica takes the K-th fleet step, which is
how the failover oracle tests drive replica loss deterministically.
"""

from __future__ import annotations

import enum
from typing import Dict, Optional

from distkeras_tpu.resilience import faults
from distkeras_tpu.serving.engine import ServingEngine
from distkeras_tpu.serving.scheduler import AdmissionRejected

__all__ = ["EngineReplica", "ReplicaDead", "ReplicaState",
           "ReplicaUnavailable"]


class ReplicaState(enum.Enum):
    STARTING = "starting"    # constructed, not yet taking traffic
    SERVING = "serving"      # admitting and decoding
    DRAINING = "draining"    # admission closed, in-flight finishing
    DEAD = "dead"            # failed; never stepped again


class ReplicaDead(RuntimeError):
    """The replica has failed and cannot serve (``step()`` after
    death). The router fails its requests over instead of raising."""

    def __init__(self, name: str, cause: Optional[BaseException] = None):
        tail = f": {cause!r}" if cause is not None else ""
        super().__init__(f"replica {name!r} is dead{tail}")
        self.name = name
        self.cause = cause


class ReplicaUnavailable(AdmissionRejected):
    """Submit refused because the replica is not SERVING (draining,
    starting or dead). An ``AdmissionRejected`` subclass so router and
    client shed-handling paths treat it exactly like a full queue."""

    def __init__(self, name: str, state: "ReplicaState",
                 queue_depth: int = 0):
        RuntimeError.__init__(
            self, f"replica {name!r} is {state.value}: admission closed")
        self.queue_depth = queue_depth
        self.max_queue = 0


class EngineReplica:
    """One ``ServingEngine`` + lifecycle + placement signals.

    The wrapped engine must use the paged KV layout: the router's
    handoff and failover paths re-enter through the resumable
    re-prefill machinery, which is paged-only. ``name`` defaults to the
    engine's ``engine_id`` and becomes the replica's label on every
    process-global record (ring entries, tracer timelines, telemetry
    component ``serving[<name>]`` — pass ``engine_id=<name>`` at engine
    construction to make the component name match)."""

    def __init__(self, engine: ServingEngine, *, name: Optional[str] = None,
                 role: str = "both"):
        if engine.kv_layout != "paged":
            raise ValueError(
                "EngineReplica needs a paged-KV engine "
                "(kv_layout='paged'): handoff/failover re-enter "
                "through the resumable re-prefill path")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', "
                f"got {role!r}")
        self.engine = engine
        self.role = role
        if name is not None:
            # re-label the engine so its recorder/tracer records carry
            # the replica name (the snapshot component name was fixed
            # at engine construction — pass engine_id= there to align)
            engine.engine_id = str(name)
            if engine.tracer.enabled:
                engine.tracer.engine = str(name)
        self.name = str(name) if name is not None else engine.engine_id
        self.state = ReplicaState.STARTING
        self.error: Optional[BaseException] = None
        #: fleet steps this replica has taken (telemetry)
        self.steps = 0
        #: marked by ``Router.remove_replica``: the retire sweep pops
        #: this replica from the fleet once it drains empty. Controllers
        #: must treat a retiring replica as leaving — never resume it
        #: (``SLOBurnController`` skips it) and never count it toward
        #: serving capacity (``AutoscaleController`` does not).
        self.retiring = False

    def __repr__(self):
        return (f"EngineReplica({self.name!r}, role={self.role!r}, "
                f"state={self.state.value})")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """STARTING/DRAINING → SERVING (idempotent; dead replicas stay
        dead — build a new replica instead of resurrecting state the
        failover already re-homed). An explicit ``start()`` also
        cancels a pending retirement — the operator's resume beats the
        router's scheduled removal."""
        if self.state is ReplicaState.DEAD:
            raise ReplicaDead(self.name, self.error)
        self.state = ReplicaState.SERVING
        self.retiring = False

    def drain(self) -> None:
        """Close admission; in-flight streams keep stepping to
        completion. New submits (and router placement) shed with
        ``ReplicaUnavailable`` — an ``AdmissionRejected``."""
        if self.state is ReplicaState.DEAD:
            raise ReplicaDead(self.name, self.error)
        self.state = ReplicaState.DRAINING

    resume = start    # DRAINING → SERVING reads better as resume()

    def mark_dead(self, error: Optional[BaseException] = None) -> None:
        self.state = ReplicaState.DEAD
        if error is not None:
            self.error = error

    @property
    def drained(self) -> bool:
        """DRAINING and empty: safe to stop/recycle."""
        return (self.state is ReplicaState.DRAINING
                and not self.engine.scheduler.pending)

    @property
    def pending(self) -> bool:
        """Anything left to do: scheduler work, or terminals parked by
        an out-of-band pipeline flush (a handoff's preempt may finish a
        NEIGHBOUR stream — the next ``step()`` must run to deliver it
        even though the scheduler is empty)."""
        if self.state is ReplicaState.DEAD:
            return False
        eng = self.engine
        return eng.scheduler.pending or bool(eng._finish_buf)

    # -- placement signals (cheap: no device sync, no full health()) -------

    @property
    def queue_depth(self) -> int:
        return self.engine.scheduler.queue_depth

    @property
    def occupied(self) -> int:
        return self.engine.scheduler.occupied

    @property
    def free_pages(self) -> int:
        return self.engine.pool.free_pages

    @property
    def accepting(self) -> bool:
        """SERVING and the bounded queue has room."""
        if self.state is not ReplicaState.SERVING:
            return False
        sch = self.engine.scheduler
        return sch.max_queue is None or sch.queue_depth < sch.max_queue

    # -- work --------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw) -> int:
        """Guarded ``engine.submit``: a non-SERVING replica sheds with
        ``ReplicaUnavailable`` (an ``AdmissionRejected``)."""
        if self.state is not ReplicaState.SERVING:
            raise ReplicaUnavailable(self.name, self.state,
                                     self.queue_depth)
        return self.engine.submit(prompt, max_new_tokens, **kw)

    def transfer_in(self, req) -> int:
        """Guarded ``engine.transfer_in`` (same shed contract)."""
        if self.state is not ReplicaState.SERVING:
            raise ReplicaUnavailable(self.name, self.state,
                                     self.queue_depth)
        return self.engine.transfer_in(req)

    def step(self):
        """One engine iteration. ``replica.die`` is the chaos hook: an
        armed fault raising here is indistinguishable (to the router)
        from the engine crashing mid-step — the router marks the
        replica DEAD and fails its in-flight requests over."""
        if self.state is ReplicaState.DEAD:
            raise ReplicaDead(self.name, self.error)
        if self.state is ReplicaState.STARTING:
            self.start()
        faults.point("replica.die")
        self.steps += 1
        return self.engine.step()

    # -- views -------------------------------------------------------------

    def slo_burn(self) -> Optional[float]:
        """Max burn rate across the engine's declared SLO objectives
        (side-effect-free evaluation), or None without objectives /
        before any sample. The drain controller's input."""
        eng = self.engine
        if eng.slo is None:
            return None
        statuses = eng.slo.evaluate(eng.metrics, record=False)
        if not statuses:
            return None
        return max(st["burn_rate"] for st in statuses.values())

    def health(self) -> Dict:
        """The engine's ``health()`` wrapped with replica identity:
        ``status`` becomes ``"dead"``/``"draining"`` when the lifecycle
        overrides the engine view (a draining replica is healthy but
        must receive no traffic)."""
        if self.state is ReplicaState.DEAD:
            return {"status": "dead", "replica": self.name,
                    "role": self.role, "accepting": False,
                    "error": repr(self.error) if self.error else None}
        out = self.engine.health()
        out["replica"] = self.name
        out["role"] = self.role
        if self.state is not ReplicaState.SERVING:
            out["status"] = self.state.value
            out["accepting"] = False
        return out
