"""Horizontal serving tier: N ``ServingEngine`` replicas behind a
prefix-affinity router with disaggregated prefill/decode and
SLO-burn-driven drain (ROADMAP item 2; docs/serving.md §Router).

    replica.py     ``EngineReplica`` — one engine + the
                   STARTING→SERVING→DRAINING→DEAD lifecycle, cheap
                   placement signals, per-replica record labels
    policies.py    ``LeastLoaded`` (queue depth + free-page budget)
                   and ``PrefixAffinity`` (route prompts whose leading
                   pages are hot on a replica's ``PrefixCache`` there)
    router.py      ``Router`` — the submit/step/run/stream client
                   surface over the fleet, prefill→decode handoff via
                   the engine's ``transfer_out``/``transfer_in``
                   re-entry path, replica-death mass failover with
                   seed-replayed sampling keys, and the elastic
                   surface (``add_replica``/``remove_replica`` with
                   drain→rebalance→retire semantics)
    controller.py  ``SLOBurnController`` — drain replicas burning
                   their SLO error budget, rebalance their queues,
                   resume on recovery; ``AutoscaleController`` — grow
                   the fleet on sustained burn/queue-growth/shed,
                   shrink it on sustained idleness (hysteresis +
                   cool-downs); ``ControllerChain`` composes them

Everything the router does preserves the oracle contract: tokens are
identical (byte-identical sampled) to a single engine / ``generate()``.
"""

from distkeras_tpu.serving.router.controller import (  # noqa: F401
    AutoscaleController, ControllerChain, SLOBurnController)
from distkeras_tpu.serving.router.policies import (  # noqa: F401
    LeastLoaded, PlacementPolicy, PrefixAffinity)
from distkeras_tpu.serving.router.replica import (  # noqa: F401
    EngineReplica, ReplicaDead, ReplicaState, ReplicaUnavailable)
from distkeras_tpu.serving.router.router import (  # noqa: F401
    Router, RouterClient)
