"""Placement policies: which replica a new request lands on.

A policy ranks the admission-capable candidates; the router tries them
in order (the next candidate absorbs an ``AdmissionRejected`` from the
first, so a full queue degrades placement instead of shedding the
request while capacity exists elsewhere).

``LeastLoaded`` is the load-signal baseline: emptiest queue first,
then the biggest free-page budget — exactly the two numbers
``health()`` exposes, read through the replica's cheap accessors (no
device sync, no SLO evaluation).

``PrefixAffinity`` is the KV-locality policy the prefix cache makes
profitable: route a prompt to the replica whose ``PrefixCache``
already holds its leading pages, so prefill skips the shared positions
there (``PrefixCache.affinity_key`` is the O(1) routing key;
``probe()`` the side-effect-free hot-counter accessor). Replicas that
have never seen the prefix fall through to the load order — which also
spreads DISTINCT templates across the fleet (each template sticks to
the replica that first served it), partitioning the fleet's aggregate
prefix-cache capacity instead of duplicating every template
everywhere. See ``docs/serving.md`` §Router.
"""

from __future__ import annotations

from typing import List, Sequence

from distkeras_tpu.serving.router.replica import EngineReplica

__all__ = ["LeastLoaded", "PlacementPolicy", "PrefixAffinity",
           "resolve_policy"]


class PlacementPolicy:
    """Rank candidate replicas for one placement, best first. The
    router calls ``rank`` with the SERVING, role-eligible candidates
    and tries them in order."""

    def rank(self, candidates: Sequence[EngineReplica],
             prompt) -> List[EngineReplica]:
        raise NotImplementedError


class LeastLoaded(PlacementPolicy):
    """Emptiest queue, then largest free-page budget, then fewest
    occupied slots; replica name as the deterministic tiebreak (tests
    and traces stay reproducible)."""

    def rank(self, candidates, prompt):
        return sorted(
            candidates,
            key=lambda r: (r.queue_depth, -r.free_pages, r.occupied,
                           r.name))


class PrefixAffinity(PlacementPolicy):
    """Replicas whose prefix cache holds the prompt's leading page
    first (hottest chain wins); everything else in the fallback
    policy's order. A replica drowning in backlog is skipped even on a
    cache hit (``max_queue_advantage``): affinity is a prefill
    discount, not a reason to queue behind ``n`` strangers."""

    def __init__(self, fallback: PlacementPolicy = None,
                 max_queue_advantage: int = 4):
        self.fallback = fallback if fallback is not None else LeastLoaded()
        self.max_queue_advantage = int(max_queue_advantage)

    def rank(self, candidates, prompt):
        ordered = self.fallback.rank(candidates, prompt)
        if not ordered:
            return ordered
        min_depth = min(r.queue_depth for r in ordered)
        hot, cold = [], []
        for r in ordered:
            cache = r.engine.prefix
            hits = None
            if cache is not None:
                hits = cache.probe(cache.affinity_key(prompt))
            if hits is not None and (
                    r.queue_depth - min_depth
                    <= self.max_queue_advantage):
                hot.append((hits, r))
            else:
                cold.append(r)
        # hottest chain first; the fallback order breaks hit ties
        hot.sort(key=lambda hr: -hr[0])
        return [r for _, r in hot] + cold


def resolve_policy(policy) -> PlacementPolicy:
    """Router kwarg policy: a ``PlacementPolicy`` passes through;
    ``"least_loaded"`` / ``"prefix_affinity"`` name the built-ins."""
    if isinstance(policy, PlacementPolicy):
        return policy
    if policy == "least_loaded":
        return LeastLoaded()
    if policy == "prefix_affinity":
        return PrefixAffinity()
    raise ValueError(
        f"unknown placement policy {policy!r}: pass 'least_loaded', "
        "'prefix_affinity' or a PlacementPolicy instance")
