"""The router: N engine replicas behind one submit/step/run surface.

``Router`` mirrors the single-engine client API (``submit`` →
fleet-wide request id, ``step`` → finished requests, ``run`` → drain,
``stream`` → incremental tokens, ``cancel``, ``health``) over a fleet
of ``EngineReplica``s, adding the three fleet-only behaviors:

* **Placement** (``policies``): every submit is dispatched to one
  SERVING replica — prefix-affinity (route prompts whose leading pages
  are hot on a replica's ``PrefixCache`` to that replica) or
  least-loaded (queue depth + free-page budget). A replica that sheds
  (``AdmissionRejected``) falls through to the next candidate; the
  router sheds only when EVERY eligible replica refused.

* **Disaggregated prefill/decode** (replica ``role``): fresh requests
  land on prefill-class replicas; the moment a stream emits its first
  token the router hands it to a decode-class replica through
  ``ServingEngine.transfer_out``/``transfer_in`` — the proven
  preempt/resume re-entry path, so the handoff is a token-identical
  re-prefill of ``prompt + generated[:-1]`` on the target (page
  shipping is the documented follow-up; the oracle stays this path).

* **Failure + pressure handling**: any exception escaping a replica's
  ``step()`` (the ``replica.die`` chaos point included) marks it DEAD
  and mass-fails-over its in-flight requests — re-admitted elsewhere
  from the router-visible request log alone (host token mirror; the
  sampling key is REPLAYED from the request seed, one split per
  emitted token — the engine's exact key-stream rule — so sampled
  streams complete byte-identically without trusting any dead-engine
  state). ``drain()``ed replicas shed new work while in-flight streams
  finish; the ``SLOBurnController`` drives drains from SLO burn rates
  and rebalances queued work off draining replicas.

* **Elasticity** (``add_replica``/``remove_replica``): the fleet grows
  and shrinks mid-flight. Removal is drain → rebalance queued → retire
  once empty (the end-of-step sweep), DEAD replicas garbage-collect
  through the same retiring path, and every mutation lands in
  ``fleet_events`` + the ``router.fleet_size`` gauge so the recovery
  report can draw the fleet-size timeline. ``AutoscaleController``
  closes the loop: live burn/queue-growth/shed signals in,
  ``add_replica``/``remove_replica`` out, with hysteresis and
  cool-downs. Deadlines survive every move: the REMAINING budget (not
  the original value) follows a stream across handoff, rebalance and
  failover — a transferred request can never get its clock reset.

Token-identity contract (the oracle tests pin it): every request
routed, handed off, failed over or drained through the router produces
the same tokens — byte-identical for sampled streams — as a single
engine (equivalently ``generate()``).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.obs.recorder import resolve_recorder
from distkeras_tpu.obs.timeseries import TimeSeries
from distkeras_tpu.resilience import faults
from distkeras_tpu.serving.engine import DegradedRequest, ServingEngine
from distkeras_tpu.serving.router.policies import resolve_policy
from distkeras_tpu.serving.router.replica import (EngineReplica,
                                                  ReplicaDead,
                                                  ReplicaState)
from distkeras_tpu.serving.scheduler import (AdmissionRejected, Request,
                                             RequestState,
                                             TERMINAL_STATES)

__all__ = ["Router", "RouterClient"]


def _replay_key(seed: int, n_tokens: int) -> np.ndarray:
    """The sampling key of a live stream that has emitted ``n_tokens``
    tokens, reconstructed from its seed alone: the engine's key stream
    advances by exactly ONE ``split`` (carrying row 0) per emitted
    token — first token, plain decode, fused windows and speculative
    verify all keep that rule — so failover needs no key state from
    the dead replica."""
    key = jax.random.PRNGKey(int(seed))
    for _ in range(int(n_tokens)):
        key = jax.random.split(key)[0]
    return np.array(key)


class _Tracked:
    """Router-side record of one in-flight request: the stable
    fleet-wide id, the replica currently serving it, and the live
    ``Request`` object (the router's request log — its host token
    mirror is what failover trusts)."""

    __slots__ = ("grid", "replica", "req", "handoffs", "failovers")

    def __init__(self, grid: int, replica: EngineReplica, req: Request):
        self.grid = grid
        self.replica = replica          # None while orphaned
        self.req = req
        self.handoffs = 0
        self.failovers = 0


class Router:
    """See module doc. ``replicas`` is a sequence of ``EngineReplica``
    (or bare paged ``ServingEngine``s, auto-wrapped ``role="both"``
    with their ``engine_id`` as the replica name). Roles either all
    ``"both"`` (homogeneous fleet) or at least one ``"prefill"`` AND
    one ``"decode"`` (disaggregated; ``"both"`` replicas then serve in
    both pools). ``policy`` places fresh admissions;
    decode-handoff/failover placement always uses the same policy over
    the decode-capable pool."""

    #: router steps between attached-controller ticks
    _CTL_EVERY = 16

    def __init__(self, replicas, *, policy="prefix_affinity",
                 start: bool = True, timeseries=None):
        reps: List[EngineReplica] = []
        for r in replicas:
            if isinstance(r, ServingEngine):
                r = EngineReplica(r)
            reps.append(r)
        if not reps:
            raise ValueError("Router needs at least one replica")
        names = [r.name for r in reps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        roles = {r.role for r in reps}
        if roles - {"both"} and not (
                {"prefill", "both"} & roles and {"decode", "both"} & roles):
            raise ValueError(
                "disaggregated fleets need at least one prefill-capable "
                "AND one decode-capable replica "
                f"(roles: {sorted(roles)})")
        self.replicas = reps
        self.policy = resolve_policy(policy)
        #: disaggregated = any role-split replica exists: the router
        #: then migrates streams off prefill-class replicas at first
        #: token
        self.disaggregated = bool(roles - {"both"})
        self.controller = None
        self._grid = itertools.count()
        self._requests: Dict[int, _Tracked] = {}
        #: (id(replica), local rid) -> grid
        self._local: Dict[Tuple[int, int], int] = {}
        #: detached requests awaiting a replica (all targets shed)
        self._orphans: List[_Tracked] = []
        #: terminals surfaced out-of-band (death sweep, cancel races)
        self._finish_buf: List[Tuple[int, Request]] = []
        self._steps = 0
        self.recorder = resolve_recorder()
        # registry series for exporters (labeled by replica where it
        # means something) + plain totals for counters()/bench reads
        reg = obs.get_registry()
        self._c_dispatch = reg.counter("router.dispatched")
        self._c_handoff = reg.counter("router.handoffs")
        self._c_failover = reg.counter("router.failovers")
        self._c_rebalance = reg.counter("router.rebalanced")
        self._c_shed = reg.counter("router.rejected")
        self._c_added = reg.counter("router.replicas_added")
        self._c_removed = reg.counter("router.replicas_removed")
        self._c_deadline = reg.counter("router.deadline_expired")
        self._g_fleet = reg.gauge("router.fleet_size")
        self._n: Dict[str, int] = {
            "dispatched": 0, "handoffs": 0, "failovers": 0,
            "rebalanced": 0, "rejected": 0, "deadline_expired": 0,
            "replicas_added": 0, "replicas_removed": 0}
        #: bumped on every fleet mutation (add/remove/death) — harness
        #: code (loadgen.replay) keys per-engine instrumentation sync
        #: off this instead of diffing the replica list
        self._fleet_version = 0
        #: (router step, event, replica name) for add/remove/dead —
        #: the fleet-size timeline's raw material
        self.fleet_events: List[Tuple[int, str, str]] = []
        self._g_fleet.set(len(reps))
        # fleet-level time series (obs.timeseries): scrapes the GLOBAL
        # registry (router.* counters, slo gauges, device watermarks)
        # on the controller cadence; per-replica serving series live on
        # each engine's OWN scraper (engine-id-tagged). ``None`` =
        # default scraper, ``False`` = off, instance = used as-is.
        if timeseries is False:
            self.timeseries = None
        elif isinstance(timeseries, TimeSeries):
            self.timeseries = timeseries
        else:
            self.timeseries = TimeSeries(
                obs.get_registry(),
                interval_s=0.0 if timeseries is None else float(timeseries),
                tags={"component": "router"})
        if start:
            for r in reps:
                if r.state is ReplicaState.STARTING:
                    r.start()

    # -- pools -------------------------------------------------------------

    def _admission_pool(self) -> List[EngineReplica]:
        """Replicas a FRESH request may land on."""
        return [r for r in self.replicas
                if r.state is ReplicaState.SERVING
                and r.role in ("both", "prefill")]

    def _decode_pool(self) -> List[EngineReplica]:
        """Replicas a decode-progress stream may land on."""
        return [r for r in self.replicas
                if r.state is ReplicaState.SERVING
                and r.role in ("both", "decode")]

    def replica(self, name: str) -> EngineReplica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(name)

    def attach_controller(self, controller) -> None:
        """Tick ``controller`` every ``_CTL_EVERY`` router steps (the
        SLO-burn drain controller's cadence)."""
        self.controller = controller

    # -- fleet elasticity --------------------------------------------------

    def add_replica(self, replica, *, start: bool = True) -> EngineReplica:
        """Grow the fleet mid-flight. ``replica`` is an
        ``EngineReplica``, a bare paged ``ServingEngine`` (auto-wrapped
        ``role="both"``) or a zero-arg factory returning either — the
        factory form is what ``AutoscaleController`` holds, so engine
        construction cost is only paid when a scale-up actually fires.
        The new replica joins the placement pools immediately (next
        ``submit``/``_place`` sees it); queued work already on other
        replicas moves only through an explicit ``rebalance_queued``
        or the normal shed-retry paths. Returns the added replica."""
        if not isinstance(replica, (EngineReplica, ServingEngine)) \
                and callable(replica):
            replica = replica()
        if isinstance(replica, ServingEngine):
            replica = EngineReplica(replica)
        if any(r.name == replica.name for r in self.replicas):
            raise ValueError(
                f"duplicate replica name: {replica.name!r}")
        self.replicas.append(replica)
        if replica.role != "both":
            self.disaggregated = True
        self._fleet_version += 1
        self._c_added.inc(replica=replica.name)
        self._n["replicas_added"] += 1
        self.fleet_events.append((self._steps, "add", replica.name))
        self._g_fleet.set(len(self.replicas))
        if self.recorder.enabled:
            self.recorder.record(
                "router.replica_added", replica=replica.name,
                role=replica.role, fleet=len(self.replicas))
        if start and replica.state is ReplicaState.STARTING:
            replica.start()
        return replica

    def remove_replica(self, name: str) -> EngineReplica:
        """Shrink the fleet: drain ``name`` (admission closes, in-flight
        streams finish in place through the normal drain contract),
        rebalance its queued work onto the rest of the fleet, and mark
        it retiring — the end-of-step sweep pops it from the fleet once
        it is empty. A DEAD replica is garbage-collected through the
        same path (its in-flight work was already failed over), so dead
        weight and planned retirement share one bookkeeping funnel.
        Raises when removing the last live admission-capable (or, in a
        disaggregated fleet, decode-capable) replica."""
        rep = self.replica(name)
        if rep.state is not ReplicaState.DEAD:
            survivors = [r for r in self.replicas
                         if r is not rep and not r.retiring
                         and r.state is not ReplicaState.DEAD]
            if not any(r.role in ("both", "prefill") for r in survivors) \
                    or (self.disaggregated and not any(
                        r.role in ("both", "decode") for r in survivors)):
                raise ValueError(
                    f"cannot remove {name!r}: the fleet would have no "
                    "live admission/decode-capable replica left")
            if rep.state is not ReplicaState.DRAINING:
                rep.drain()
            rep.retiring = True
            self.rebalance_queued(rep)
        else:
            rep.retiring = True
        self._retire_pass()
        return rep

    def _retire_pass(self) -> None:
        """Pop retiring replicas that have gone empty (and retiring
        DEAD replicas outright — after re-homing any stragglers a
        death outside ``step()`` left behind)."""
        for r in list(self.replicas):
            if not r.retiring:
                continue
            if r.state is ReplicaState.DEAD:
                if any(tr.replica is r
                       for tr in self._requests.values()):
                    # died outside step() (operator mark_dead): the
                    # failover sweep never ran for it — run it now so
                    # retirement cannot strand tracked requests
                    self._on_replica_death(
                        r, r.error or ReplicaDead(r.name))
            elif r.pending:
                continue
            self.replicas.remove(r)
            self._fleet_version += 1
            self._c_removed.inc(replica=r.name)
            self._n["replicas_removed"] += 1
            self.fleet_events.append((self._steps, "remove", r.name))
            self._g_fleet.set(len(self.replicas))
            if self.recorder.enabled:
                self.recorder.record(
                    "router.replica_removed", replica=r.name,
                    state=r.state.value, fleet=len(self.replicas))

    def fleet_counts(self) -> Dict[str, int]:
        """Replica-lifecycle census: total plus per-state counts (the
        fleet-size timeline samples this)."""
        out = {"total": len(self.replicas), "serving": 0,
               "starting": 0, "draining": 0, "dead": 0}
        for r in self.replicas:
            out[r.state.value] += 1
        return out

    # -- client surface ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw) -> int:
        """Place one request on the fleet; returns its FLEET-WIDE id
        (stable across handoffs and failovers — local engine rids are
        an implementation detail). Tries the policy's ranked candidates
        in order; raises ``AdmissionRejected`` only when every eligible
        replica shed."""
        # chaos hook: a dispatch fault fires BEFORE any placement or
        # tracking state mutates, so a failed dispatch leaves the
        # router consistent (the caller retries wholesale)
        faults.point("router.dispatch")
        candidates = self._admission_pool()
        last_shed: Optional[AdmissionRejected] = None
        for r in self.policy.rank(candidates, prompt):
            try:
                rid = r.submit(prompt, max_new_tokens, **kw)
            except AdmissionRejected as e:
                last_shed = e
                continue
            grid = next(self._grid)
            tr = _Tracked(grid, r, r.engine[rid])
            self._requests[grid] = tr
            self._local[(id(r), rid)] = grid
            self._c_dispatch.inc(replica=r.name)
            self._n["dispatched"] += 1
            return grid
        self._c_shed.inc()
        self._n["rejected"] += 1
        if last_shed is not None:
            raise last_shed
        raise AdmissionRejected(0, 0)    # no admission-capable replica

    def __getitem__(self, grid: int) -> Request:
        """The live ``Request`` behind a fleet id (its host token
        mirror — the object may move between replicas)."""
        return self._requests[grid].req

    @property
    def pending(self) -> bool:
        return bool(self._requests or self._finish_buf)

    def step(self) -> Dict[int, Request]:
        """One fleet iteration: every live replica advances one engine
        iteration (a replica failure here triggers the failover sweep,
        not an exception), then — disaggregated fleets — streams whose
        first token just landed on a prefill-class replica hand off to
        the decode pool. Returns ``{fleet id: terminal Request}``."""
        finished: Dict[int, Request] = {}
        for grid, req in self._finish_buf:
            finished[grid] = req
        self._finish_buf.clear()
        for r in list(self.replicas):
            if r.state is ReplicaState.DEAD or not r.pending:
                continue
            try:
                done = r.step()
            except Exception as e:     # lint: allow-swallow (fleet failover: the error is kept on the replica and every request is re-homed)
                self._on_replica_death(r, e)
                continue
            for req in done:
                grid = self._local.pop((id(r), req.rid), None)
                if grid is None:
                    continue           # not router-placed (direct use)
                tr = self._requests.pop(grid, None)
                if tr is not None:
                    self._stamp(tr)
                finished[grid] = req
        if self.disaggregated:
            self._handoff_pass()
        if self._orphans:
            self._retry_orphans()
        self._retire_pass()
        self._steps += 1
        if self.controller is not None \
                and self._steps % self._CTL_EVERY == 0:
            self.controller.tick()
        if self.timeseries is not None \
                and self._steps % self._CTL_EVERY == 0:
            # fleet scrape on the controller cadence — host-side
            # registry reads only, no device syncs
            self.timeseries.maybe_sample(step=self._steps)
        for grid, req in self._finish_buf:
            finished[grid] = req       # produced by handoff/cancel races
        self._finish_buf.clear()
        return finished

    def run(self, max_steps: Optional[int] = None,
            on_degraded: str = "raise") -> Dict[int, np.ndarray]:
        """Drive ``step()`` until every routed request is terminal;
        returns ``{fleet id: tokens}`` — the same contract as
        ``ServingEngine.run`` (``DegradedRequest`` on TIMED_OUT /
        CANCELLED drains unless ``on_degraded="return"``)."""
        if on_degraded not in ("raise", "return"):
            raise ValueError(
                f"on_degraded must be 'raise' or 'return', "
                f"got {on_degraded!r}")
        out: Dict[int, np.ndarray] = {}
        steps = 0
        while self.pending:
            for grid, req in self.step().items():
                if req.state is not RequestState.FINISHED \
                        and on_degraded == "raise":
                    self.recorder.auto_dump(
                        f"degraded_request:{req.state.value}")
                    raise DegradedRequest(req)
                out[grid] = req.tokens
            steps += 1
            if max_steps is not None and steps >= max_steps \
                    and self.pending:
                raise RuntimeError(
                    f"router made no full drain in {max_steps} steps "
                    f"({len(self._requests)} requests in flight)")
        return out

    def stream(self, grid: int):
        """Generator of this request's GENERATED tokens as the fleet
        produces them (drives ``step()`` while waiting — single-thread
        streaming; finished neighbours drained meanwhile surface via
        later ``step()``/``run`` calls is NOT supported here, so use
        one driver). The stream is seamless across handoffs and
        failovers: the router-side token log persists while the
        request moves."""
        tr = self._requests.get(grid)
        if tr is None:
            raise KeyError(grid)
        sent = 0
        while True:
            gen = tr.req.generated
            while sent < len(gen):
                yield int(gen[sent])
                sent += 1
            if tr.req.state in TERMINAL_STATES \
                    and sent >= len(tr.req.generated):
                return
            self.step()

    def cancel(self, grid: int) -> Request:
        """Cancel a routed request wherever it currently lives."""
        tr = self._requests.pop(grid)
        self._stamp(tr)
        if tr.replica is None:                    # orphaned: no engine
            self._orphans = [o for o in self._orphans if o is not tr]
            tr.req.state = RequestState.CANCELLED
            return tr.req
        self._local.pop((id(tr.replica), tr.req.rid), None)
        return tr.replica.engine.cancel(tr.req.rid)

    # -- migration ---------------------------------------------------------

    def _stamp(self, tr: _Tracked) -> None:
        """Copy the router-side movement counts onto the request before
        it is delivered: terminal requests carry how many times they
        moved (handoff/rebalance) and how many replica deaths they
        survived — the recovery accounting's per-request ground truth."""
        tr.req.n_handoffs = tr.handoffs
        tr.req.n_failovers = tr.failovers

    def _shrink_deadline(self, tr: _Tracked, req: Request,
                         src: EngineReplica) -> bool:
        """Carry the REMAINING deadline budget across a replica move.
        ``transfer_in`` restarts ``submit_t`` on the adopting engine's
        clock, so without this adjustment every migration would silently
        re-arm the full original budget. Returns False when the budget
        is already spent — the request is terminated TIMED_OUT at the
        router (it never reaches a new replica) and surfaced through
        the finish buffer."""
        if req.deadline_s is None:
            return True
        elapsed = max(0.0, src.engine.metrics.clock() - req.submit_t)
        remaining = req.deadline_s - elapsed
        if remaining <= 0:
            req.state = RequestState.TIMED_OUT
            self._requests.pop(tr.grid, None)
            self._stamp(tr)
            self._finish_buf.append((tr.grid, req))
            self._c_deadline.inc(src=src.name)
            self._n["deadline_expired"] += 1
            if self.recorder.enabled:
                self.recorder.record(
                    "router.deadline_expired", grid=tr.grid,
                    src=src.name, n_generated=len(req.generated))
            return False
        req.deadline_s = remaining
        return True

    def _targets_for(self, req: Request) -> List[EngineReplica]:
        pool = (self._decode_pool() if req.generated
                else self._admission_pool())
        return self.policy.rank(pool, req.prompt)

    def _place(self, tr: _Tracked, req: Request,
               exclude: Optional[EngineReplica] = None):
        """THE placement loop (every migration/failover/retry path
        funnels through here so the mapping bookkeeping cannot drift):
        try the policy's ranked targets; on success bind ``tr`` to the
        target and return it, else detach ``tr`` onto the orphan retry
        queue and return None."""
        for target in self._targets_for(req):
            if target is exclude:
                continue
            try:
                new_rid = target.transfer_in(req)
            except AdmissionRejected:
                continue
            tr.replica = target
            self._local[(id(target), new_rid)] = tr.grid
            return target
        tr.replica = None
        if tr not in self._orphans:
            self._orphans.append(tr)
        return None

    def _migrate(self, tr: _Tracked, counter, kind: str,
                 nkey: str) -> bool:
        """Move one live request off its replica through
        ``transfer_out``/``transfer_in``. Returns True when it landed
        somewhere; False when it finished during the pipeline drain
        (stays on the source for delivery) or no target accepted (the
        request is orphaned and retried next step)."""
        src = tr.replica
        old_key = (id(src), tr.req.rid)
        req = src.engine.transfer_out(tr.req.rid)
        if req is None:
            return False       # finished mid-drain; src delivers it
        self._local.pop(old_key, None)
        if not self._shrink_deadline(tr, req, src):
            return False       # budget spent mid-move: TIMED_OUT here
        target = self._place(tr, req, exclude=src)
        if target is None:
            return False
        counter.inc()
        self._n[nkey] += 1
        if self.recorder.enabled:
            self.recorder.record(
                f"router.{kind}", grid=tr.grid,
                src=src.name, dst=target.name,
                n_generated=len(req.generated))
        return True

    def _handoff_pass(self) -> None:
        """Disaggregated fleets: a stream whose first token landed on a
        prefill-class replica moves to the decode pool (token-identical
        re-prefill re-entry on the target)."""
        for tr in list(self._requests.values()):
            if tr.replica is None or tr.replica.role != "prefill":
                continue
            if tr.req.state is RequestState.DECODING \
                    and tr.req.generated:
                if self._migrate(tr, self._c_handoff, "handoff",
                                 "handoffs"):
                    tr.handoffs += 1

    def _retry_orphans(self) -> None:
        """Place detached requests that had nowhere to go (every
        target shed when they left their replica)."""
        orphans, self._orphans = self._orphans, []
        for tr in orphans:
            target = self._place(tr, tr.req)
            if target is not None and self.recorder.enabled:
                self.recorder.record(
                    "router.placed", grid=tr.grid, dst=target.name,
                    n_generated=len(tr.req.generated))

    def rebalance_queued(self, replica: EngineReplica) -> int:
        """Move a (typically draining) replica's QUEUED requests to the
        rest of the fleet; admitted streams stay and finish in place —
        the drain contract. Returns the number moved."""
        moved = 0
        for tr in list(self._requests.values()):
            if tr.replica is not replica:
                continue
            if tr.req.state is RequestState.QUEUED:
                if self._migrate(tr, self._c_rebalance, "rebalance",
                                 "rebalanced"):
                    tr.handoffs += 1
                    moved += 1
        return moved

    # -- failure handling --------------------------------------------------

    def _on_replica_death(self, replica: EngineReplica,
                          error: BaseException) -> None:
        """Replica failure = mass preemption at fleet scope: every
        in-flight request is re-admitted elsewhere from the router's
        request log alone — generated-token mirror plus a seed-replayed
        sampling key — and completes token-identically. Nothing from
        the dead engine (device state, pipeline, KV pages) is
        trusted."""
        replica.mark_dead(error)
        self._fleet_version += 1
        self.fleet_events.append((self._steps, "dead", replica.name))
        failed_over = 0
        for tr in list(self._requests.values()):
            if tr.replica is not replica:
                continue
            req = tr.req
            self._local.pop((id(replica), req.rid), None)
            if req.state in TERMINAL_STATES:
                # terminal but undelivered (the dying step's finished
                # list was lost with the exception): surface it now
                self._requests.pop(tr.grid, None)
                self._stamp(tr)
                self._finish_buf.append((tr.grid, req))
                continue
            if not self._shrink_deadline(tr, req, replica):
                continue       # budget spent before the re-admit
            # discard everything engine-local: the in-flight pipeline
            # step (recomputed identically), page/prefix bookkeeping,
            # and the slot key — replayed from the seed instead
            req.rng = _replay_key(req.seed, len(req.generated))
            tr.failovers += 1
            self._place(tr, req)
            self._c_failover.inc()
            self._n["failovers"] += 1
            failed_over += 1
        if self.recorder.enabled:
            self.recorder.record(
                "router.replica_dead", replica=replica.name,
                error=repr(error), failed_over=failed_over)
        self.recorder.auto_dump(f"replica_dead:{replica.name}")

    # -- views -------------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        """Plain fleet totals (the registry carries the same series,
        labeled by replica, for exporters)."""
        return dict(self._n)

    def health(self) -> Dict:
        """Fleet readiness: per-replica ``health()`` plus the fleet
        verdict — ``"ok"`` while every live replica is clean,
        ``"degraded"`` while any replica is breaching/draining/dead but
        admission is still possible somewhere, ``"saturated"`` when no
        replica accepts."""
        reps = {r.name: r.health() for r in self.replicas}
        accepting = any(r.accepting for r in self._admission_pool())
        clean = all(
            st.get("status") == "ok" for st in reps.values())
        status = ("ok" if accepting and clean
                  else "degraded" if accepting else "saturated")
        return {
            "status": status,
            "accepting": accepting,
            "replicas": reps,
            "in_flight": len(self._requests),
            "orphans": len(self._orphans),
            "counters": self.counters(),
        }

    def telemetry(self) -> Dict:
        """Cross-replica telemetry: ``obs.aggregate_serving()`` over
        the unified snapshot (per-replica component summaries + summed
        fleet totals) plus router counters and replica lifecycle
        states."""
        agg = obs.aggregate_serving()
        agg["router"] = self.counters()
        agg["states"] = {r.name: r.state.value for r in self.replicas}
        agg["fleet"] = self.fleet_counts()
        if self.timeseries is not None:
            agg["timeseries"] = self.timeseries.summary()
        return agg


#: the client-facing alias: ``Router`` IS the client surface
#: (submit/run/stream mirror the single-engine API); the name exists
#: so call sites can say what they hold
RouterClient = Router
