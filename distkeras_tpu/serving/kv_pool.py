"""KV cache pools for the serving engine: the legacy slab pool and the
block-pooled PAGED cache that replaced it as the engine default.

``KVPool`` (slab) reserves one resident ``[S, max_len]`` buffer row per
slot: occupancy is bounded by WORST-CASE length, so a pool sized for
8K-token requests wastes ~94% of its HBM on a workload whose median
request is 500 tokens. ``PagedKVPool`` is the vLLM/PagedAttention fix:
one fixed pool of ``[num_pages, Hkv, page_len, Dh]`` pages per layer,
a per-slot page table mapping logical position ``t`` to physical page
``table[slot, t // page_len]``, pages allocated on demand as requests
grow and returned the moment they finish. Occupancy tracks ACTUAL
tokens (within ``page_len`` rounding), which is what turns memory into
throughput: at equal HBM the paged pool admits however many requests
fit their real lengths, not ``HBM / max_len``.

On top of the pool, ``PrefixCache`` hash-conses shared prompt
prefixes: finished requests register their full (immutable) prompt
pages under a chained token hash, and a new request whose prompt
matches reuses those pages read-only (refcounted) — prefill then skips
the shared positions entirely. A PARTIAL page match is served
copy-on-write: the donor page is loaded into the prefill staging
cache, the chunks from the first divergent token overwrite its tail
there, and the insert writes the result to the request's own private
page — the shared original is never written.

Refcounting contract: a physical page is held by every slot whose
table points at it plus (for registered prefix pages) the cache node;
``decref`` to zero returns it to the free list. Pages the prefix cache
alone holds (``ref == 1``) are reclaimable LRU-leaf-first when
allocation pressure needs them.

Both pools compose with the int8 quantized cache (``dtype="int8"``):
payload and per-token-per-head scale planes share the page tables and
move together through every insert/load/gather program.

HOST KV OFFLOAD TIER (decode-kernel/offload PR, ROADMAP item 3b):
``PagedKVPool(host_pages=N)`` adds a host-memory page pool mirroring
the device pool's per-layer planes. ``offload_pages`` copies physical
device pages D2H (all layers' transfers enqueued async first, then
fenced and copied into the pinned host rows — the checkpoint-snapshot
discipline from docs/overlap.md) and ``restore_pages`` scatters them
back into freshly allocated device pages byte-identically. Two
consumers:

  * the serving engine's PREEMPTION path — a victim's pages swap out
    instead of being discarded, so resume is an H2D page copy + table
    restore instead of a full context re-prefill (order-of-magnitude
    cheaper eviction, which is what makes aggressive oversubscription
    safe);
  * ``PrefixCache`` eviction — a cold chain SPILLS its LRU leaves to
    host before dropping them outright, so the effective prefix-cache
    capacity multiplies: a later match restores the spilled page H2D
    and the chain serves hits again.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distkeras_tpu.models.decoding import (init_cache, pack_int4,
                                           unpack_int4)


@jax.jit
def _insert_row(pool, req_cache, slot):
    """Write a batch-1 request cache into pool row ``slot`` (``slot``
    is traced — one compiled program serves every slot index). The
    request cache may be SHORTER than the row (the prompt-length
    prefix): only its positions are written."""
    def write(pl, rq):
        return lax.dynamic_update_slice(
            pl, rq.astype(pl.dtype), (slot,) + (0,) * (pl.ndim - 1))
    return jax.tree_util.tree_map(write, pool, req_cache)


class KVPool:
    """S-slot slab-pooled KV cache over ``module``'s attention layers.

    ``cache`` is the live device pytree (the exact structure
    ``decode_step_slots`` consumes); ``insert`` replaces it — callers
    must not hold on to the old value."""

    def __init__(self, module, num_slots: int, max_len: int,
                 dtype=jnp.float32):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        self._module = module
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.dtype = dtype
        # init_cache validates max_len against the position table up
        # front (out-of-range gathers CLAMP under jit — silent wrong-
        # position logits otherwise)
        self.cache = init_cache(module, self.num_slots, self.max_len,
                                dtype)

    def make_request_cache(self):
        """A batch-1 cache with the pool's exact per-position layout —
        what per-request prefill fills and ``insert`` consumes."""
        return init_cache(self._module, 1, self.max_len, self.dtype)

    def insert(self, req_cache, slot: int,
               n_pos: Optional[int] = None) -> None:
        """Copy a batch-1 request cache (layout of
        ``make_request_cache``) into row ``slot``. ``n_pos`` bounds the
        copy to the positions the prompt actually filled — the full-row
        write (the pre-paged behavior, kept when ``n_pos`` is None) was
        a measurable admit-latency tax at large ``max_len``: it moved
        ``max_len``/prompt_len times the bytes the admit needed. The
        stale tail beyond ``n_pos`` is safe either way: the slot's own
        decode writes position t before the attention mask admits it.
        Like the ragged final prefill chunk, each distinct ``n_pos``
        is its own compiled program (same cardinality, prompt lengths).
        """
        if not 0 <= slot < self.num_slots:
            raise ValueError(
                f"slot {slot} out of range [0, {self.num_slots})")
        if n_pos is not None:
            if not 0 < n_pos <= self.max_len:
                raise ValueError(
                    f"n_pos must be in (0, {self.max_len}], got {n_pos}")
            req_cache = jax.tree_util.tree_map(
                lambda x: x[:, :, :n_pos], req_cache)
        self.cache = _insert_row(self.cache, req_cache, slot)


# --- paged pool -------------------------------------------------------------


#: refcount slot for "no page": table entries >= num_pages are the
#: unallocated sentinel (scatter drops, gather clamps into masked range)


@jax.jit
def _write_pages(pool, staging, table):
    """Scatter staging pages into the pool: logical page ``p`` of the
    batch-1 staging cache lands on physical page ``table[p]``; sentinel
    entries (>= N) drop. One compiled program serves every insert —
    which pages to SKIP (shared prefix pages, pages past the prompt)
    is encoded by the sentinel, not by program shape. int4 pools
    (``"q4"`` marker) nibble-pack the payload pages here: the staging
    cache stays unpacked (one int8 byte per entry, the shared dequant
    contract), the POOL planes are where the 2x byte saving lives."""
    def write(pl, st, packed):
        page_len = 2 * pl.shape[2] if packed else pl.shape[2]
        if st.ndim == 4:
            _, h, s_max, d = st.shape
            pages = st.reshape(h, s_max // page_len, page_len, d) \
                      .transpose(1, 0, 2, 3)
            if packed:
                pages = pack_int4(pages)
        else:
            _, h, s_max = st.shape
            pages = st.reshape(h, s_max // page_len, page_len) \
                      .transpose(1, 0, 2)
        return pl.at[table].set(pages.astype(pl.dtype), mode="drop")
    out = []
    for pl_kv, st_kv in zip(pool, staging):
        if pl_kv is None:
            out.append(None)
            continue
        q4 = "q4" in pl_kv
        out.append({
            key: pl if key == "q4"
            else write(pl, st_kv[key], q4 and key in ("k", "v"))
            for key, pl in pl_kv.items()})
    return out


@jax.jit
def _gather_rows(pool, ids):
    """Gather physical pages ``ids`` out of every pool plane — the D2H
    offload read. One compiled program per (structure, n) pair, the
    same bounded cardinality as the per-``n_pos`` insert programs."""
    return jax.tree_util.tree_map(lambda p: p[ids], pool)


@jax.jit
def _scatter_rows(pool, ids, vals):
    """Scatter host page payloads ``vals`` into pool rows ``ids`` —
    the H2D restore write (byte-identical: storage dtypes in, storage
    dtypes out, no recompute anywhere)."""
    return jax.tree_util.tree_map(
        lambda p, v: p.at[ids].set(v.astype(p.dtype)), pool, vals)


@jax.jit
def _load_pages(staging, pool, table, valid):
    """Gather pool pages into the batch-1 staging cache: logical page
    ``p`` becomes ``pool[table[p]]`` where ``valid[p]``, else keeps the
    staging content. The prefix-cache hit path: shared pages (and a
    copy-on-write donor) materialize as the staging prefix the
    remaining prefill chunks attend to."""
    def load(st, pl, packed):
        g = pl[table]                        # [P, H, page_len(/2), D?]
        if packed:
            g = unpack_int4(g)               # [P, H, page_len, D]
        page_len = g.shape[2]
        if st.ndim == 4:
            _, h, s_max, d = st.shape
            cur = st.reshape(h, s_max // page_len, page_len, d) \
                    .transpose(1, 0, 2, 3)
            sel = jnp.where(valid[:, None, None, None],
                            g.astype(cur.dtype), cur)
            return sel.transpose(1, 0, 2, 3).reshape(1, h, s_max, d)
        _, h, s_max = st.shape
        cur = st.reshape(h, s_max // page_len, page_len) \
                .transpose(1, 0, 2)
        sel = jnp.where(valid[:, None, None], g.astype(cur.dtype), cur)
        return sel.transpose(1, 0, 2).reshape(1, h, s_max)
    out = []
    for st_kv, pl_kv in zip(staging, pool):
        if st_kv is None:
            out.append(None)
            continue
        q4 = "q4" in pl_kv
        out.append({
            key: st if key == "q4"
            else load(st, pl_kv[key], q4 and key in ("k", "v"))
            for key, st in st_kv.items()})
    return out


class PagedKVPool:
    """Fixed pool of ``num_pages`` KV pages per layer + per-slot page
    tables + host-side refcounted allocation.

    ``cache`` is the live device pytree ``decode_step_slots_paged``
    consumes; ``tables`` is the host ``[S, P]`` int32 page-table array
    (``device_tables()`` returns the cached device mirror, invalidated
    by any mutation). A table entry of ``num_pages`` is the
    unallocated sentinel."""

    def __init__(self, module, num_slots: int, max_len: int,
                 page_len: int = 16, num_pages: Optional[int] = None,
                 host_pages: int = 0, dtype=jnp.float32,
                 hbm_budget: Optional[int] = None,
                 reserve_bytes: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        if page_len < 1:
            raise ValueError(f"page_len must be >= 1, got {page_len}")
        self._module = module
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.page_len = int(page_len)
        self._int4 = isinstance(dtype, str) and dtype == "int4"
        if self._int4 and self.page_len % 2:
            raise ValueError(
                f"int4 pages nibble-pack two positions per byte; "
                f"page_len must be even, got {page_len}")
        #: logical pages per slot: the page-table width (covers max_len)
        self.pages_per_slot = -(-self.max_len // self.page_len)
        #: bytes ONE physical page occupies across every layer's
        #: planes — quantized payload (int4: packed, page_len // 2
        #: bytes per head-dim row) AND the per-token scale planes.
        #: Satellite fix: budget math that counts payload bytes only
        #: overcommits quantized admission by the scale-plane share
        #: (Dh=64 -> ~6% at int8, ~12% at int4 f32 scales).
        self.page_bytes = self._page_bytes(module, self.page_len, dtype,
                                           self.max_len)
        if hbm_budget is not None:
            # size the pool to a BYTE budget: pages = what fits after
            # reserved bytes (weights etc.) — quantization translates
            # directly into more resident pages, hence more admitted
            # streams under the same budget
            if num_pages is not None:
                raise ValueError(
                    "pass num_pages or hbm_budget, not both")
            avail = int(hbm_budget) - int(reserve_bytes)
            num_pages = avail // self.page_bytes
            if num_pages < 1:
                raise ValueError(
                    f"hbm_budget {hbm_budget} - reserve {reserve_bytes}"
                    f" does not fit one {self.page_bytes}-byte page")
        if num_pages is None:
            # capacity parity with the slab pool by default; real
            # deployments size this to the HBM budget and rely on
            # cost-aware admission + preemption
            num_pages = self.num_slots * self.pages_per_slot
        self.num_pages = int(num_pages)
        if self.num_pages < 1:
            raise ValueError(
                f"num_pages must be >= 1, got {self.num_pages}")
        # a pool SMALLER than worst-case-per-request is legitimate —
        # that is what cost-aware admission is for; the engine rejects
        # individual requests whose own worst case exceeds the pool
        self.dtype = dtype
        # page pool: init_cache's batch axis is the PAGE axis; the
        # position table is validated against max_len (check_len), not
        # the page length
        self.cache = init_cache(module, self.num_pages, self.page_len,
                                dtype, check_len=self.max_len)
        if self._int4:
            # the POOL stores packed nibbles: the unpacked-payload
            # planes init_cache built become [N, H, page_len//2, D]
            # byte planes (zeros pack to zeros — no convert pass)
            self.cache = [
                kv if kv is None else {
                    key: (jnp.zeros(a.shape[:2] + (a.shape[2] // 2,)
                                    + a.shape[3:], jnp.int8)
                          if key in ("k", "v") else a)
                    for key, a in kv.items()}
                for kv in self.cache]
        self.tables = np.full((self.num_slots, self.pages_per_slot),
                              self.num_pages, np.int32)
        #: cached [pages_per_slot] logical-page index — reused by the
        #: serving loop's per-iteration vector scans (pages_per_slot is
        #: fixed at construction; rebuilding the arange every decode
        #: iteration is avoidable hot-loop churn)
        self.page_index = np.arange(self.pages_per_slot)
        self.ref = np.zeros(self.num_pages, np.int64)
        # pop() hands out page 0 first (deterministic placement for
        # tests/traces, same convention as the slot allocator)
        self._free = list(range(self.num_pages))[::-1]
        self._tables_dev = None
        # --- host offload tier (module doc): a host-memory mirror of
        # the page planes, sized independently of the device pool —
        # host RAM is an order of magnitude cheaper than HBM, so this
        # is where preemption victims and cold prefix chains go
        self.host_pages = int(host_pages)
        if self.host_pages < 0:
            raise ValueError(
                f"host_pages must be >= 0, got {host_pages}")
        self.host_cache = None
        self._host_free: List[int] = []
        if self.host_pages:
            self.host_cache = [
                None if kv is None else
                {key: np.zeros((self.host_pages,) + tuple(a.shape[1:]),
                               a.dtype)
                 for key, a in kv.items()}
                for kv in self.cache]
            self._host_free = list(range(self.host_pages))[::-1]
        #: offload odometers (cumulative since construction — the
        #: engine publishes per-window deltas into ServingMetrics)
        self.pages_offloaded = 0
        self.pages_restored = 0
        self.offload_bytes = 0
        #: async swap-out (tree-speculation PR satellite): offload
        #: batches whose D2H copies are enqueued but not yet fenced
        #: into the host rows — each entry {"hids": [...], "dev":
        #: gathered device pages}. The gather is a jitted snapshot, so
        #: holding it is safe against later cache mutation; it pins
        #: device memory until the fence, bounded by outstanding swaps.
        self._pending_host: List[Dict] = []
        #: lazy-fence odometer (tests pin laziness through it)
        self.host_fences = 0

    @staticmethod
    def _page_bytes(module, page_len: int, dtype, max_len: int) -> int:
        """Per-physical-page byte cost across all layers, from an
        abstract (eval_shape — nothing allocated) one-page probe:
        payload planes (int4: halved, two nibbles per byte) plus scale
        planes. The structural ``"q4"`` marker is per-LAYER, not
        per-page, and is excluded."""
        probe = jax.eval_shape(
            lambda: init_cache(module, 1, page_len, dtype,
                               check_len=max_len))
        int4 = isinstance(dtype, str) and dtype == "int4"
        total = 0
        for kv in probe:
            if kv is None:
                continue
            for key, a in kv.items():
                if key == "q4":
                    continue
                n = int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
                if int4 and key in ("k", "v"):
                    n //= 2
                total += n
        return total

    # -- device views -------------------------------------------------------

    def make_request_cache(self):
        """The batch-1 prefill staging cache: ``pages_per_slot *
        page_len`` positions (a page-multiple, so page loads/inserts
        reshape exactly), position-validated at ``max_len`` — prefill
        never writes past it."""
        return init_cache(self._module, 1,
                          self.pages_per_slot * self.page_len,
                          self.dtype, check_len=self.max_len)

    def device_tables(self):
        """The [S, P] page tables on device (cached; any host-side
        table mutation invalidates). Built from a SNAPSHOT of the host
        array: the CPU client zero-copy aliases suitably aligned numpy
        buffers into device memory, and the zero-bubble serving loop
        keeps launched programs in flight while the host mutates
        ``tables`` — without the copy an in-flight step could read a
        page assignment made after its dispatch."""
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self.tables.copy())
        return self._tables_dev

    def _dirty(self):
        self._tables_dev = None

    # -- allocation ---------------------------------------------------------

    def pages_for(self, n_positions: int) -> int:
        """Pages required to hold ``n_positions`` cache positions."""
        return -(-int(n_positions) // self.page_len)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def shared_pages(self) -> int:
        """Physical pages with more than one holder (slots and/or the
        prefix cache) — the prefix-sharing win, measured."""
        return int((self.ref > 1).sum())

    def alloc_page(self) -> Optional[int]:
        """One free page with ``ref = 1`` (the caller's), or None."""
        if not self._free:
            return None
        pid = self._free.pop()
        self.ref[pid] = 1
        return pid

    def incref(self, pid: int) -> None:
        self.ref[pid] += 1

    def decref(self, pid: int) -> None:
        self.ref[pid] -= 1
        if self.ref[pid] < 0:
            raise RuntimeError(
                f"page {pid} refcount went negative (double free)")
        if self.ref[pid] == 0:
            self._free.append(pid)

    def assign(self, slot: int, logical: int, pid: int) -> None:
        """Point ``tables[slot, logical]`` at ``pid`` (the caller has
        already arranged the refcount)."""
        self.tables[slot, logical] = pid
        self._dirty()

    def slot_pages(self, slot: int) -> List[int]:
        row = self.tables[slot]
        return row[row < self.num_pages].tolist()

    def release_slot(self, slot: int) -> int:
        """Drop the slot's hold on every page it references (pages the
        prefix cache still holds survive with the cache's ref) and
        reset its table row to the sentinel; returns the number of
        pages released. Vectorized (zero-bubble PR): one numpy
        decrement over the row instead of a per-page python loop —
        this runs on the serving loop's finish/preempt path."""
        row = self.tables[slot]
        pages = row[row < self.num_pages]
        if pages.size:
            self.ref[pages] -= 1    # a row never repeats a page
            if (self.ref[pages] < 0).any():
                raise RuntimeError(
                    f"slot {slot} release drove a page refcount "
                    "negative (double free)")
            # freed pages return in row (logical) order — the same
            # deterministic order the per-page decref loop produced
            self._free.extend(pages[self.ref[pages] == 0].tolist())
        self.tables[slot] = self.num_pages
        self._dirty()
        return int(pages.size)

    # -- host offload tier --------------------------------------------------

    @property
    def host_free_pages(self) -> int:
        return len(self._host_free)

    def offload_pages(self, page_ids) -> Optional[List[int]]:
        """Enqueue physical device pages for D2H copy into free host
        pages; returns the host page ids (the caller owns them until
        ``free_host``), or None when the host tier is off or lacks
        capacity — callers fall back to the discard/re-prefill path.

        ASYNC (tree-speculation PR satellite): the call only gathers
        the pages into a device-side snapshot (a jitted copy — later
        cache mutation cannot touch it) and enqueues the D2H
        transfers (``copy_to_host_async``); nothing blocks. The fence
        into the pinned host rows runs LAZILY at the first
        ``restore_pages``/``free_host`` touch of these host pages —
        the preempt-heavy serving path no longer stalls its iteration
        on a D2H round trip that only the (much later, often never)
        resume actually needs. A batch freed before any restore is
        dropped without ever fencing."""
        n = len(page_ids)
        if self.host_cache is None or n == 0 \
                or len(self._host_free) < n:
            return None
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        dev = _gather_rows(self.cache, ids)
        for leaf in jax.tree_util.tree_leaves(dev):
            self.offload_bytes += leaf.nbytes
            try:
                leaf.copy_to_host_async()
            except Exception:  # lint: allow-swallow — a backend
                pass           # without async D2H fetches at the fence
        hids = [self._host_free.pop() for _ in range(n)]
        self._pending_host.append({"hids": list(hids), "dev": dev})
        self.pages_offloaded += n
        return hids

    @property
    def host_swap_pending(self) -> int:
        """Host pages whose D2H payload is enqueued but not yet
        fenced (the async swap-out's backlog; tests pin laziness)."""
        return sum(len(p["hids"]) for p in self._pending_host)

    def _fence_host(self, host_ids) -> None:
        """Materialize every pending D2H batch that covers any of
        ``host_ids`` into the host pool rows (whole batches — the
        gather was batch-granular). The fancy-index store always
        copies, so no view of runtime-owned device memory survives."""
        need = {int(h) for h in host_ids}
        if not need or not self._pending_host:
            return
        keep = []
        for pend in self._pending_host:
            if need.isdisjoint(pend["hids"]):
                keep.append(pend)
                continue
            self.host_fences += 1
            hsel = np.asarray(pend["hids"], np.int64)
            for kv_host, kv_dev in zip(self.host_cache, pend["dev"]):
                if kv_host is None:
                    continue
                for key, host_arr in kv_host.items():
                    host_arr[hsel] = np.asarray(kv_dev[key])
        self._pending_host = keep

    def restore_pages(self, host_ids, dev_ids) -> None:
        """H2D: host page payloads -> the given (already allocated)
        device pages, byte-identical — the swap-in that replaces a
        preemption victim's full context re-prefill. Fences any
        pending async swap-out of these pages first. The host pages
        are NOT freed here (``free_host`` is the owner's call)."""
        if self.host_cache is None:
            raise RuntimeError(
                "no host page pool (construct with host_pages > 0)")
        if len(host_ids) != len(dev_ids):
            raise ValueError(
                f"host/device page counts differ: {len(host_ids)} "
                f"vs {len(dev_ids)}")
        if not len(host_ids):
            return
        self._fence_host(host_ids)
        hsel = np.asarray(host_ids, np.int64)
        vals = [None if kv is None else
                {key: a[hsel] for key, a in kv.items()}
                for kv in self.host_cache]
        self.cache = _scatter_rows(
            self.cache, jnp.asarray(np.asarray(dev_ids, np.int32)),
            vals)
        self.pages_restored += len(host_ids)

    def free_host(self, host_ids) -> None:
        """Return host pages to the free list. A pending async batch
        fully covered by the free is DROPPED without fencing (its
        payload has no reader left); partially freed batches fence
        first so the surviving pages' data lands. Double-free is a
        loud error — two owners sharing one host page would corrupt
        both (the device-side ``decref`` contract, host edition)."""
        need = {int(h) for h in host_ids}
        if need and self._pending_host:
            keep = []
            for pend in self._pending_host:
                hs = set(pend["hids"])
                if hs and hs <= need:
                    continue             # fully freed: never fence
                keep.append(pend)
            self._pending_host = keep
            self._fence_host(need)
        for h in host_ids:
            h = int(h)
            if h in self._host_free:
                raise RuntimeError(f"host page {h} double-freed")
            self._host_free.append(h)

    # -- staging transfers --------------------------------------------------

    def insert_pages(self, staging, slot: int, skip_pages: int,
                     n_pos: int) -> None:
        """Scatter the staging cache's logical pages
        ``[skip_pages, pages_for(n_pos))`` into the slot's physical
        pages — ONLY the pages the prompt actually fills and that are
        not already shared (the prefix-cache pages at the front hold
        identical data and are skipped wholesale)."""
        n_needed = self.pages_for(n_pos)
        tv = np.full(self.pages_per_slot, self.num_pages, np.int32)
        tv[skip_pages:n_needed] = self.tables[slot, skip_pages:n_needed]
        self.cache = _write_pages(self.cache, staging, jnp.asarray(tv))

    def load_prefix(self, staging, page_ids: List[int], n_tokens: int):
        """Materialize a shared prefix into the staging cache: pages
        ``page_ids`` (full shared pages, plus the copy-on-write donor
        as the last entry for a partial match) become staging positions
        ``[0, n_tokens)`` (plus donor tail garbage the prefill chunks
        overwrite). Returns the new staging pytree."""
        n_load = self.pages_for(n_tokens)
        if len(page_ids) < n_load:
            raise ValueError(
                f"{len(page_ids)} pages cannot cover {n_tokens} shared "
                f"tokens ({n_load} pages)")
        tv = np.full(self.pages_per_slot, self.num_pages, np.int32)
        tv[:n_load] = page_ids[:n_load]
        valid = self.page_index < n_load
        return _load_pages(staging, self.cache, jnp.asarray(tv),
                           jnp.asarray(valid))


# --- prefix cache -----------------------------------------------------------


class _Node:
    __slots__ = ("nid", "page", "parent", "key", "last_used", "host")

    def __init__(self, nid, page, parent, key, last_used):
        self.nid = nid
        self.page = page                 # device page id, or None when
        self.host = None                 # spilled (``host`` holds the
        self.parent = parent             # host page id instead)
        self.key = key
        self.last_used = last_used


class PrefixCache:
    """Hash-consed shared prompt prefixes over a ``PagedKVPool``.

    A trie keyed by page-sized token runs: node ``(parent, tokens)``
    owns the physical page holding those positions' KV. Finished
    prefills ``register()`` their full (immutable — decode never
    writes them) prompt pages; ``match()`` walks the longest chain a
    new prompt shares and additionally finds the best PARTIAL match
    among the last node's children (the copy-on-write donor). Matches
    are capped at ``len(tokens) - 1``: the final prompt position is
    always recomputed because its logits seed the first sampled token.

    KV sharing is exact up to chunked-prefill fp reassociation: a
    page's values were computed by SOME request's prefill over the
    same token prefix; a different total prompt length can place the
    ragged final chunk differently, which reassociates the softmax
    sums. Greedy token identity is unaffected at any realistic argmax
    margin (the oracle tests pin this); bitwise-KV-sensitive callers
    can disable sharing per engine.

    Eviction is LRU over LEAF nodes whose page only the cache holds
    (``ref == 1``) — evicting a leaf exposes its parent for the next
    round, so sustained pressure unwinds whole chains.

    With a pool host tier (``PagedKVPool(host_pages=N)``) eviction
    SPILLS before it drops: the LRU victim's page copies D2H and the
    node stays in the trie host-resident (``match()`` restores it to
    a fresh device page on the next hit — H2D copy, no recompute), so
    the effective cache capacity is device + host pages. Only when
    the host tier is full (or absent) does a victim drop outright;
    sustained pressure then unwinds the OLDEST host-resident leaves
    first, exposing their parents for spilling in turn."""

    def __init__(self, pool: PagedKVPool):
        self._pool = pool
        self._nodes: Dict[int, _Node] = {}
        #: parent nid -> {page-token bytes -> node}; 0 is the root
        self._children: Dict[int, Dict[bytes, _Node]] = {0: {}}
        #: parent nid -> {first token -> [nodes]}: the partial-match
        #: candidate index (a donor match needs >= 1 leading token, so
        #: only children sharing the probe's first token can qualify —
        #: without this, every lookup scanned ALL children of the
        #: chain end, O(distinct prompts) per admission)
        self._first: Dict[int, Dict[int, List[_Node]]] = {}
        #: routing signal (serving router): first-page key -> how many
        #: times ``match()`` served a chain rooted at that page. The
        #: dict is bounded by the root's live children (entries die
        #: with their node in ``evict_one``)
        self._hits: Dict[bytes, int] = {}
        #: device page id -> owning node: the O(1) residency probe the
        #: engine's prefix-aware swap snapshot consults (tree-spec PR
        #: satellite) — a resident page need not be copied to host, it
        #: just needs a refcount hold until resume re-links it
        self._by_page: Dict[int, _Node] = {}
        self._nid = itertools.count(1)
        self._tick = itertools.count()

    def __len__(self) -> int:
        return len(self._nodes)

    def resident(self, pid: int) -> bool:
        """Is device page ``pid`` held by a cache node right now?"""
        return int(pid) in self._by_page

    # -- router affinity signal ---------------------------------------------

    def affinity_key(self, tokens) -> bytes:
        """Cheap placement key for prefix-affinity routing: the byte
        string of the prompt's FIRST page-sized token run — the trie's
        root edge, so two prompts share cached pages only if their
        affinity keys agree. A prompt shorter than one full page can
        never share a full page; its (short) raw bytes come back and
        ``probe()`` simply misses."""
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
        return toks[:self._pool.page_len].tobytes()

    def probe(self, key: bytes) -> Optional[int]:
        """Side-effect-free affinity probe (no LRU touch, no counter
        bump — a router may call this per replica per submit): ``None``
        when no registered chain starts with this page run, else the
        number of times ``match()`` has served a chain rooted at it
        (0 = resident but not yet re-used). The serving router ranks
        replicas by this signal (``serving.router.PrefixAffinity``)."""
        if key not in self._children.get(0, {}):
            return None
        return self._hits.get(key, 0)

    def match(self, tokens) -> Tuple[List[int], int, Optional[int]]:
        """Longest shared prefix of ``tokens``: returns ``(full_pages,
        shared_len, donor_page)`` where ``full_pages`` are the chained
        full-page hits (``len * page_len`` tokens), ``shared_len`` adds
        the best partial-page match and ``donor_page`` is the page to
        copy-on-write for it (None for a page-aligned match)."""
        pool = self._pool
        pl = pool.page_len
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
        n = len(toks)
        tick = next(self._tick)
        pages: List[int] = []
        parent = 0
        pos = 0
        # full pages, capped so shared_len stays <= n - 1
        while pos + pl < n:
            key = toks[pos:pos + pl].tobytes()
            node = self._children.get(parent, {}).get(key)
            if node is None:
                break
            if node.page is None and not self._restore_node(node):
                break                    # host-resident, no device page
            node.last_used = tick
            if parent == 0:
                # affinity hit counter: this chain's root page served
                # a match (the router's "hot prefix" signal)
                self._hits[key] = self._hits.get(key, 0) + 1
            pages.append(node.page)
            parent = node.nid
            pos += pl
        # best partial continuation among the chain's children (the
        # copy-on-write donor); also catches the "whole prompt cached"
        # case — the last page re-enters here with pl - 1 tokens
        donor = None
        best = 0
        limit = min(pl, n - 1 - pos)
        if limit > 0:
            cands = self._first.get(parent, {}).get(int(toks[pos]), [])
            for node in cands:
                cand = np.frombuffer(node.key, np.int32)[:limit]
                m = int((np.cumprod(cand == toks[pos:pos + limit]))
                        .sum())
                if m > best:
                    best, donor = m, node
        if donor is not None and donor.page is None \
                and not self._restore_node(donor):
            donor = None                 # spilled donor, pool full
        if donor is not None:
            donor.last_used = tick
            return pages, pos + best, donor.page
        return pages, pos, None

    def _restore_node(self, node: _Node) -> bool:
        """Bring a host-resident (spilled) node back onto a fresh
        device page — H2D copy, byte-identical, no prefill recompute.
        False when no device page can be allocated (the chain walk
        stops there; the node stays spilled for a later try)."""
        pool = self._pool
        pid = pool.alloc_page()          # ref = 1: the cache's hold
        if pid is None:
            return False
        pool.restore_pages([node.host], [pid])
        pool.free_host([node.host])
        node.host = None
        node.page = pid
        self._by_page[pid] = node
        return True

    def register(self, tokens, table_row) -> int:
        """Install every FULL prompt page of ``tokens`` (physical ids
        from ``table_row``) into the trie; pages already registered
        along the chain are left as-is (a privately recomputed
        duplicate stays private and dies with its request). Each new
        node increfs its page — the cache is a holder. Returns the
        number of pages newly registered."""
        pool = self._pool
        pl = pool.page_len
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
        tick = next(self._tick)
        parent = 0
        added = 0
        for j in range(len(toks) // pl):
            key = toks[j * pl:(j + 1) * pl].tobytes()
            ch = self._children.setdefault(parent, {})
            node = ch.get(key)
            if node is not None and node.page is None:
                # the chain spilled (or its restore failed) between
                # this request's match and its register — the request
                # recomputed the page privately, so ADOPT that live
                # device page and retire the host copy: sharing
                # revives at zero copy cost (same fp-reassociation
                # contract as any registered page)
                pid = int(table_row[j])
                if pid < pool.num_pages:
                    node.page = pid
                    pool.incref(pid)
                    self._by_page[pid] = node
                    pool.free_host([node.host])
                    node.host = None
            if node is None:
                pid = int(table_row[j])
                if pid >= pool.num_pages:
                    break                # unallocated: nothing to share
                node = _Node(next(self._nid), pid, parent, key, tick)
                ch[key] = node
                self._children[node.nid] = {}
                self._nodes[node.nid] = node
                self._first.setdefault(parent, {}).setdefault(
                    int(toks[j * pl]), []).append(node)
                pool.incref(pid)
                self._by_page[pid] = node
                added += 1
            node.last_used = tick
            parent = node.nid
        return added

    def _drop(self, node: _Node) -> None:
        """Remove a node from the trie, releasing whichever page
        (device or host) it holds."""
        del self._children[node.parent][node.key]
        del self._children[node.nid]
        del self._nodes[node.nid]
        if node.parent == 0:
            self._hits.pop(node.key, None)
        tok0 = int(np.frombuffer(node.key, np.int32)[0])
        bucket = self._first.get(node.parent, {}).get(tok0, [])
        if node in bucket:
            bucket.remove(node)
        if node.page is not None:
            self._by_page.pop(node.page, None)
            self._pool.decref(node.page)
        else:
            self._pool.free_host([node.host])

    def evict_one(self) -> bool:
        """Free ONE device page held only by the cache. With a pool
        host tier, the LRU cache-only node SPILLS (page copied D2H,
        node stays matchable — ``match()`` restores it in place, so
        spilling ANY node, leaf or interior, leaves the trie intact);
        without host space the LRU cache-only LEAF drops outright
        (dropping must stay leaf-first or the chain below would
        orphan), and when every droppable leaf is already
        host-resident, the OLDEST spilled leaves drop first to free
        host space and expose their parents. False when no device
        page can be freed (every cached page is also live in some
        slot)."""
        pool = self._pool
        while True:
            spill = drop = host_leaf = None
            for node in self._nodes.values():
                leaf = not self._children.get(node.nid)
                if node.page is None:
                    if leaf and (host_leaf is None or
                                 node.last_used < host_leaf.last_used):
                        host_leaf = node
                    continue
                if pool.ref[node.page] != 1:
                    continue                      # a slot still reads it
                if spill is None or node.last_used < spill.last_used:
                    spill = node
                if leaf and (drop is None
                             or node.last_used < drop.last_used):
                    drop = node
            if spill is not None and pool.host_free_pages > 0:
                hids = pool.offload_pages([spill.page])
                if hids is not None:
                    self._by_page.pop(spill.page, None)
                    pool.decref(spill.page)
                    spill.page = None
                    spill.host = hids[0]
                    return True
            if drop is not None:
                self._drop(drop)
                return True
            if spill is None or host_leaf is None:
                # no device page to free at all (spill is None: the
                # host-resident remainder must NOT be drained for
                # nothing), or nothing left to unwind
                return False
            # unwind: a device page exists but the host tier is full
            # and it is not a droppable leaf — dropping the oldest
            # spilled leaf frees host space (the next round can spill
            # again) and may expose a device-resident parent
            self._drop(host_leaf)

    def evictable_pages(self) -> int:
        """DEVICE pages the cache could EVENTUALLY free under
        pressure. Without a host tier: nodes whose page only the
        cache holds and whose whole subtree is in the same position
        (dropping is leaf-first, so children must be freeable before
        their parent; a host-resident node blocks nothing and
        contributes nothing). A cache-only node NOT drop-reachable
        that way can still SPILL — but each such spill permanently
        consumes a host page (a spilled interior node is not
        unwindable while slot-pinned children keep it off the leaf
        frontier), so the spill-only contribution is capped at the
        host pool's free capacity. Callers check this BEFORE
        reclaiming toward a target — a reclaim that cannot reach its
        goal would drain the whole reusable cache for nothing."""
        memo: Dict[int, bool] = {}

        def ok(nid: int) -> bool:
            got = memo.get(nid)
            if got is not None:
                return got
            node = self._nodes[nid]
            memo[nid] = res = (
                (node.page is None
                 or self._pool.ref[node.page] == 1)
                and all(ok(c.nid)
                        for c in self._children.get(nid, {}).values()))
            return res

        droppable = spill_only = 0
        for node in self._nodes.values():
            if node.page is None or self._pool.ref[node.page] != 1:
                continue
            if ok(node.nid):
                droppable += 1
            else:
                spill_only += 1
        return droppable + min(spill_only, self._pool.host_free_pages)

    def reclaim(self, n_pages: int) -> int:
        """Evict until ``n_pages`` pages were freed (or nothing more is
        evictable); returns the number freed."""
        freed = 0
        while freed < n_pages and self.evict_one():
            freed += 1
        return freed
