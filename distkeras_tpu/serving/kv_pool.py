"""Pooled KV cache: one resident ``[S, max_len]`` buffer set shared by
every request the engine ever serves.

``generate()`` creates its cache inside each compiled program and drops
it on exit — correct for one call, hopeless for serving, where cache
allocation per request would dominate short decodes and fragment HBM.
The pool is allocated ONCE (slot-major: the same head-major
``[S, Hkv, max_len, Dh]`` per-layer layout ``init_cache`` builds, with
the batch axis reinterpreted as slots) and stays on device; a finished
request's slot is simply reused — stale positions are never read
because the per-slot decode masks attention at ``<= t`` and the next
occupant's prefill overwrites the whole row.

Composes with the int8 quantized cache (``dtype="int8"``): the payload
and per-token-per-head scale planes all carry the slot axis and insert
together.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.decoding import init_cache


@jax.jit
def _insert_row(pool, req_cache, slot):
    """Write a batch-1 request cache into pool row ``slot`` (``slot``
    is traced — one compiled program serves every slot index)."""
    def write(pl, rq):
        return lax.dynamic_update_slice_in_dim(
            pl, rq.astype(pl.dtype), slot, axis=0)
    return jax.tree_util.tree_map(write, pool, req_cache)


class KVPool:
    """S-slot pooled KV cache over ``module``'s attention layers.

    ``cache`` is the live device pytree (the exact structure
    ``decode_step_slots`` consumes); ``insert`` replaces it — callers
    must not hold on to the old value."""

    def __init__(self, module, num_slots: int, max_len: int,
                 dtype=jnp.float32):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        self._module = module
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.dtype = dtype
        # init_cache validates max_len against the position table up
        # front (out-of-range gathers CLAMP under jit — silent wrong-
        # position logits otherwise)
        self.cache = init_cache(module, self.num_slots, self.max_len,
                                dtype)

    def make_request_cache(self):
        """A batch-1 cache with the pool's exact per-position layout —
        what per-request prefill fills and ``insert`` consumes."""
        return init_cache(self._module, 1, self.max_len, self.dtype)

    def insert(self, req_cache, slot: int) -> None:
        """Copy a batch-1 request cache (layout of
        ``make_request_cache``) into row ``slot``. The whole row is
        written — any stale tail beyond the new request's prompt is
        overwritten by its own decode steps before the attention mask
        ever reaches it."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(
                f"slot {slot} out of range [0, {self.num_slots})")
        self.cache = _insert_row(self.cache, req_cache, slot)
