"""Request scheduling for the continuous-batching engine: admission,
per-request state machine, slot allocation/release, preemption.

The scheduler is pure host-side bookkeeping — it never touches device
arrays. Two policies (docs/serving.md; degradation semantics in
docs/resilience.md):

``FIFOScheduler`` (the slab-pool engine's policy, deliberately simple):

  * FCFS admission: queued requests take free slots in arrival order.
  * BOUNDED queue: with ``max_queue`` set, a submit past the bound
    raises ``AdmissionRejected`` (explicit load shedding — the queue
    never grows without bound under overload).
  * ONE prefill stream: the oldest admitted-but-not-yet-decoding
    request advances one prompt chunk per engine iteration, interleaved
    between decode steps (long prompts therefore do not stall in-flight
    decode streams; they just take several iterations to come online).
  * Slots release on finish (stop token or length limit) and are
    immediately reusable by the next queued request. A request can also
    leave via ``cancel()`` — deadline timeout (``TIMED_OUT``) or
    poisoned-request isolation (``CANCELLED``) — from ANY live state.
  * Double-release is a loud error, never a silent double-free: two
    requests sharing one KV slot would corrupt both streams.

``PriorityScheduler`` (the paged-pool engine's cost-aware policy):

  * Priority classes: lower ``Request.priority`` admits first
    (0 = interactive, 1 = standard, 2 = batch by convention; any int
    works). Within a class, FCFS — except preempted requests, which
    resume AT THE FRONT of their class (they hold progress).
  * Admission is budgeted: the engine admits head-of-line requests
    while ``peek()`` fits the free-PAGE budget (plus a free slot),
    not merely while slots exist — the slab policy's failure mode was
    admitting by worst-case slot count while HBM sat idle.
  * PREEMPTION: ``preempt()`` ejects a DECODING request back to the
    queue (state → QUEUED, slot freed, generated tokens kept). The
    engine preempts when a decode step needs a page and none is free,
    or when a strictly-higher-priority request cannot admit; the
    victim re-prefills its prompt + generated context on re-admission
    (the resumable ``prefill_chunk_step``) and continues
    token-identically.
"""

from __future__ import annotations

import enum
import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


class AdmissionRejected(RuntimeError):
    """Submit refused: the bounded admission queue is full (load
    shedding). Callers retry later or route elsewhere — the engine
    sheds explicitly instead of queueing unboundedly."""

    def __init__(self, queue_depth: int, max_queue: int):
        super().__init__(
            f"admission queue full ({queue_depth}/{max_queue} waiting); "
            "request shed")
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class RequestState(enum.Enum):
    QUEUED = "queued"            # submitted, waiting for a slot
    PREFILLING = "prefilling"    # slot assigned, prompt chunks running
    DECODING = "decoding"        # in the slot-batched decode loop
    FINISHED = "finished"        # stop token or length limit reached
    TIMED_OUT = "timed_out"      # per-request deadline_s expired
    CANCELLED = "cancelled"      # isolated after a step error / by API


#: states a request never leaves
TERMINAL_STATES = frozenset(
    {RequestState.FINISHED, RequestState.TIMED_OUT,
     RequestState.CANCELLED})


@dataclass
class Request:
    """One serving request and its mutable progress state. Sampling
    knobs use the engine's per-slot sentinels (``temperature 0`` =
    greedy, ``top_k 0`` = no truncation, ``top_p 1.0`` = no nucleus
    cut, ``stop_token -1`` = never stop) so they can be placed directly
    into the per-slot sampling vectors."""

    rid: int
    prompt: np.ndarray                   # [P] int32
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_token: int = -1
    seed: int = 0
    priority: int = 1                    # lower admits first (0 = most
    #                                      urgent; 1 = standard default)
    state: RequestState = RequestState.QUEUED
    slot: Optional[int] = None
    prefill_pos: int = 0                 # prompt positions ingested
    generated: List[int] = field(default_factory=list)
    rng: object = None                   # per-request PRNG key (engine)
    deadline_s: Optional[float] = None   # submit->finish budget (engine
    #                                      clock); None = no deadline
    submit_t: float = 0.0                # engine-clock submit timestamp
    error: Optional[BaseException] = None  # why CANCELLED (isolation)
    n_preempted: int = 0                 # times evicted back to queue
    # fleet bookkeeping (router PRs): how many times this stream moved
    # between replicas — stamped by the router when it delivers the
    # terminal request, so replay outcomes can count lost vs replayed
    # vs degraded work per incident
    n_handoffs: int = 0                  # planned moves (disagg/rebalance)
    n_failovers: int = 0                 # replica-death re-admissions
    # speculative decoding (spec-decode PR): whether this request
    # participates in draft-and-verify iterations, the acceptance EMA
    # that decides it keeps paying off, and the sticky kill switch the
    # engine throws for adversarial (never-accepting) streams
    speculate: bool = False
    spec_disabled: bool = False
    spec_ema: Optional[float] = None     # EMA of per-verify accept rate
    spec_checks: int = 0                 # verify steps observed
    spec_disabled_at: Optional[int] = None  # generated-count at demotion
    #                                      (re-probe cooldown anchor)
    # tree speculation (tree-speculation PR): the adaptive controller's
    # per-stream tree shape (None until the engine seeds them from its
    # spec_k/spec_width caps at first use; survives preempt/resume)
    tree_depth: Optional[int] = None
    tree_width: Optional[int] = None

    @property
    def stopped(self) -> bool:
        return (self.stop_token >= 0 and bool(self.generated)
                and self.generated[-1] == self.stop_token)

    @property
    def done(self) -> bool:
        return self.stopped or len(self.generated) >= self.max_new_tokens

    @property
    def context_tokens(self) -> np.ndarray:
        """Every token whose KV must be IN CACHE before this request
        can (re)join decode: the prompt, plus — after a preemption —
        all generated tokens but the last (the last one is the pending
        decode input; its KV is written by the resumed step itself).
        For a fresh request this is just the prompt."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt,
             np.asarray(self.generated[:-1], self.prompt.dtype)])

    @property
    def tokens(self) -> np.ndarray:
        """Prompt + generated continuation (ends AT the stop token when
        one fired — no padding, unlike ``generate()``'s fixed-shape
        output)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, self.prompt.dtype)])


class FIFOScheduler:
    """FIFO queue + slot allocator + state machine transitions."""

    def __init__(self, num_slots: int, max_queue: Optional[int] = None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_queue is not None and int(max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.num_slots = int(num_slots)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.waiting: deque = deque()          # QUEUED, FIFO
        self.prefilling: deque = deque()       # PREFILLING, FIFO
        self.running: Dict[int, Request] = {}  # slot -> DECODING request
        # request-level tracing hook (obs.tracing): the engine binds
        # its tracer here so admission decisions are recorded WHERE
        # they are made; None (standalone scheduler use) records
        # nothing
        self.tracer = None
        # pop() hands out slot 0 first — deterministic placement makes
        # oracle tests and trace reading reproducible
        self._free = list(range(self.num_slots))[::-1]

    # --- queue ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        if self.max_queue is not None \
                and len(self.waiting) >= self.max_queue:
            raise AdmissionRejected(len(self.waiting), self.max_queue)
        req.state = RequestState.QUEUED
        self.waiting.append(req)

    def admit(self) -> List[Request]:
        """Move queued requests into free slots (FCFS) and mark them
        PREFILLING; returns the newly admitted requests."""
        admitted = []
        while self.waiting and self._free:
            req = self.waiting.popleft()
            req.slot = self._free.pop()
            req.state = RequestState.PREFILLING
            req.prefill_pos = 0
            self.prefilling.append(req)
            admitted.append(req)
            if self.tracer is not None:
                # queue depth AT admission: requests still waiting
                # after this one took its slot
                self.tracer.on_admit(req.rid, req.slot,
                                     len(self.waiting))
        return admitted

    def next_prefill(self) -> Optional[Request]:
        """The single request whose prompt chunks currently advance (the
        oldest admitted one; FCFS)."""
        return self.prefilling[0] if self.prefilling else None

    # --- transitions ------------------------------------------------------

    def to_decoding(self, req: Request) -> None:
        assert req is self.prefilling[0], "prefill completes FCFS"
        self.prefilling.popleft()
        req.state = RequestState.DECODING
        self.running[req.slot] = req

    def _evict(self, req: Request) -> None:
        """Remove an in-flight request from its live structure and free
        its slot. Raises on a request that holds no slot — a terminal
        (double-release) or still-QUEUED request — because silently
        appending its slot to the free list would hand the same KV slot
        to two requests."""
        if req.state is RequestState.DECODING:
            del self.running[req.slot]
        elif req.state is RequestState.PREFILLING:
            self.prefilling.remove(req)
        else:
            raise RuntimeError(
                f"cannot release request {req.rid} in state "
                f"{req.state.value!r}: it holds no slot "
                "(double release, or the request was never admitted)")
        self._free.append(req.slot)

    def release(self, req: Request) -> None:
        """Finish a request from either in-flight state and free its
        slot. Releasing twice (or releasing a QUEUED request) raises —
        it would put one slot on the free list twice."""
        self._evict(req)
        req.state = RequestState.FINISHED

    def cancel(self, req: Request,
               state: RequestState = RequestState.CANCELLED) -> None:
        """Terminate a request from ANY live state (degradation paths:
        deadline ``TIMED_OUT``, poisoned-request ``CANCELLED``). A
        queued request just leaves the queue; an admitted one also
        frees its slot. Terminal requests raise (same double-free
        guard as ``release``)."""
        if state not in (RequestState.CANCELLED, RequestState.TIMED_OUT):
            raise ValueError(
                f"cancel() target state must be CANCELLED or TIMED_OUT, "
                f"got {state}")
        if req.state is RequestState.QUEUED:
            self.waiting.remove(req)
        else:
            self._evict(req)
        req.state = state

    # --- introspection ----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def occupied(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def pending(self) -> bool:
        """Any request not yet FINISHED."""
        return bool(self.waiting or self.prefilling or self.running)

    @property
    def free_slots(self) -> int:
        return len(self._free)


class PriorityScheduler(FIFOScheduler):
    """Cost-aware scheduling over the same state machine: priority
    classes, budgeted admission (the engine gates ``admit_one`` on its
    page budget), and preemption of decoding requests back to the
    queue. ``waiting`` stays the single deque the base class (and its
    bounded-admission / cancel paths) already manage; ordering is by
    ``(priority, order)`` key at ``peek()`` time — queues are short
    (bounded under overload), so the O(n) min costs nothing next to a
    device step."""

    def __init__(self, num_slots: int, max_queue: Optional[int] = None):
        super().__init__(num_slots, max_queue=max_queue)
        self._order = itertools.count()   # arrival order within class
        self._front = itertools.count()   # requeue order (preempted)

    def submit(self, req: Request) -> None:
        # rank 1: fresh arrivals sort after every preempted (rank 0)
        # request of the same class, FCFS within the rank
        req._order = (1, next(self._order))
        super().submit(req)

    def _key(self, req: Request):
        return (req.priority, getattr(req, "_order", (1, 0)))

    def peek(self) -> Optional[Request]:
        """The request admission would take next (highest class, FCFS
        within it, preempted requests first), without taking it."""
        if not self.waiting:
            return None
        return min(self.waiting, key=self._key)

    def admit_one(self, req: Request) -> None:
        """Admit ONE queued request (the engine calls this only after
        reserving its pages) into a free slot."""
        if not self._free:
            raise RuntimeError("admit_one with no free slot")
        self.waiting.remove(req)
        req.slot = self._free.pop()
        req.state = RequestState.PREFILLING
        req.prefill_pos = 0
        self.prefilling.append(req)
        if self.tracer is not None:
            self.tracer.on_admit(req.rid, req.slot, len(self.waiting))

    def admit(self) -> List[Request]:
        """Unbudgeted admission (standalone/scheduler-only use): fill
        free slots in priority order."""
        admitted = []
        while self.waiting and self._free:
            req = self.peek()
            self.admit_one(req)
            admitted.append(req)
        return admitted

    def preempt(self, req: Request) -> None:
        """Evict an admitted request back to the queue: slot freed,
        state → QUEUED, generated tokens kept (its re-prefill context),
        resumed ahead of its class peers. DECODING victims resume
        token-identically (the engine snapshots their sampling key);
        a PREFILLING victim simply discards its staged chunks and
        re-prefills from scratch — its pages are page-budget holders
        too, and leaving them unpreemptable would let one mid-prefill
        request starve a decoding stream into a dead pool."""
        if req.state is RequestState.DECODING:
            del self.running[req.slot]
        elif req.state is RequestState.PREFILLING:
            self.prefilling.remove(req)
        else:
            raise RuntimeError(
                f"cannot preempt request {req.rid} in state "
                f"{req.state.value!r}: it holds no page-backed slot")
        self._free.append(req.slot)
        req.slot = None
        req.state = RequestState.QUEUED
        req.prefill_pos = 0
        req.n_preempted += 1
        req._order = (0, next(self._front))
        self.waiting.append(req)
