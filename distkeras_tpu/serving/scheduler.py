"""Request scheduling for the continuous-batching engine: FIFO
admission, per-request state machine, slot allocation/release.

The scheduler is pure host-side bookkeeping — it never touches device
arrays. Policy (deliberately simple, documented in docs/serving.md;
degradation semantics in docs/resilience.md):

  * FCFS admission: queued requests take free slots in arrival order.
  * BOUNDED queue: with ``max_queue`` set, a submit past the bound
    raises ``AdmissionRejected`` (explicit load shedding — the queue
    never grows without bound under overload).
  * ONE prefill stream: the oldest admitted-but-not-yet-decoding
    request advances one prompt chunk per engine iteration, interleaved
    between decode steps (long prompts therefore do not stall in-flight
    decode streams; they just take several iterations to come online).
  * Slots release on finish (stop token or length limit) and are
    immediately reusable by the next queued request. A request can also
    leave via ``cancel()`` — deadline timeout (``TIMED_OUT``) or
    poisoned-request isolation (``CANCELLED``) — from ANY live state.
  * Double-release is a loud error, never a silent double-free: two
    requests sharing one KV slot would corrupt both streams.
"""

from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


class AdmissionRejected(RuntimeError):
    """Submit refused: the bounded admission queue is full (load
    shedding). Callers retry later or route elsewhere — the engine
    sheds explicitly instead of queueing unboundedly."""

    def __init__(self, queue_depth: int, max_queue: int):
        super().__init__(
            f"admission queue full ({queue_depth}/{max_queue} waiting); "
            "request shed")
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class RequestState(enum.Enum):
    QUEUED = "queued"            # submitted, waiting for a slot
    PREFILLING = "prefilling"    # slot assigned, prompt chunks running
    DECODING = "decoding"        # in the slot-batched decode loop
    FINISHED = "finished"        # stop token or length limit reached
    TIMED_OUT = "timed_out"      # per-request deadline_s expired
    CANCELLED = "cancelled"      # isolated after a step error / by API


#: states a request never leaves
TERMINAL_STATES = frozenset(
    {RequestState.FINISHED, RequestState.TIMED_OUT,
     RequestState.CANCELLED})


@dataclass
class Request:
    """One serving request and its mutable progress state. Sampling
    knobs use the engine's per-slot sentinels (``temperature 0`` =
    greedy, ``top_k 0`` = no truncation, ``top_p 1.0`` = no nucleus
    cut, ``stop_token -1`` = never stop) so they can be placed directly
    into the per-slot sampling vectors."""

    rid: int
    prompt: np.ndarray                   # [P] int32
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_token: int = -1
    seed: int = 0
    state: RequestState = RequestState.QUEUED
    slot: Optional[int] = None
    prefill_pos: int = 0                 # prompt positions ingested
    generated: List[int] = field(default_factory=list)
    rng: object = None                   # per-request PRNG key (engine)
    deadline_s: Optional[float] = None   # submit->finish budget (engine
    #                                      clock); None = no deadline
    submit_t: float = 0.0                # engine-clock submit timestamp
    error: Optional[BaseException] = None  # why CANCELLED (isolation)

    @property
    def stopped(self) -> bool:
        return (self.stop_token >= 0 and bool(self.generated)
                and self.generated[-1] == self.stop_token)

    @property
    def done(self) -> bool:
        return self.stopped or len(self.generated) >= self.max_new_tokens

    @property
    def tokens(self) -> np.ndarray:
        """Prompt + generated continuation (ends AT the stop token when
        one fired — no padding, unlike ``generate()``'s fixed-shape
        output)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, self.prompt.dtype)])


class FIFOScheduler:
    """FIFO queue + slot allocator + state machine transitions."""

    def __init__(self, num_slots: int, max_queue: Optional[int] = None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_queue is not None and int(max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.num_slots = int(num_slots)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.waiting: deque = deque()          # QUEUED, FIFO
        self.prefilling: deque = deque()       # PREFILLING, FIFO
        self.running: Dict[int, Request] = {}  # slot -> DECODING request
        # request-level tracing hook (obs.tracing): the engine binds
        # its tracer here so admission decisions are recorded WHERE
        # they are made; None (standalone scheduler use) records
        # nothing
        self.tracer = None
        # pop() hands out slot 0 first — deterministic placement makes
        # oracle tests and trace reading reproducible
        self._free = list(range(self.num_slots))[::-1]

    # --- queue ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        if self.max_queue is not None \
                and len(self.waiting) >= self.max_queue:
            raise AdmissionRejected(len(self.waiting), self.max_queue)
        req.state = RequestState.QUEUED
        self.waiting.append(req)

    def admit(self) -> List[Request]:
        """Move queued requests into free slots (FCFS) and mark them
        PREFILLING; returns the newly admitted requests."""
        admitted = []
        while self.waiting and self._free:
            req = self.waiting.popleft()
            req.slot = self._free.pop()
            req.state = RequestState.PREFILLING
            req.prefill_pos = 0
            self.prefilling.append(req)
            admitted.append(req)
            if self.tracer is not None:
                # queue depth AT admission: requests still waiting
                # after this one took its slot
                self.tracer.on_admit(req.rid, req.slot,
                                     len(self.waiting))
        return admitted

    def next_prefill(self) -> Optional[Request]:
        """The single request whose prompt chunks currently advance (the
        oldest admitted one; FCFS)."""
        return self.prefilling[0] if self.prefilling else None

    # --- transitions ------------------------------------------------------

    def to_decoding(self, req: Request) -> None:
        assert req is self.prefilling[0], "prefill completes FCFS"
        self.prefilling.popleft()
        req.state = RequestState.DECODING
        self.running[req.slot] = req

    def _evict(self, req: Request) -> None:
        """Remove an in-flight request from its live structure and free
        its slot. Raises on a request that holds no slot — a terminal
        (double-release) or still-QUEUED request — because silently
        appending its slot to the free list would hand the same KV slot
        to two requests."""
        if req.state is RequestState.DECODING:
            del self.running[req.slot]
        elif req.state is RequestState.PREFILLING:
            self.prefilling.remove(req)
        else:
            raise RuntimeError(
                f"cannot release request {req.rid} in state "
                f"{req.state.value!r}: it holds no slot "
                "(double release, or the request was never admitted)")
        self._free.append(req.slot)

    def release(self, req: Request) -> None:
        """Finish a request from either in-flight state and free its
        slot. Releasing twice (or releasing a QUEUED request) raises —
        it would put one slot on the free list twice."""
        self._evict(req)
        req.state = RequestState.FINISHED

    def cancel(self, req: Request,
               state: RequestState = RequestState.CANCELLED) -> None:
        """Terminate a request from ANY live state (degradation paths:
        deadline ``TIMED_OUT``, poisoned-request ``CANCELLED``). A
        queued request just leaves the queue; an admitted one also
        frees its slot. Terminal requests raise (same double-free
        guard as ``release``)."""
        if state not in (RequestState.CANCELLED, RequestState.TIMED_OUT):
            raise ValueError(
                f"cancel() target state must be CANCELLED or TIMED_OUT, "
                f"got {state}")
        if req.state is RequestState.QUEUED:
            self.waiting.remove(req)
        else:
            self._evict(req)
        req.state = state

    # --- introspection ----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def occupied(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def pending(self) -> bool:
        """Any request not yet FINISHED."""
        return bool(self.waiting or self.prefilling or self.running)
