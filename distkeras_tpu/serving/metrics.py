"""Serving metrics: the numbers that describe a serving workload, none
of which a single ``generate()`` call can even express.

Per request: TTFT (submit -> first token — prefill queueing + prompt
ingestion) and end-to-end latency. Per engine iteration: queue depth,
slot occupancy, decoding-slot count and decode wall time (the
steady-state tokens/s series ``bench.py --model serving`` reduces).
Phase wall-clock (prefill vs decode) rides on
``utils.profiling.StepTimer``; percentile summaries use
``utils.profiling.percentiles`` — one latency-summary convention across
the repo.

Per-request state is STREAMING: submit timestamps live only while a
request is in flight (popped into the ttft/latency sample lists as it
progresses), so a long-lived engine holds O(in-flight) dict state, not
O(requests ever served). The sample lists themselves grow one float per
request / iteration — a server that runs forever should treat a
ServingMetrics as a measurement window and swap in a fresh one per
reporting interval (``engine.metrics = ServingMetrics()``, the
``bench.py`` per-pass pattern).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from distkeras_tpu.utils.profiling import StepTimer, percentiles


class ServingMetrics:
    """Host-side counters; negligible overhead (dict writes and two
    ``perf_counter`` calls per phase). ``clock`` is injectable so tests
    can drive deterministic time."""

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self.timer = StepTimer()                 # "prefill" / "decode"
        self.submit_ts: Dict[int, float] = {}    # in-flight only
        self._ttfts: List[float] = []
        self._latencies: List[float] = []
        self.requests_finished = 0
        self.tokens_generated = 0
        self._t_first_submit: Optional[float] = None
        self._t_last_finish: Optional[float] = None
        self.queue_depth: List[int] = []         # per engine iteration
        self.occupancy: List[float] = []         # occupied slots / S
        self.decode_samples: List = []           # (decoding slots, dt)
        self.prefill_chunks = 0

    # --- per-request ------------------------------------------------------

    def record_submit(self, rid: int) -> None:
        now = self.clock()
        self.submit_ts[rid] = now
        if self._t_first_submit is None:
            self._t_first_submit = now

    def record_first_token(self, rid: int) -> None:
        t0 = self.submit_ts.get(rid)
        if t0 is not None:
            self._ttfts.append(self.clock() - t0)

    def record_finish(self, rid: int, n_generated: int) -> None:
        now = self.clock()
        t0 = self.submit_ts.pop(rid, None)
        if t0 is not None:
            self._latencies.append(now - t0)
        self.requests_finished += 1
        self.tokens_generated += int(n_generated)
        self._t_last_finish = now

    # --- per-iteration ----------------------------------------------------

    def record_prefill_chunk(self) -> None:
        self.prefill_chunks += 1

    def record_iteration(self, queue_depth: int, occupied: int,
                         num_slots: int) -> None:
        self.queue_depth.append(int(queue_depth))
        self.occupancy.append(occupied / num_slots)

    def record_decode(self, n_decoding: int, dt: float) -> None:
        self.decode_samples.append((int(n_decoding), float(dt)))

    # --- reductions -------------------------------------------------------

    def ttfts(self) -> List[float]:
        return list(self._ttfts)

    def latencies(self) -> List[float]:
        return list(self._latencies)

    def decode_tokens_per_sec(self,
                              min_occupancy: int = 0) -> Optional[float]:
        """Marginal decode throughput over iterations with at least
        ``min_occupancy`` decoding slots — ``min_occupancy = S`` is the
        steady-state full-batch rate the acceptance criterion compares
        against a raw batched decode loop."""
        toks = sum(n for n, _ in self.decode_samples
                   if n >= min_occupancy)
        secs = sum(dt for n, dt in self.decode_samples
                   if n >= min_occupancy)
        return toks / secs if secs > 0 else None

    def summary(self) -> Dict:
        """The metrics glossary of docs/serving.md, as one dict."""
        elapsed = (self._t_last_finish - self._t_first_submit
                   if self._t_first_submit is not None
                   and self._t_last_finish is not None else 0.0)
        return {
            "requests_finished": self.requests_finished,
            "tokens_generated": self.tokens_generated,
            # request-level throughput: all generated tokens over the
            # first-submit -> last-finish span (includes queueing +
            # prefill)
            "tokens_per_sec": (self.tokens_generated / elapsed
                               if elapsed > 0 else None),
            # marginal decode rate, all iterations / full batch only
            "decode_tokens_per_sec": self.decode_tokens_per_sec(),
            "ttft_s": percentiles(self._ttfts),
            "latency_s": percentiles(self._latencies),
            "queue_depth": ({"mean": sum(self.queue_depth)
                             / len(self.queue_depth),
                             "max": max(self.queue_depth)}
                            if self.queue_depth else None),
            "slot_occupancy": ({"mean": sum(self.occupancy)
                                / len(self.occupancy),
                                "max": max(self.occupancy)}
                               if self.occupancy else None),
            "prefill_chunks": self.prefill_chunks,
            "phases": self.timer.summary(),
        }
