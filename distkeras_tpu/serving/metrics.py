"""Serving metrics: the numbers that describe a serving workload, none
of which a single ``generate()`` call can even express.

Per request: TTFT (submit -> first token — prefill queueing + prompt
ingestion), TPOT (mean seconds per generated token after the first —
the streaming-cadence number the ``tpot_p99`` SLO reads) and
end-to-end latency. Per engine iteration: queue depth,
slot occupancy, decoding-slot count and decode wall time (the
steady-state tokens/s series ``bench.py --model serving`` reduces).
Phase wall-clock (prefill vs decode) rides on
``utils.profiling.StepTimer``.

Since the telemetry PR this class is a thin shape over the
``obs.MetricsRegistry``: TTFT/latency/queue-depth/occupancy live in
registry **reservoir histograms**, so memory is BOUNDED —
O(reservoir + in-flight requests + distinct batch sizes) no matter how
long the engine runs (previously the ttft/latency/occupancy lists grew
one float per request/iteration forever). Exact count/sum/min/max are
streaming; percentiles come from the reservoir (exact until it fills,
a uniform sample after). Per-request state is still streaming: submit
timestamps live only while a request is in flight and are evicted at
finish. A fresh ``ServingMetrics`` per reporting interval
(``engine.metrics = ServingMetrics()``, the ``bench.py`` per-pass
pattern) remains the way to get windowed percentiles.

``summary()`` keys are unchanged from the pre-registry class — the
backward-compat contract existing callers (bench, tests, dashboards)
rely on; ``docs/observability.md`` is the glossary.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import numpy as np

from distkeras_tpu.obs import MetricsRegistry
from distkeras_tpu.utils.profiling import StepTimer, now

#: per-histogram reservoir: the percentile window of a metrics instance
DEFAULT_RESERVOIR = 2048


class ServingMetrics:
    """Host-side counters; negligible overhead (a few registry updates
    and two clock reads per phase). ``clock`` is injectable so tests
    can drive deterministic time. ``registry`` defaults to a PRIVATE
    registry per instance — a metrics object is a measurement window,
    and windows must not share reservoirs; the engine attaches the
    window to the unified ``obs.telemetry_snapshot()`` by reference."""

    def __init__(self, clock=now, registry: Optional[MetricsRegistry] = None,
                 reservoir: int = DEFAULT_RESERVOIR):
        self.clock = clock
        self.registry = registry if registry is not None \
            else MetricsRegistry(reservoir_size=reservoir)
        self.timer = StepTimer()                 # "prefill" / "decode"
        self.submit_ts: Dict[int, float] = {}    # in-flight only
        self.first_ts: Dict[int, float] = {}     # in-flight only
        self._ttft = self.registry.histogram("serving.ttft_s")
        self._tpot = self.registry.histogram("serving.tpot_s")
        self._latency = self.registry.histogram("serving.latency_s")
        self._qdepth = self.registry.histogram("serving.queue_depth")
        self._occ = self.registry.histogram("serving.slot_occupancy")
        self._finished = self.registry.counter("serving.requests_finished")
        self._tokens = self.registry.counter("serving.tokens_generated")
        self._chunks = self.registry.counter("serving.prefill_chunks")
        # degradation counters (resilience PR): shed at admission,
        # expired deadlines, poisoned-request isolations
        self._rejected = self.registry.counter("serving.requests_rejected")
        self._timed_out = self.registry.counter(
            "serving.requests_timed_out")
        self._cancelled = self.registry.counter(
            "serving.requests_cancelled")
        self._decode_toks = self.registry.counter("serving.decode_tokens")
        self._decode_secs = self.registry.counter("serving.decode_seconds")
        # paged-KV accounting (paged-cache PR): page-budget gauges set
        # once per iteration, prefix-cache hit counters, preemptions.
        # Gauges stay unset (None) on a slab engine — summary keys are
        # additive and layout-honest
        self._pages_free = self.registry.gauge("serving.pages_free")
        self._pages_shared = self.registry.gauge("serving.pages_shared")
        self._page_frag = self.registry.gauge(
            "serving.page_fragmentation")
        self._prefix_hits = self.registry.counter("serving.prefix_hits")
        self._prefix_lookups = self.registry.counter(
            "serving.prefix_lookups")
        self._prefix_hit_toks = self.registry.counter(
            "serving.prefix_hit_tokens")
        self._prefix_lookup_toks = self.registry.counter(
            "serving.prefix_lookup_tokens")
        self._preempted = self.registry.counter(
            "serving.requests_preempted")
        # host KV offload tier (offload PR): pages swapped D2H on
        # preemption / prefix spill, pages restored H2D, bytes moved;
        # resume-latency histograms split by path (page swap-in vs
        # context re-prefill — the bench's crossover measurement) and
        # the re-prefill token tallies (recomputed vs avoided)
        self._pages_offloaded = self.registry.counter(
            "serving.pages_offloaded")
        self._pages_restored = self.registry.counter(
            "serving.pages_restored")
        self._offload_bytes = self.registry.counter(
            "serving.offload_bytes")
        self._resume_swap = self.registry.histogram(
            "serving.resume_swap_s")
        self._resume_reprefill = self.registry.histogram(
            "serving.resume_reprefill_s")
        self._reprefill_toks = self.registry.counter(
            "serving.reprefill_tokens")
        self._reprefill_toks_avoided = self.registry.counter(
            "serving.reprefill_tokens_avoided")
        # serving router (router PR): requests detached from this
        # engine for re-admission on another replica (prefill->decode
        # handoff, drain rebalancing) — NOT terminal, NOT preemptions
        self._transferred = self.registry.counter(
            "serving.requests_transferred")
        # speculative decoding (spec-decode PR): drafts offered to the
        # verify step vs drafts the target accepted, plus a per-slot
        # per-iteration acceptance-rate histogram (the bench's
        # percentile source) and streams the acceptance EMA kicked
        # back to plain decode
        self._spec_proposed = self.registry.counter("serving.spec_proposed")
        self._spec_accepted = self.registry.counter("serving.spec_accepted")
        self._spec_rate = self.registry.histogram(
            "serving.spec_accept_rate")
        self._spec_disabled = self.registry.counter(
            "serving.spec_disabled")
        # adaptive re-enable (ServingEngine(spec_reprobe=...)): demoted
        # streams the cooldown re-probe won back to speculation
        self._spec_reenabled = self.registry.counter(
            "serving.spec_reenabled")
        # tree speculation (tree-speculation PR): the per-verify tree
        # width a stream ran at and the accepted root-path length —
        # the adaptive controller's observable trajectory
        self._spec_tree_width = self.registry.histogram(
            "serving.spec_tree_width")
        self._spec_path_len = self.registry.histogram(
            "serving.spec_path_len")
        # MoE serving (MoE-serving PR): per-expert routing load (one
        # gauge series per expert id — BOUNDED by the model's expert
        # count), the router-entropy gauge, and the concentration the
        # engine's MoE-aware admission reads. Unset (None) on MoE-free
        # engines — summary keys stay layout-honest like "pages"
        self._moe_load = self.registry.gauge("serving.moe_expert_load")
        self._moe_entropy = self.registry.gauge(
            "serving.moe_router_entropy")
        self._moe_conc = self.registry.gauge(
            "serving.moe_concentration")
        self._moe_experts = 0            # label-set bound, for summary
        #: exact (tokens, seconds) aggregation per decoding-slot count —
        #: bounded by the slot count, and authoritative for
        #: ``decode_tokens_per_sec`` (the labeled counters mirror it for
        #: exporters)
        self._decode_agg: Dict[int, List[float]] = {}
        #: recent (n_decoding, dt) samples — a BOUNDED window view
        #: (bench.py reads the warm-up iterations from it)
        self._decode_recent = deque(maxlen=reservoir)
        self._t_first_submit: Optional[float] = None
        self._t_last_finish: Optional[float] = None

    # --- per-request ------------------------------------------------------

    def record_submit(self, rid: int) -> None:
        now_ = self.clock()
        self.submit_ts[rid] = now_
        if self._t_first_submit is None:
            self._t_first_submit = now_

    def record_first_token(self, rid: int) -> None:
        now_ = self.clock()
        t0 = self.submit_ts.get(rid)
        if t0 is not None:
            self._ttft.observe(now_ - t0)
            self.first_ts[rid] = now_

    def record_finish(self, rid: int, n_generated: int) -> None:
        now_ = self.clock()
        # evict the in-flight entries: finished-request state must not
        # accumulate in a long-lived engine
        t0 = self.submit_ts.pop(rid, None)
        if t0 is not None:
            self._latency.observe(now_ - t0)
        t_first = self.first_ts.pop(rid, None)
        if t_first is not None and n_generated > 1:
            # TPOT: mean seconds per generated token AFTER the first
            # (the streaming-cadence number; the first token is TTFT's)
            self._tpot.observe((now_ - t_first) / (n_generated - 1))
        self._finished.inc()
        self._tokens.inc(int(n_generated))
        self._t_last_finish = now_

    def record_rejected(self) -> None:
        """A submit shed by the bounded admission queue (the request
        never entered the engine — no submit timestamp to evict)."""
        self._rejected.inc()

    def record_timeout(self, rid: int) -> None:
        """A request's deadline expired before it finished."""
        self.submit_ts.pop(rid, None)
        self.first_ts.pop(rid, None)
        self._timed_out.inc()

    def record_cancelled(self, rid: int) -> None:
        """A request isolated after a step error (or cancelled by API)."""
        self.submit_ts.pop(rid, None)
        self.first_ts.pop(rid, None)
        self._cancelled.inc()

    def record_preemption(self, rid: int) -> None:
        """A decoding request evicted back to the queue (page-budget
        pressure). NOT terminal: its submit/first-token timestamps
        stay — TTFT already fired and latency measures to the real
        finish, across however many preemptions."""
        self._preempted.inc()

    def record_transfer(self, rid: int) -> None:
        """A request left this engine ALIVE (``transfer_out``: router
        handoff or rebalancing). Its in-flight timestamps are evicted —
        the window must not leak entries for requests that will finish
        on another replica's metrics window."""
        self.submit_ts.pop(rid, None)
        self.first_ts.pop(rid, None)
        self._transferred.inc()

    def record_prefix_lookup(self, hit_tokens: int,
                             total_tokens: int) -> None:
        """One prefix-cache lookup at admission: ``hit_tokens`` of the
        request's ``total_tokens`` context came off shared pages."""
        self._prefix_lookups.inc()
        self._prefix_lookup_toks.inc(int(total_tokens))
        if hit_tokens > 0:
            self._prefix_hits.inc()
            self._prefix_hit_toks.inc(int(hit_tokens))

    def record_pages(self, free: int, shared: int,
                     fragmentation: float) -> None:
        """Per-iteration page-budget gauges (paged engine only)."""
        self._pages_free.set(int(free))
        self._pages_shared.set(int(shared))
        self._page_frag.set(float(fragmentation))

    def record_offload(self, offloaded: int, restored: int,
                       nbytes: int) -> None:
        """Host-tier page movement since the last flush (the engine
        publishes per-window DELTAS of the pool's cumulative
        odometers)."""
        self._pages_offloaded.inc(int(offloaded))
        self._pages_restored.inc(int(restored))
        self._offload_bytes.inc(int(nbytes))

    def record_swap_resume(self, dur_s: float,
                           tokens_avoided: int) -> None:
        """One preemption resume served by a host-page SWAP-IN:
        ``dur_s`` is the H2D copy + table restore wall;
        ``tokens_avoided`` the context tokens a re-prefill resume
        would have recomputed."""
        self._resume_swap.observe(float(dur_s))
        self._reprefill_toks_avoided.inc(int(tokens_avoided))

    def record_reprefill_resume(self, dur_s: float,
                                tokens: int) -> None:
        """One preemption resume served by context RE-PREFILL:
        ``dur_s`` spans first recompute chunk -> rejoining decode,
        ``tokens`` the context positions recomputed (net of shared
        prefix pages)."""
        self._resume_reprefill.observe(float(dur_s))
        self._reprefill_toks.inc(int(tokens))

    def record_spec_verify(self, proposed: int, accepted: int) -> None:
        """One slot's outcome in one speculative verify step:
        ``proposed`` drafts offered (the engine's fixed k), ``accepted``
        of them matched the target's own choices."""
        proposed, accepted = int(proposed), int(accepted)
        self._spec_proposed.inc(proposed)
        self._spec_accepted.inc(accepted)
        if proposed > 0:
            self._spec_rate.observe(accepted / proposed)

    def record_spec_disabled(self) -> None:
        """The acceptance EMA kicked one stream back to plain decode."""
        self._spec_disabled.inc()

    def record_spec_reenabled(self) -> None:
        """A demoted stream's cooldown re-probe won speculation back."""
        self._spec_reenabled.inc()

    def record_spec_tree(self, tree_width: int,
                         accepted_path_len: int) -> None:
        """One slot's outcome in one TREE verify (tree-speculation PR):
        the branch width the stream's adaptive tree ran at and the
        accepted root-path length (0 = only the bonus token emitted)."""
        self._spec_tree_width.observe(float(tree_width))
        self._spec_path_len.observe(float(accepted_path_len))

    def record_moe_route(self, expert_load, entropy: float,
                         concentration: float) -> None:
        """One decode iteration's MoE routing picture: ``expert_load``
        [E] routing-slot assignments per expert (summed over the
        model's MoE layers, live slots only), the mean router entropy
        (nats), and the engine's smoothed concentration (0 = uniform
        routing, 1 = everything on one expert). One gauge series per
        expert id — the label set is bounded by E."""
        load = np.asarray(expert_load, np.float64)
        self._moe_experts = max(self._moe_experts, len(load))
        for e, v in enumerate(load):
            self._moe_load.set(float(v), expert=str(e))
        self._moe_entropy.set(float(entropy))
        self._moe_conc.set(float(concentration))

    # --- per-iteration ----------------------------------------------------

    def record_prefill_chunk(self) -> None:
        self._chunks.inc()

    def record_iteration(self, queue_depth: int, occupied: int,
                         num_slots: int) -> None:
        self._qdepth.observe(int(queue_depth))
        self._occ.observe(occupied / num_slots)

    def record_decode(self, n_decoding: int, dt: float,
                      n_tokens: Optional[int] = None) -> None:
        """One decode iteration over ``n_decoding`` slots taking ``dt``
        seconds. ``n_tokens`` is the tokens actually emitted — it
        defaults to one per decoding slot (the plain step) and exceeds
        it under speculation (a verify step emits ``1 + accepted`` per
        slot), so ``decode_tokens_per_sec`` prices speculation's win
        without any caller-side special-casing."""
        n, dt = int(n_decoding), float(dt)
        toks = n if n_tokens is None else int(n_tokens)
        agg = self._decode_agg.setdefault(n, [0.0, 0.0])
        agg[0] += toks
        agg[1] += dt
        self._decode_toks.inc(toks, slots=n)
        self._decode_secs.inc(dt, slots=n)
        self._decode_recent.append((n, dt))

    # --- properties kept for existing callers -----------------------------

    @property
    def requests_finished(self) -> int:
        return int(self._finished.value())

    @property
    def tokens_generated(self) -> int:
        return int(self._tokens.value())

    @property
    def prefill_chunks(self) -> int:
        return int(self._chunks.value())

    @property
    def requests_rejected(self) -> int:
        return int(self._rejected.value())

    @property
    def requests_timed_out(self) -> int:
        return int(self._timed_out.value())

    @property
    def requests_cancelled(self) -> int:
        return int(self._cancelled.value())

    @property
    def requests_preempted(self) -> int:
        return int(self._preempted.value())

    @property
    def requests_transferred(self) -> int:
        return int(self._transferred.value())

    @property
    def pages_offloaded(self) -> int:
        return int(self._pages_offloaded.value())

    @property
    def pages_restored(self) -> int:
        return int(self._pages_restored.value())

    def resume_swap_samples(self) -> List[float]:
        """Swap-in resume durations (histogram reservoir) — the
        offload bench reduces these to p50/p99."""
        return self._resume_swap.samples()

    def resume_reprefill_samples(self) -> List[float]:
        return self._resume_reprefill.samples()

    @property
    def spec_proposed(self) -> int:
        return int(self._spec_proposed.value())

    @property
    def spec_accepted(self) -> int:
        return int(self._spec_accepted.value())

    @property
    def acceptance_rate(self) -> Optional[float]:
        """Fraction of proposed draft tokens the target accepted (None
        before any speculative verify ran)."""
        prop = self._spec_proposed.value()
        if prop <= 0:
            return None
        return self._spec_accepted.value() / prop

    @property
    def moe_expert_load(self) -> Optional[List[float]]:
        """Last-iteration per-expert routing load (None on MoE-free
        engines or before the first MoE decode step)."""
        if not self._moe_experts:
            return None
        return [self._moe_load.value(expert=str(e)) or 0.0
                for e in range(self._moe_experts)]

    @property
    def prefix_hit_rate(self) -> Optional[float]:
        """Fraction of looked-up context tokens served off shared
        pages (None before any lookup)."""
        total = self._prefix_lookup_toks.value()
        if total <= 0:
            return None
        return self._prefix_hit_toks.value() / total

    @property
    def decode_samples(self) -> List:
        """Recent ``(n_decoding, dt)`` pairs (bounded window)."""
        return list(self._decode_recent)

    # --- reductions -------------------------------------------------------

    def ttfts(self) -> List[float]:
        """TTFT samples (the histogram reservoir — exact until
        ``reservoir`` requests, a uniform sample after)."""
        return self._ttft.samples()

    def latencies(self) -> List[float]:
        return self._latency.samples()

    def spec_accept_rates(self) -> List[float]:
        """Per-slot per-iteration draft acceptance-rate samples (the
        histogram reservoir) — bench reduces these to percentiles."""
        return self._spec_rate.samples()

    def decode_tokens_per_sec(self,
                              min_occupancy: int = 0) -> Optional[float]:
        """Marginal decode throughput over iterations with at least
        ``min_occupancy`` decoding slots — ``min_occupancy = S`` is the
        steady-state full-batch rate the acceptance criterion compares
        against a raw batched decode loop. Exact over ALL iterations
        (streaming per-slot-count aggregation, not the sample window).
        """
        toks = sum(a[0] for n, a in self._decode_agg.items()
                   if n >= min_occupancy)
        secs = sum(a[1] for n, a in self._decode_agg.items()
                   if n >= min_occupancy)
        return toks / secs if secs > 0 else None

    @staticmethod
    def _pcts(hist) -> Optional[Dict[str, float]]:
        stats = hist.stats()
        if stats is None:
            return None
        return {"p50": stats["p50"], "p99": stats["p99"]}

    def summary(self) -> Dict:
        """The metrics glossary of docs/observability.md, as one dict —
        keys unchanged across the registry migration."""
        elapsed = (self._t_last_finish - self._t_first_submit
                   if self._t_first_submit is not None
                   and self._t_last_finish is not None else 0.0)
        qd = self._qdepth.stats()
        occ = self._occ.stats()
        tokens = self.tokens_generated
        pages_free = self._pages_free.value()
        return {
            "requests_finished": self.requests_finished,
            # degradation tally (keys ADDED by the resilience PR; all
            # pre-existing keys unchanged)
            "requests_rejected": self.requests_rejected,
            "requests_timed_out": self.requests_timed_out,
            "requests_cancelled": self.requests_cancelled,
            # paged-KV tally (keys ADDED by the paged-cache PR): page
            # budget at the last iteration, prefix-cache hit rate,
            # preemption count; "pages" is None on a slab engine
            "requests_preempted": self.requests_preempted,
            # serving-router tally (key ADDED by the router PR):
            # live departures to another replica
            "requests_transferred": self.requests_transferred,
            "pages": (None if pages_free is None else {
                "free": int(pages_free),
                "shared": int(self._pages_shared.value() or 0),
                "fragmentation": self._page_frag.value()}),
            # host KV offload tier (keys ADDED by the offload PR):
            # page-swap traffic and the per-path resume latencies —
            # the swap-vs-re-prefill crossover, measured
            "offload": {
                "pages_offloaded": self.pages_offloaded,
                "pages_restored": self.pages_restored,
                "offload_bytes": int(self._offload_bytes.value()),
                "reprefill_tokens": int(self._reprefill_toks.value()),
                "reprefill_tokens_avoided": int(
                    self._reprefill_toks_avoided.value()),
                "resume_swap_s": self._pcts(self._resume_swap),
                "resume_reprefill_s": self._pcts(
                    self._resume_reprefill)},
            "prefix_cache": {
                "lookups": int(self._prefix_lookups.value()),
                "hits": int(self._prefix_hits.value()),
                "hit_rate": self.prefix_hit_rate},
            # speculative decoding (keys ADDED by the spec-decode PR):
            # aggregate acceptance plus the per-slot-per-iteration
            # acceptance-rate percentiles bench records
            # MoE serving (keys ADDED by the MoE-serving PR): the
            # last iteration's expert-load picture; None on MoE-free
            # engines
            "moe": (None if not self._moe_experts else {
                "expert_load": self.moe_expert_load,
                "router_entropy": self._moe_entropy.value(),
                "concentration": self._moe_conc.value()}),
            "acceptance_rate": self.acceptance_rate,
            "speculation": {
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "disabled_streams": int(self._spec_disabled.value()),
                # key ADDED by the loadgen/timeseries PR: re-probe wins
                "reenabled_streams": int(self._spec_reenabled.value()),
                "accept_rate": self._pcts(self._spec_rate),
                # tree keys (ADDED by the tree-speculation PR): None
                # until a tree verify ran
                "tree_width": self._pcts(self._spec_tree_width),
                "accepted_path_len": self._pcts(self._spec_path_len)},
            "tokens_generated": tokens,
            # request-level throughput: all generated tokens over the
            # first-submit -> last-finish span (includes queueing +
            # prefill)
            "tokens_per_sec": (tokens / elapsed if elapsed > 0 else None),
            # marginal decode rate, all iterations / full batch only
            "decode_tokens_per_sec": self.decode_tokens_per_sec(),
            "ttft_s": self._pcts(self._ttft),
            # key ADDED by the tracing/SLO PR (pre-existing keys
            # unchanged): per-token decode cadence of finished requests
            "tpot_s": self._pcts(self._tpot),
            "latency_s": self._pcts(self._latency),
            "queue_depth": ({"mean": qd["mean"], "max": qd["max"]}
                            if qd else None),
            "slot_occupancy": ({"mean": occ["mean"], "max": occ["max"]}
                               if occ else None),
            "prefill_chunks": self.prefill_chunks,
            "phases": self.timer.summary(),
        }
