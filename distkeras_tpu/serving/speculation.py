"""Draft sources for speculative decoding in the serving engine.

Decode is memory-bandwidth-bound: every iteration moves the whole
parameter set plus the KV pages to emit ONE token per slot. Speculative
decoding (Leviathan et al.) amortizes one target-model pass over ``k``
candidate tokens: a cheap DRAFT proposes ``d_1..d_k`` per slot, the
target scores the whole ``[tok, d_1, .., d_k]`` window in one batched
verify step (``models.decoding.verify_step_slots[_paged]``), and the
longest prefix of drafts matching the target's own choices is accepted
— plus the target's next candidate for free. High-acceptance streams
emit up to ``k + 1`` tokens per target pass; the worst case emits the
1 token plain decode would have.

Two draft sources, one interface:

``NgramDraft`` — prompt-lookup / n-gram SELF-drafting: propose the
    continuation that followed the most recent earlier occurrence of
    the stream's current suffix (searched over prompt + generated
    tokens, host-side, zero extra weights and zero device work).
    Excellent on repetitive / templated / retrieval-grounded streams
    (summarization, code edits, RAG quoting its context); near-zero
    acceptance on text whose continuation never re-occurs — which the
    engine's per-request acceptance EMA detects, kicking the stream
    back to plain decode.

``DraftModel`` — a small target-compatible model (same vocab) decoded
    greedily ``k`` steps ahead through the EXISTING paged machinery:
    its own ``PagedKVPool`` (sized worst-case up front, so drafting can
    never starve the target pool's admission budget mid-flight), its
    own per-slot page tables, ``decode_step_slots_paged`` as the draft
    step. Context enters via a head-less chunk prefill at the moment a
    request joins decode (``begin_slot``); after every verify the
    engine's position vector is the single source of truth, so the
    draft cache's rejected-tail garbage self-heals exactly like the
    target's (each position is re-written the iteration it becomes
    current, before any mask admits it).

Drafts are DETERMINISTIC (argmax / lookup) by design: a point-mass
draft distribution makes the exact rejection-sampling acceptance rule
collapse to "sample from the target, accept while it equals the
draft" — which keeps sampled streams byte-identical to plain decode
(same per-request key stream, one split per emitted token) instead of
merely distribution-equivalent. See docs/serving.md §Speculative
decoding for the acceptance math.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

__all__ = ["DraftSource", "NgramDraft", "DraftModel"]


class DraftSource:
    """Interface the serving engine drives. Implementations fill a
    fixed ``[S, k]`` draft buffer per iteration; all hooks are
    host-side calls on the engine thread (no locking needed).

    ``begin_slot`` returns False when the source cannot draft for this
    request (e.g. its own KV pool is dry) — the engine then disables
    speculation for THAT request and admission proceeds untouched:
    drafting is an accelerator, never a gate."""

    def bind(self, engine) -> None:
        """Called once from ``ServingEngine.__init__`` with the owning
        engine (slot count, max_len, spec_k are known here)."""

    def begin_slot(self, slot: int, context: np.ndarray) -> bool:
        """A request joined the decode batch in ``slot`` with
        ``context`` tokens already in the TARGET cache (prompt, plus
        generated[:-1] after a preemption resume). Returns whether this
        source can draft for the slot."""
        return True

    def end_slot(self, slot: int) -> None:
        """The slot's request left decode (finish/preempt/cancel).
        Must be tolerant of slots never begun."""

    def propose(self, requests: Dict[int, object], tok: np.ndarray,
                t: np.ndarray, out: np.ndarray,
                active: np.ndarray) -> None:
        """Fill ``out[slot, :k]`` with draft tokens continuing after
        ``tok[slot]`` (the slot's pending decode input at position
        ``t[slot]``) for every slot with ``active[slot]``.
        ``requests`` maps slot -> Request (token history access).
        Rows left untouched are harmless — inactive slots' drafts are
        force-rejected in the verify program."""
        raise NotImplementedError


class NgramDraft(DraftSource):
    """Prompt-lookup self-drafting: suffix-match over each stream's own
    prompt + generated tokens.

    For suffix lengths ``max_ngram`` down to ``min_ngram``, find the
    most recent EARLIER occurrence of the stream's current suffix and
    propose the ``k`` tokens that followed it (preferring an occurrence
    with a full ``k``-token continuation). No weights, no device work —
    the proposal is a numpy scan over at most ``max_context`` recent
    tokens. Streams whose continuation never re-occurs get filler
    drafts that the verify step rejects; the engine's acceptance EMA
    then disables speculation for them."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_context: int = 4096):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}/{max_ngram}")
        if max_context < max_ngram + 1:
            raise ValueError(
                f"max_context ({max_context}) must exceed max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.max_context = int(max_context)

    def propose(self, requests, tok, t, out, active):
        k = out.shape[1]
        cap = self.max_context
        for slot, req in requests.items():
            if not active[slot]:
                continue
            # slice BEFORE concatenating: the cap must bound the
            # per-iteration host copy too, not just the scan — at long
            # prompts the full-history concat was the hot-loop cost
            gen = req.generated[-cap:]
            head = req.prompt[-max(0, cap - len(gen)):] \
                if len(gen) < cap else req.prompt[:0]
            ctx = np.concatenate(
                [head, np.asarray(gen, np.int32)])
            out[slot] = self.lookup(ctx, k)

    def lookup(self, ctx: np.ndarray, k: int) -> np.ndarray:
        """The k-token proposal continuing ``ctx`` (which ends with the
        pending decode input). Zeros when no suffix re-occurs — filler
        the verify step will reject."""
        buf = np.zeros(k, np.int32)
        n_hi = min(self.max_ngram, len(ctx) - 1)
        for n in range(n_hi, self.min_ngram - 1, -1):
            suffix = ctx[-n:]
            # candidate starts 0 .. len-1-n: every hit has at least one
            # continuation token; the suffix's own occurrence (start
            # len-n) is excluded by construction
            win = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
            hits = np.flatnonzero((win == suffix).all(axis=1))
            if not hits.size:
                continue
            # most recent occurrence, preferring one whose continuation
            # covers the full k tokens (periodic streams: the last
            # overlapping hit may sit too close to the end)
            full = hits[hits + n + k <= len(ctx)]
            i = int(full[-1] if full.size else hits[-1])
            cont = ctx[i + n:i + n + k]
            buf[:len(cont)] = cont
            if 0 < len(cont) < k:
                buf[len(cont):] = cont[-1]       # pad; tail likely rejects
            return buf
        return buf


class DraftModel(DraftSource):
    """A small target-compatible LM drafting ``k`` greedy steps ahead
    through its own paged KV machinery.

    The draft pool is provisioned at ``bind`` time — by default at
    worst-case parity (``num_slots * ceil(max_len / page_len)`` pages),
    so draft-KV memory is a FIXED budget decided up front and the
    target pool's admission arithmetic never competes with drafting. A
    smaller explicit ``num_pages`` is allowed: ``begin_slot`` then
    allocates a slot's worst case eagerly and reports False when the
    draft pool is dry, which disables speculation for that request
    only — admission is never blocked on draft pages.

    The draft model must share the target's tokenizer/vocab (the
    proposals are target token ids); architecture and size are free —
    the win condition is ``k`` draft steps + one (k+1)-wide target pass
    beating ``acc + 1`` plain target steps."""

    def __init__(self, model, *, page_len: int = 16,
                 num_pages: Optional[int] = None, cache_dtype=None,
                 weights_dtype="auto"):
        from distkeras_tpu.models.core import Sequential
        module = model.module
        if not isinstance(module, Sequential):
            raise TypeError("DraftModel expects a Sequential LM "
                            f"(got {type(module).__name__})")
        from distkeras_tpu.models.decoding import (_attn_compute_dtype,
                                                   _resolve_head_dims,
                                                   _serving_params)
        self.model = model
        self.module = module
        _resolve_head_dims(module, model.params)
        compute_dt = _attn_compute_dtype(module)
        import jax.numpy as jnp
        if cache_dtype is None:
            cache_dtype = (compute_dt if compute_dt is not None
                           else jnp.float32)
        if weights_dtype == "auto":
            weights_dtype = compute_dt if (
                compute_dt is not None
                and compute_dt != jnp.dtype(jnp.float32)) else None
        self._params = (model.params if weights_dtype is None
                        else _serving_params(model.params, weights_dtype))
        self._state = model.state
        self._page_len = int(page_len)
        self._num_pages = num_pages
        self._cache_dtype = cache_dtype
        self.pool = None                     # built at bind()
        self._staging = None
        self._prefill_fns = {}               # length-keyed LRU, engine cap
        self._step_fn = None
        self._active = set()                 # slots with live draft KV

    #: same LRU bound the engine uses for its ragged prefill programs
    MAX_PREFILL_PROGRAMS = 64

    def bind(self, engine) -> None:
        from distkeras_tpu.serving.kv_pool import PagedKVPool
        self.pool = PagedKVPool(self.module, engine.num_slots,
                                engine.max_len, page_len=self._page_len,
                                num_pages=self._num_pages,
                                dtype=self._cache_dtype)
        self._staging = self.pool.make_request_cache()

    def begin_slot(self, slot: int, context: np.ndarray) -> bool:
        import jax.numpy as jnp
        self.end_slot(slot)                  # tolerate re-begin
        pool = self.pool
        # eager worst-case allocation: the draft step never needs a
        # mid-decode growth path (and with the default parity sizing
        # this can never fail)
        pids = []
        for _ in range(pool.pages_per_slot):
            pid = pool.alloc_page()
            if pid is None:
                for p in pids:
                    pool.decref(p)
                return False                 # draft pool dry: no drafting
            pids.append(pid)
        for j, pid in enumerate(pids):
            pool.assign(slot, j, pid)
        n = len(context)
        fn = self._prefill_fn(n)
        self._staging = fn(self._params, self._state, self._staging,
                           jnp.asarray(np.asarray(context,
                                                  np.int32)[None]))
        pool.insert_pages(self._staging, slot, 0, n)
        self._active.add(slot)
        return True

    def end_slot(self, slot: int) -> None:
        if self.pool is not None and slot in self._active:
            self.pool.release_slot(slot)
            self._active.discard(slot)

    def _prefill_fn(self, n: int):
        """Head-less whole-context chunk prefill at batch 1 (the draft
        only ever needs cache entries, never logits). One program per
        context length, LRU-capped like the engine's."""
        fn = self._prefill_fns.pop(n, None)
        if fn is None:
            from distkeras_tpu.models.decoding import prefill_chunk_step
            module = self.module

            def f(params, state, cache, chunk):
                _, cache = prefill_chunk_step(module, params, state,
                                              cache, chunk, 0,
                                              final=False)
                return cache

            fn = jax.jit(f)
        self._prefill_fns[n] = fn
        while len(self._prefill_fns) > self.MAX_PREFILL_PROGRAMS:
            self._prefill_fns.pop(next(iter(self._prefill_fns)))
        return fn

    def _decode_fn(self):
        if self._step_fn is None:
            from distkeras_tpu.models.decoding import \
                decode_step_slots_paged
            import jax.numpy as jnp
            module = self.module
            page_len = self.pool.page_len

            @jax.jit
            def fn(params, state, cache, tok, t, tables):
                logits, cache = decode_step_slots_paged(
                    module, params, state, cache, tok, t, tables,
                    page_len)
                return jnp.argmax(logits, axis=-1), cache

            self._step_fn = fn
        return self._step_fn

    def propose(self, requests, tok, t, out, active):
        import jax.numpy as jnp
        if not self._active:
            return
        k = out.shape[1]
        fn = self._decode_fn()
        tables = self.pool.device_tables()
        # slots without live draft KV (speculation disabled, or the
        # draft pool was dry at begin) run at the inert sentinel so
        # their writes drop and their garbage proposals stay inactive
        tt = np.where([s in self._active for s in range(len(t))],
                      t, self.pool.max_len).astype(np.int32)
        cur = np.asarray(tok, np.int32).copy()
        for j in range(k):
            nxt, self.pool.cache = fn(self._params, self._state,
                                      self.pool.cache, jnp.asarray(cur),
                                      jnp.asarray(tt), tables)
            cur = np.asarray(nxt).astype(np.int32)
            out[:, j] = cur
            tt = tt + 1
