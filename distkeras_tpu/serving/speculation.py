"""Draft sources for speculative decoding in the serving engine.

Decode is memory-bandwidth-bound: every iteration moves the whole
parameter set plus the KV pages to emit ONE token per slot. Speculative
decoding (Leviathan et al.) amortizes one target-model pass over ``k``
candidate tokens: a cheap DRAFT proposes ``d_1..d_k`` per slot, the
target scores the whole ``[tok, d_1, .., d_k]`` window in one batched
verify step (``models.decoding.verify_step_slots[_paged]``), and the
longest prefix of drafts matching the target's own choices is accepted
— plus the target's next candidate for free. High-acceptance streams
emit up to ``k + 1`` tokens per target pass; the worst case emits the
1 token plain decode would have.

Two draft sources, one interface:

``NgramDraft`` — prompt-lookup / n-gram SELF-drafting: propose the
    continuation that followed the most recent earlier occurrence of
    the stream's current suffix (searched over prompt + generated
    tokens, host-side, zero extra weights and zero device work).
    Excellent on repetitive / templated / retrieval-grounded streams
    (summarization, code edits, RAG quoting its context); near-zero
    acceptance on text whose continuation never re-occurs — which the
    engine's per-request acceptance EMA detects, kicking the stream
    back to plain decode.

``DraftModel`` — a small target-compatible model (same vocab) decoded
    greedily ``k`` steps ahead through the EXISTING paged machinery:
    its own ``PagedKVPool`` (sized worst-case up front, so drafting can
    never starve the target pool's admission budget mid-flight), its
    own per-slot page tables, ``decode_step_slots_paged`` as the draft
    step. Context enters via a head-less chunk prefill at the moment a
    request joins decode (``begin_slot``); after every verify the
    engine's position vector is the single source of truth, so the
    draft cache's rejected-tail garbage self-heals exactly like the
    target's (each position is re-written the iteration it becomes
    current, before any mask admits it).

Drafts are DETERMINISTIC (argmax / lookup) by design: a point-mass
draft distribution makes the exact rejection-sampling acceptance rule
collapse to "sample from the target, accept while it equals the
draft" — which keeps sampled streams byte-identical to plain decode
(same per-request key stream, one split per emitted token) instead of
merely distribution-equivalent. See docs/serving.md §Speculative
decoding for the acceptance math.

TREE SPECULATION (tree-speculation PR): the engine can also drive
``propose_tree`` — a per-slot token TREE (SpecInfer/Medusa-style
multi-chain drafts) verified through ONE tree-masked window
(``models.decoding.verify_step_slots[_tree kwarg]``). A tree raises
expected accepted-tokens-per-verify over a single chain exactly when
the chain's next token is AMBIGUOUS: several plausible continuations
exist and the linear draft can only bet on one. ``NgramDraft`` trees
branch on distinct historical continuations of the matched suffix
(top-m continuations hash-consed into a trie — one node per divergence
point); ``DraftModel`` trees are beam-style (the greedy chain plus the
per-step top-``width`` runner-up tokens as single-node side branches).
Every ``DraftSource`` gets trees for free via the default
``propose_tree`` (its linear chain laid out as a width-1 tree — the
engine's ``spec_tree`` A/B and the byte-identity oracle hook).

Host-sync discipline: ``propose``/``propose_tree`` and the tree
helpers below run INSIDE the serving iteration (a speculative
iteration is synchronous by design — the verify fetch is its
sanctioned sync), so they are a ``tools/lint_host_sync.py`` zone: no
``jax.device_get``/``block_until_ready``/``float(<traced>)``. The
draft-model step's per-step ``np.asarray`` fetch is the sources'
sanctioned medium (drafting is host-driven by design).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

__all__ = ["DraftSource", "NgramDraft", "DraftModel", "tree_ancestors",
           "build_token_tree"]


def tree_ancestors(parents: np.ndarray):
    """Host-side tree derivation: parent-index vectors ``[S, W]``
    (node 0 = root; ``parents[s, 0] = -1``; unused nodes carry -1) ->
    ``(depth [S, W] int32, anc [S, W, W] bool, n_nodes [S] int64)``.
    ``anc[s, i, j]`` is True iff node j is i or an ancestor of i —
    the verify window's visibility mask; ``depth`` is each node's
    root-path position offset; ``n_nodes`` counts root + used nodes
    (the page-lookahead span: the forward writes window columns
    ``t .. t + n_nodes - 1``). Parents must be topologically ordered
    (``parents[s, j] < j``) — the tree builders guarantee it."""
    parents = np.asarray(parents, np.int64)
    s_n, w_len = parents.shape
    depth = np.zeros((s_n, w_len), np.int32)
    anc = np.zeros((s_n, w_len, w_len), bool)
    anc[:, 0, 0] = True
    rows = np.arange(s_n)
    for j in range(1, w_len):
        p = parents[:, j]
        used = p >= 0
        pc = np.where(used, p, 0)
        anc[:, j] = np.where(used[:, None], anc[rows, pc], False)
        anc[rows, j, j] = used
        depth[:, j] = np.where(used, depth[rows, pc] + 1, 0)
    n_nodes = (parents >= 0).sum(axis=1) + 1
    return depth, anc, n_nodes


def build_token_tree(chains, toks_row: np.ndarray,
                     parents_row: np.ndarray, max_nodes: int) -> int:
    """Merge candidate continuation ``chains`` (iterable of int token
    sequences, best first) into one slot's tree arrays: shared
    prefixes hash-cons onto one node — the trie of continuations, one
    branch per divergence point — under a ``max_nodes`` draft-node
    budget (later chains truncate first: insertion order is priority
    order). ``toks_row[0]`` (the pending input/root) is the caller's;
    returns the number of draft nodes used."""
    index = {}
    nxt = 1
    cap = min(int(max_nodes), len(toks_row) - 1)
    for chain in chains:
        par = 0
        for tokv in chain:
            key = (par, int(tokv))
            nid = index.get(key)
            if nid is None:
                if nxt > cap:
                    break
                nid = nxt
                nxt += 1
                index[key] = nid
                toks_row[nid] = int(tokv)
                parents_row[nid] = par
            par = nid
    return nxt - 1


class DraftSource:
    """Interface the serving engine drives. Implementations fill a
    fixed ``[S, k]`` draft buffer per iteration; all hooks are
    host-side calls on the engine thread (no locking needed).

    ``begin_slot`` returns False when the source cannot draft for this
    request (e.g. its own KV pool is dry) — the engine then disables
    speculation for THAT request and admission proceeds untouched:
    drafting is an accelerator, never a gate."""

    def bind(self, engine) -> None:
        """Called once from ``ServingEngine.__init__`` with the owning
        engine (slot count, max_len, spec_k are known here)."""

    def begin_slot(self, slot: int, context: np.ndarray) -> bool:
        """A request joined the decode batch in ``slot`` with
        ``context`` tokens already in the TARGET cache (prompt, plus
        generated[:-1] after a preemption resume). Returns whether this
        source can draft for the slot."""
        return True

    def end_slot(self, slot: int) -> None:
        """The slot's request left decode (finish/preempt/cancel).
        Must be tolerant of slots never begun."""

    def propose(self, requests: Dict[int, object], tok: np.ndarray,
                t: np.ndarray, out: np.ndarray,
                active: np.ndarray) -> None:
        """Fill ``out[slot, :k]`` with draft tokens continuing after
        ``tok[slot]`` (the slot's pending decode input at position
        ``t[slot]``) for every slot with ``active[slot]``.
        ``requests`` maps slot -> Request (token history access).
        Rows left untouched are harmless — inactive slots' drafts are
        force-rejected in the verify program."""
        raise NotImplementedError

    def propose_tree(self, requests: Dict[int, object], tok: np.ndarray,
                     t: np.ndarray, toks: np.ndarray,
                     parents: np.ndarray, active: np.ndarray,
                     depth: np.ndarray, width: np.ndarray,
                     max_nodes: np.ndarray) -> None:
        """Fill per-slot token TREES for a tree-masked verify window.
        ``toks``/``parents`` are ``[S, W]``; node 0 (the root) already
        holds the pending input with parent -1, and every unused node
        must keep parent -1. For each active slot the source may use
        up to ``max_nodes[slot]`` draft nodes shaped by the engine's
        adaptive per-stream ``depth[slot]`` (longest chain) and
        ``width[slot]`` (branches per divergence point) — parents must
        stay topologically ordered (``parents[s, j] < j``).

        The default lays the source's LINEAR proposal out as a width-1
        root path, so every ``DraftSource`` speculates through the
        tree window unchanged (the engine's byte-identity oracle
        hook); branching sources override."""
        k = toks.shape[1] - 1
        buf = np.zeros((toks.shape[0], k), np.int32)
        self.propose(requests, tok, t, buf, active)
        cols = np.arange(k)
        use = active[:, None] & (
            cols[None, :] < np.minimum(depth, max_nodes)[:, None])
        toks[:, 1:] = np.where(use, buf, 0)
        parents[:, 1:] = np.where(use, cols[None, :], -1)


class NgramDraft(DraftSource):
    """Prompt-lookup self-drafting: suffix-match over each stream's own
    prompt + generated tokens.

    For suffix lengths ``max_ngram`` down to ``min_ngram``, find the
    most recent EARLIER occurrence of the stream's current suffix and
    propose the ``k`` tokens that followed it (preferring an occurrence
    with a full ``k``-token continuation). No weights, no device work —
    the proposal is a numpy scan over at most ``max_context`` recent
    tokens. Streams whose continuation never re-occurs get filler
    drafts that the verify step rejects; the engine's acceptance EMA
    then disables speculation for them."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_context: int = 4096):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}/{max_ngram}")
        if max_context < max_ngram + 1:
            raise ValueError(
                f"max_context ({max_context}) must exceed max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.max_context = int(max_context)

    def _context(self, req) -> np.ndarray:
        """The capped lookup context (prompt + generated, most recent
        ``max_context`` tokens). Slices BEFORE concatenating: the cap
        must bound the per-iteration host copy too, not just the scan
        — at long prompts the full-history concat was the hot-loop
        cost. Shared by the linear and tree proposals so the bound
        stays in one place."""
        cap = self.max_context
        gen = req.generated[-cap:]
        head = req.prompt[-max(0, cap - len(gen)):] \
            if len(gen) < cap else req.prompt[:0]
        return np.concatenate([head, np.asarray(gen, np.int32)])

    def propose(self, requests, tok, t, out, active):
        k = out.shape[1]
        for slot, req in requests.items():
            if not active[slot]:
                continue
            out[slot] = self.lookup(self._context(req), k)

    def lookup(self, ctx: np.ndarray, k: int) -> np.ndarray:
        """The k-token proposal continuing ``ctx`` (which ends with the
        pending decode input). Zeros when no suffix re-occurs — filler
        the verify step will reject."""
        buf = np.zeros(k, np.int32)
        n_hi = min(self.max_ngram, len(ctx) - 1)
        for n in range(n_hi, self.min_ngram - 1, -1):
            suffix = ctx[-n:]
            # candidate starts 0 .. len-1-n: every hit has at least one
            # continuation token; the suffix's own occurrence (start
            # len-n) is excluded by construction
            win = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
            hits = np.flatnonzero((win == suffix).all(axis=1))
            if not hits.size:
                continue
            # most recent occurrence, preferring one whose continuation
            # covers the full k tokens (periodic streams: the last
            # overlapping hit may sit too close to the end)
            full = hits[hits + n + k <= len(ctx)]
            i = int(full[-1] if full.size else hits[-1])
            cont = ctx[i + n:i + n + k]
            buf[:len(cont)] = cont
            if 0 < len(cont) < k:
                buf[len(cont):] = cont[-1]       # pad; tail likely rejects
            return buf
        return buf

    def continuations(self, ctx: np.ndarray, m: int):
        """The ``m`` most recent DISTINCT next tokens following the
        current suffix of ``ctx`` — the single-step branching
        primitive of the tree proposal: where :meth:`lookup` bets on
        ONE occurrence's whole continuation, this surfaces every way
        the matched suffix has historically continued (most recent
        first). Suffix lengths ``max_ngram`` down to ``min_ngram``;
        empty when nothing re-occurs."""
        if m < 1:
            return []
        n_hi = min(self.max_ngram, len(ctx) - 1)
        for n in range(n_hi, self.min_ngram - 1, -1):
            suffix = ctx[-n:]
            win = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
            hits = np.flatnonzero((win == suffix).all(axis=1))
            if not hits.size:
                continue
            out = []
            for h in hits[::-1]:                 # most recent first
                tv = int(ctx[h + n])
                if tv not in out:
                    out.append(tv)
                    if len(out) >= m:
                        break
            return out
        return []

    def propose_tree(self, requests, tok, t, toks, parents, active,
                     depth, width, max_nodes):
        """Branching prompt-lookup: grow each stream's tree node by
        node, branching into the top-``width`` distinct historical
        continuations AT EVERY DIVERGENCE POINT — a node whose
        (context + root path) suffix has only ever continued one way
        gets one child; a suffix with disagreeing historical
        continuations gets up to ``width``. Depth-first along the
        most-recent continuation (the linear draft's exact chain is
        the tree's primary path), so a tight node budget spends
        itself on the primary chain before the alternates."""
        for slot, req in requests.items():
            if not active[slot]:
                continue
            self._grow(self._context(req), toks[slot], parents[slot],
                       int(depth[slot]), int(width[slot]),
                       int(max_nodes[slot]))

    def _grow(self, ctx, toks_row, parents_row, depth: int, width: int,
              max_nodes: int) -> int:
        """Tree growth over historical continuations; returns the
        number of draft nodes placed. Budget order: (1) the PRIMARY
        chain — the most-recent continuation at every node, i.e. the
        linear draft's exact bet — to full depth; (2) alternates
        SHALLOW-FIRST (a divergence near the root truncates the whole
        window when missed, so its coverage is worth the most), each
        alternate immediately extended by its own primary chain (the
        branch's aftermath is usually unambiguous — a bare one-token
        branch would waste the depth behind it). Each expansion
        re-runs the suffix scan on ``ctx`` extended by the node's
        root path, so deeper nodes condition on the branch taken;
        scans are bounded by ``max_nodes`` (≤ depth * width)."""
        from collections import deque
        cap = min(int(max_nodes), len(toks_row) - 1)
        if cap < 1 or depth < 1:
            return 0
        used = 0
        alternates = deque()

        def chain(par: int, path, depth_left: int):
            nonlocal used
            while depth_left > 0 and used < cap:
                ctx_ext = (np.concatenate(
                    [ctx, np.asarray(path, np.int32)]) if path else ctx)
                conts = self.continuations(ctx_ext, width)
                if not conts:
                    return
                for tv in conts[1:]:
                    alternates.append((par, list(path), tv, depth_left))
                used += 1
                nid = used
                toks_row[nid] = conts[0]
                parents_row[nid] = par
                par = nid
                path = path + [conts[0]]
                depth_left -= 1

        chain(0, [], depth)
        while alternates and used < cap:
            par, path, tv, depth_left = alternates.popleft()
            used += 1
            nid = used
            toks_row[nid] = tv
            parents_row[nid] = par
            chain(nid, path + [tv], depth_left - 1)
        return used


class DraftModel(DraftSource):
    """A small target-compatible LM drafting ``k`` greedy steps ahead
    through its own paged KV machinery.

    The draft pool is provisioned at ``bind`` time — by default at
    worst-case parity (``num_slots * ceil(max_len / page_len)`` pages),
    so draft-KV memory is a FIXED budget decided up front and the
    target pool's admission arithmetic never competes with drafting. A
    smaller explicit ``num_pages`` is allowed: ``begin_slot`` then
    allocates a slot's worst case eagerly and reports False when the
    draft pool is dry, which disables speculation for that request
    only — admission is never blocked on draft pages.

    The draft model must share the target's tokenizer/vocab (the
    proposals are target token ids); architecture and size are free —
    the win condition is ``k`` draft steps + one (k+1)-wide target pass
    beating ``acc + 1`` plain target steps."""

    def __init__(self, model, *, page_len: int = 16,
                 num_pages: Optional[int] = None, cache_dtype=None,
                 weights_dtype="auto"):
        from distkeras_tpu.models.core import Sequential
        module = model.module
        if not isinstance(module, Sequential):
            raise TypeError("DraftModel expects a Sequential LM "
                            f"(got {type(module).__name__})")
        from distkeras_tpu.models.decoding import (_attn_compute_dtype,
                                                   _resolve_head_dims,
                                                   _serving_params)
        self.model = model
        self.module = module
        _resolve_head_dims(module, model.params)
        compute_dt = _attn_compute_dtype(module)
        import jax.numpy as jnp
        if cache_dtype is None:
            cache_dtype = (compute_dt if compute_dt is not None
                           else jnp.float32)
        if weights_dtype == "auto":
            weights_dtype = compute_dt if (
                compute_dt is not None
                and compute_dt != jnp.dtype(jnp.float32)) else None
        self._params = (model.params if weights_dtype is None
                        else _serving_params(model.params, weights_dtype))
        self._state = model.state
        self._page_len = int(page_len)
        self._num_pages = num_pages
        self._cache_dtype = cache_dtype
        self.pool = None                     # built at bind()
        self._staging = None
        self._prefill_fns = {}               # length-keyed LRU, engine cap
        self._step_fns = {}                  # width -> jit draft step
        self._active = set()                 # slots with live draft KV
        #: slot -> (t0, [tokens]) — what the last draft round WROTE
        #: into the draft KV at positions t0.. (the greedy chain). The
        #: heal pass rewrites positions where the stream actually
        #: committed a DIFFERENT token (an accepted tree side branch);
        #: without it the draft cache silently diverges after the
        #: first non-primary acceptance and every later draft attends
        #: wrong-token KV (code-review finding, this PR).
        self._written = {}

    #: same LRU bound the engine uses for its ragged prefill programs
    MAX_PREFILL_PROGRAMS = 64

    def bind(self, engine) -> None:
        from distkeras_tpu.serving.kv_pool import PagedKVPool
        self.pool = PagedKVPool(self.module, engine.num_slots,
                                engine.max_len, page_len=self._page_len,
                                num_pages=self._num_pages,
                                dtype=self._cache_dtype)
        self._staging = self.pool.make_request_cache()

    def begin_slot(self, slot: int, context: np.ndarray) -> bool:
        import jax.numpy as jnp
        self.end_slot(slot)                  # tolerate re-begin
        pool = self.pool
        # eager worst-case allocation: the draft step never needs a
        # mid-decode growth path (and with the default parity sizing
        # this can never fail)
        pids = []
        for _ in range(pool.pages_per_slot):
            pid = pool.alloc_page()
            if pid is None:
                for p in pids:
                    pool.decref(p)
                return False                 # draft pool dry: no drafting
            pids.append(pid)
        for j, pid in enumerate(pids):
            pool.assign(slot, j, pid)
        n = len(context)
        fn = self._prefill_fn(n)
        self._staging = fn(self._params, self._state, self._staging,
                           jnp.asarray(np.asarray(context,
                                                  np.int32)[None]))
        pool.insert_pages(self._staging, slot, 0, n)
        self._active.add(slot)
        return True

    def end_slot(self, slot: int) -> None:
        if self.pool is not None and slot in self._active:
            self.pool.release_slot(slot)
            self._active.discard(slot)
        self._written.pop(slot, None)

    def _prefill_fn(self, n: int):
        """Head-less whole-context chunk prefill at batch 1 (the draft
        only ever needs cache entries, never logits). One program per
        context length, LRU-capped like the engine's."""
        fn = self._prefill_fns.pop(n, None)
        if fn is None:
            from distkeras_tpu.models.decoding import prefill_chunk_step
            module = self.module

            def f(params, state, cache, chunk):
                _, cache = prefill_chunk_step(module, params, state,
                                              cache, chunk, 0,
                                              final=False)
                return cache

            fn = jax.jit(f)
        self._prefill_fns[n] = fn
        while len(self._prefill_fns) > self.MAX_PREFILL_PROGRAMS:
            self._prefill_fns.pop(next(iter(self._prefill_fns)))
        return fn

    def _decode_fn(self, width: int = 1):
        """Jitted draft step: argmax ids (``width`` 1) or the
        ``lax.top_k`` id matrix ``[S, width]`` (beam-style trees —
        column 0 is the argmax the greedy chain follows). One program
        per distinct width (the engine's per-request widths share the
        engine-level cap, so the set is tiny)."""
        fn = self._step_fns.get(width)
        if fn is None:
            from distkeras_tpu.models.decoding import \
                decode_step_slots_paged
            import jax.numpy as jnp
            from jax import lax
            module = self.module
            page_len = self.pool.page_len

            @jax.jit
            def fn(params, state, cache, tok, t, tables):
                logits, cache = decode_step_slots_paged(
                    module, params, state, cache, tok, t, tables,
                    page_len)
                if width == 1:
                    return jnp.argmax(logits, axis=-1), cache
                return lax.top_k(logits, width)[1], cache

            self._step_fns[width] = fn
        return fn

    def _heal(self, requests, tok, t) -> None:
        """Rewrite draft-KV positions where the stream committed a
        token OTHER than the one the last draft round wrote there —
        the accepted side branch of a tree verify. The linear path is
        immune by construction (the accepted prefix IS the draft's
        own chain), so this almost always no-ops; after a non-primary
        acceptance it replays the actual accepted tokens through the
        ordinary draft step (correct rope, correct KV), bounded by
        the previous round's chain length. Runs batched over slots
        like ``_draft_steps``, inert slots at the sentinel."""
        import jax.numpy as jnp
        s_n = len(t)
        start = np.full(s_n, -1, np.int64)
        stop = np.zeros(s_n, np.int64)
        actual = {}
        for slot, req in requests.items():
            rec = self._written.get(slot)
            if slot not in self._active or rec is None:
                continue
            t0, chain = rec
            ctx = np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)])
            hi = min(int(t[slot]), t0 + len(chain), len(ctx))
            d = t0
            while d < hi and chain[d - t0] == int(ctx[d]):
                d += 1
            if d < hi:
                start[slot] = d
                stop[slot] = hi
                actual[slot] = ctx
        if (start < 0).all():
            return
        fn = self._decode_fn(1)
        tables = self.pool.device_tables()
        n_heal = int((stop - np.maximum(start, 0)).max())
        for j in range(n_heal):
            pos = start + j
            live = (start >= 0) & (pos < stop)
            tt = np.where(live, pos, self.pool.max_len).astype(np.int32)
            cur = np.zeros(s_n, np.int32)
            for slot in actual:
                if live[slot]:
                    cur[slot] = int(actual[slot][pos[slot]])
            _, self.pool.cache = fn(self._params, self._state,
                                    self.pool.cache, jnp.asarray(cur),
                                    jnp.asarray(tt), tables)

    def _draft_steps(self, requests, tok, t, k: int, width: int):
        """Run ``k`` greedy draft steps feeding the argmax forward;
        returns the per-step ``[S, width]`` top-id matrices. Slots
        without live draft KV run at the inert sentinel so their
        writes drop and their garbage proposals stay inactive. Heals
        side-branch divergence from the previous round first, and
        records what this round writes for the next heal."""
        import jax.numpy as jnp
        self._heal(requests, tok, t)
        fn = self._decode_fn(width)
        tables = self.pool.device_tables()
        tt = np.where([s in self._active for s in range(len(t))],
                      t, self.pool.max_len).astype(np.int32)
        cur = np.asarray(tok, np.int32).copy()
        tops = []
        for _ in range(k):
            nxt, self.pool.cache = fn(self._params, self._state,
                                      self.pool.cache, jnp.asarray(cur),
                                      jnp.asarray(tt), tables)
            ids = np.asarray(nxt, np.int32)
            if ids.ndim == 1:
                ids = ids[:, None]
            tops.append(ids)
            cur = ids[:, 0].copy()
            tt = tt + 1
        for slot in self._active:
            self._written[slot] = (
                int(t[slot]),
                [int(tok[slot])] + [int(ids[slot, 0])
                                    for ids in tops[:-1]])
        return tops

    def propose(self, requests, tok, t, out, active):
        if not self._active:
            return
        tops = self._draft_steps(requests, tok, t, out.shape[1], 1)
        for j, ids in enumerate(tops):
            out[:, j] = ids[:, 0]

    def propose_tree(self, requests, tok, t, toks, parents, active,
                     depth, width, max_nodes):
        """Beam-style draft tree: the greedy chain carries the depth,
        and at every chain position the draft's top-``width`` runner-up
        tokens hang off as single-node side branches — the target gets
        ``width`` chances per divergence point at one extra verify
        column each, without the draft paying extra sequential
        steps."""
        if not self._active:
            return
        k = int(depth.max()) if depth.size else 0
        w = int(width.max()) if width.size else 1
        if k < 1:
            return
        tops = self._draft_steps(requests, tok, t, k, max(1, w))
        for slot in range(toks.shape[0]):
            if not active[slot] or slot not in self._active:
                continue
            d = int(depth[slot])
            wd = int(width[slot])
            greedy_chain = np.asarray(
                [tops[j][slot, 0] for j in range(d)], np.int32)
            chains = [greedy_chain]
            for j in range(d):
                for r in range(1, min(wd, tops[j].shape[1])):
                    chains.append(np.concatenate(
                        [greedy_chain[:j],
                         tops[j][slot, r:r + 1]]).astype(np.int32))
            build_token_tree(chains, toks[slot], parents[slot],
                             int(max_nodes[slot]))
