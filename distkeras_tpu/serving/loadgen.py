"""Production-shaped workload generator + deterministic replayer.

``bench.py``'s hand-rolled open-loop traces exercise one arrival
process (exponential inter-arrivals at a fixed rate) with fixed-length
prompts — nothing like production traffic, whose defining features are
exactly what stress a serving fleet: *phased* load (diurnal ramps, step
bursts, flash crowds), *heavy-tailed* prompt/output lengths, and
*structured* prompt populations (shared templates that exercise the
prefix cache, tenants with different priorities). This module
synthesizes such traffic as a replayable artifact and drives it through
an engine or a router fleet deterministically:

* :func:`synthesize` expands a :class:`WorkloadSpec` (phases + length
  distributions + template/tenant mixes) into a :class:`Trace` — every
  request materialized with explicit arrival iteration, prompt tokens,
  output budget, tenant and phase tag — from one numpy seed. Same spec
  + same seed = bit-identical trace, on any host.
* ``Trace.to_jsonl`` / ``Trace.from_jsonl`` round-trip the trace
  through the ``obs.exporters`` JSONL conventions (typed lines under
  the ``SCHEMA_VERSION`` forward-compat contract: the new ``"phase"``
  and ``"request"`` record types are additive — old readers skip
  them, no version bump).
* :func:`replay` drives the trace open-loop on the **engine's own
  iteration clock**: arrivals are indexed by iteration, not wall time,
  and an :class:`IterationClock` (``t = iteration * dt``) is installed
  as the metrics/SLO/time-series clock — no sleeps, no wall-clock
  reads in any recorded number, so a CPU tier-1 test can assert two
  replays produce *identical* per-phase report numbers. Each trace
  phase gets its own ``ServingMetrics`` window (swapped at the
  boundary — the engine drains its pipeline into the old window
  first), so per-phase percentiles and SLO attainment are exact, not
  approximations over a shared reservoir.

The produced :class:`ReplayResult` is the input to ``obs.report``,
which joins phase annotations against the time series into the
scenario SLO report.
"""

from __future__ import annotations

import json
import math
import zlib
from dataclasses import asdict, dataclass, field, fields
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu.obs.exporters import SCHEMA_VERSION
from distkeras_tpu.obs.slo import Objective, SLOEngine
from distkeras_tpu.obs.timeseries import TimeSeries
from distkeras_tpu.resilience import faults
from distkeras_tpu.serving.metrics import ServingMetrics
from distkeras_tpu.serving.scheduler import AdmissionRejected

__all__ = ["ChaosSpec", "IterationClock", "PhaseSpec", "PhaseResult",
           "ReplayResult", "TenantSpec", "Trace", "TraceRequest",
           "WorkloadSpec", "diurnal_burst_scenario",
           "flash_crowd_chaos_scenario", "replay", "synthesize"]


# --- workload specification -------------------------------------------------


@dataclass(frozen=True)
class PhaseSpec:
    """One arrival-process phase, ``duration`` engine iterations long.

    ``rate`` is the mean arrivals per iteration at the phase's end;
    ``shape="flat"`` holds it constant (a step burst / flash crowd is
    just a short flat phase at a high rate), ``shape="ramp"``
    interpolates linearly from ``rate0`` to ``rate`` (a diurnal ramp
    up, or down when ``rate0 > rate``)."""

    name: str
    duration: int
    rate: float
    shape: str = "flat"
    rate0: float = 0.0

    def __post_init__(self):
        if self.duration < 1:
            raise ValueError(f"phase {self.name!r}: duration must be "
                             f">= 1, got {self.duration}")
        if self.shape not in ("flat", "ramp"):
            raise ValueError(f"phase {self.name!r}: shape must be "
                             f"'flat' or 'ramp', got {self.shape!r}")
        if self.rate < 0 or self.rate0 < 0:
            raise ValueError(f"phase {self.name!r}: rates must be >= 0")

    def rate_at(self, i: int) -> float:
        """Arrival rate at iteration ``i`` of the phase (0-based)."""
        if self.shape == "flat" or self.duration <= 1:
            return self.rate
        frac = i / (self.duration - 1)
        return self.rate0 + (self.rate - self.rate0) * frac


@dataclass(frozen=True)
class TenantSpec:
    """One tenant class in the mix: sampled by ``weight``, submitted at
    ``priority`` (the PriorityScheduler classes)."""

    name: str
    weight: float = 1.0
    priority: int = 1


@dataclass(frozen=True)
class ChaosSpec:
    """One phase-anchored fault script entry: arm a
    ``resilience.faults`` injection point when the replay's iteration
    cursor reaches ``at``, optionally disarm it at ``clear_at``.

    The trigger knobs mirror ``faults.inject`` — ``nth`` (fire on the
    N-th pass after arming; default 1 when no trigger is given),
    ``every`` (a sustained fault storm), ``prob`` + ``seed`` (seeded
    stochastic faults — still deterministic, the fault point keeps its
    own ``RandomState``), ``action`` (``"raise"``/``"stall"``/
    ``"nan"``), ``stall_s`` and ``transient``. Scripts serialize into
    the trace JSONL as additive ``"chaos"`` records, so a chaos
    scenario is a replayable artifact exactly like its traffic:
    same trace + same fleet = byte-identical outcome, twice."""

    point: str
    at: int
    clear_at: Optional[int] = None
    nth: Optional[int] = None
    every: Optional[int] = None
    prob: Optional[float] = None
    seed: int = 0
    action: Optional[str] = None     # faults.inject default: raise
    stall_s: Optional[float] = None
    transient: bool = False

    def __post_init__(self):
        if not self.point:
            raise ValueError("ChaosSpec needs an injection point name")
        if self.at < 0:
            raise ValueError(f"chaos {self.point!r}: at must be >= 0")
        if self.clear_at is not None and self.clear_at <= self.at:
            raise ValueError(
                f"chaos {self.point!r}: clear_at ({self.clear_at}) "
                f"must be > at ({self.at})")

    def inject_kwargs(self) -> Dict:
        """The ``faults.inject`` keyword set this entry arms (defaults
        to ``nth=1`` when no trigger knob is given)."""
        kw: Dict = {"seed": self.seed, "transient": self.transient}
        if self.action is not None:
            kw["action"] = self.action
        if self.stall_s is not None:
            kw["stall_s"] = self.stall_s
        if self.nth is not None:
            kw["nth"] = self.nth
        if self.every is not None:
            kw["every"] = self.every
        if self.prob is not None:
            kw["prob"] = self.prob
        if self.nth is None and self.every is None and self.prob is None:
            kw["nth"] = 1
        return kw


@dataclass(frozen=True)
class WorkloadSpec:
    """The full workload shape :func:`synthesize` expands.

    Lengths are heavy-tailed lognormals (median/sigma), clipped to
    ``[1, *_max]``; prompt lengths additionally round UP to multiples
    of ``length_quantum`` — production deployments bucket prompt
    lengths to bound prefill-program compiles (see
    ``ServingEngine.MAX_PREFILL_PROGRAMS``), and the generator models
    that. A ``template_frac`` fraction of prompts start with one of
    ``n_templates`` shared ``template_len``-token prefixes (the
    prefix-cache exercise); the rest are fully random.

    A ``sampled_frac`` fraction of requests decode stochastically
    (``temperature``/``top_p`` — the byte-identity acceptance for
    chaos scenarios needs sampled streams, greedy ones cannot expose a
    broken failover key replay); a ``deadline_frac`` fraction carry a
    ``deadline_iters``-iteration submit→finish budget (a deadline
    flood = a phase worth of arrivals with tight budgets). ``chaos``
    is the phase-anchored fault script (:class:`ChaosSpec`), carried
    into the trace and armed live by :func:`replay`."""

    vocab: int
    phases: Tuple[PhaseSpec, ...]
    prompt_median: float = 12.0
    prompt_sigma: float = 0.6
    prompt_max: int = 32
    output_median: float = 8.0
    output_sigma: float = 0.6
    output_max: int = 24
    length_quantum: int = 4
    n_templates: int = 4
    template_len: int = 8
    template_frac: float = 0.5
    tenants: Tuple[TenantSpec, ...] = (TenantSpec("standard"),)
    sampled_frac: float = 0.0
    temperature: float = 0.9
    top_p: float = 0.95
    deadline_frac: float = 0.0
    deadline_iters: int = 0
    chaos: Tuple[ChaosSpec, ...] = ()

    def __post_init__(self):
        if not 0.0 <= self.sampled_frac <= 1.0:
            raise ValueError("sampled_frac must be in [0, 1]")
        if not 0.0 <= self.deadline_frac <= 1.0:
            raise ValueError("deadline_frac must be in [0, 1]")
        if self.deadline_frac > 0 and self.deadline_iters < 1:
            raise ValueError(
                "deadline_frac > 0 needs deadline_iters >= 1")
        if self.vocab < 3:
            raise ValueError(f"vocab must be >= 3, got {self.vocab}")
        if not self.phases:
            raise ValueError("WorkloadSpec needs at least one phase")
        if self.length_quantum < 1:
            raise ValueError("length_quantum must be >= 1")
        if self.template_len >= self.prompt_max:
            raise ValueError(
                f"template_len ({self.template_len}) must be < "
                f"prompt_max ({self.prompt_max})")
        if not self.tenants:
            raise ValueError("WorkloadSpec needs at least one tenant")
        if not 0.0 <= self.template_frac <= 1.0:
            raise ValueError("template_frac must be in [0, 1]")

    @property
    def total_iterations(self) -> int:
        return sum(p.duration for p in self.phases)


# --- the trace --------------------------------------------------------------


@dataclass(frozen=True)
class TraceRequest:
    """One materialized request: everything replay needs, explicit.
    ``deadline`` is an ITERATION budget (converted to seconds with the
    replay's ``dt``); ``temperature``/``top_p`` make the stream
    stochastic (seeded per-request at replay — index = seed)."""

    arrival: int                  # engine iteration it becomes visible
    prompt: Tuple[int, ...]
    max_new_tokens: int
    tenant: str = "standard"
    priority: int = 1
    phase: str = ""
    template: Optional[int] = None
    deadline: Optional[int] = None
    temperature: float = 0.0
    top_p: float = 1.0


@dataclass(frozen=True)
class PhaseSpan:
    """Iteration span ``[start, end)`` a phase covered in the trace."""

    name: str
    start: int
    end: int


@dataclass(frozen=True)
class Trace:
    """A replayable workload: requests + phase spans + the chaos
    script + provenance. The chaos entries ride in the same JSONL
    artifact as the traffic (additive ``"chaos"`` record type), so a
    stored chaos scenario is one self-contained file."""

    requests: Tuple[TraceRequest, ...]
    phases: Tuple[PhaseSpan, ...]
    meta: Dict = field(default_factory=dict, compare=True)
    chaos: Tuple[ChaosSpec, ...] = ()

    def __len__(self) -> int:
        return len(self.requests)

    # -- JSONL round trip (exporter conventions) ---------------------

    def to_jsonl(self, path: str) -> None:
        """Typed JSONL lines: one ``meta`` header (carries
        ``schema_version`` + provenance), one ``phase`` line per span,
        one ``chaos`` line per fault-script entry, one ``request`` line
        per request. Additive record types under the exporter
        forward-compat contract."""
        with open(path, "w") as f:
            f.write(json.dumps(
                {"type": "meta", "seq": 0,
                 "schema_version": SCHEMA_VERSION,
                 "kind": "loadgen_trace", "n_requests": len(self.requests),
                 **self.meta}) + "\n")
            for p in self.phases:
                f.write(json.dumps(
                    {"type": "phase", "seq": 0, "name": p.name,
                     "start": p.start, "end": p.end}) + "\n")
            for c in self.chaos:
                f.write(json.dumps(
                    {"type": "chaos", "seq": 0, **asdict(c)}) + "\n")
            for i, r in enumerate(self.requests):
                rec = {"type": "request", "seq": 0, "i": i,
                       "arrival": r.arrival, "prompt": list(r.prompt),
                       "max_new_tokens": r.max_new_tokens,
                       "tenant": r.tenant, "priority": r.priority,
                       "phase": r.phase, "template": r.template}
                # additive keys, written only when non-default so old
                # traces byte-compare against re-serialized ones
                if r.deadline is not None:
                    rec["deadline"] = r.deadline
                if r.temperature:
                    rec["temperature"] = r.temperature
                    rec["top_p"] = r.top_p
                f.write(json.dumps(rec) + "\n")

    @classmethod
    def from_jsonl(cls, path: str) -> "Trace":
        """Inverse of :meth:`to_jsonl`; skips record types it does not
        know (the same forward-compat stance as
        ``exporters.read_jsonl``)."""
        meta: Dict = {}
        phases: List[PhaseSpan] = []
        chaos: List[ChaosSpec] = []
        reqs: List[Tuple[int, TraceRequest]] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                t = rec.get("type")
                if t == "meta" and rec.get("kind") == "loadgen_trace":
                    meta = {k: v for k, v in rec.items()
                            if k not in ("type", "seq", "schema_version",
                                         "kind", "n_requests")}
                elif t == "phase":
                    phases.append(PhaseSpan(rec["name"], rec["start"],
                                            rec["end"]))
                elif t == "chaos":
                    # unknown keys skipped: additive chaos-record
                    # fields must not break old readers
                    known = {f.name for f in fields(ChaosSpec)}
                    chaos.append(ChaosSpec(**{
                        k: v for k, v in rec.items() if k in known}))
                elif t == "request":
                    reqs.append((rec["i"], TraceRequest(
                        arrival=rec["arrival"],
                        prompt=tuple(rec["prompt"]),
                        max_new_tokens=rec["max_new_tokens"],
                        tenant=rec.get("tenant", "standard"),
                        priority=rec.get("priority", 1),
                        phase=rec.get("phase", ""),
                        template=rec.get("template"),
                        deadline=rec.get("deadline"),
                        temperature=rec.get("temperature", 0.0),
                        top_p=rec.get("top_p", 1.0))))
        reqs.sort(key=lambda p: p[0])
        return cls(requests=tuple(r for _, r in reqs),
                   phases=tuple(phases), meta=meta,
                   chaos=tuple(chaos))


def synthesize(spec: WorkloadSpec, seed: int = 0) -> Trace:
    """Expand a :class:`WorkloadSpec` into a :class:`Trace` — one
    ``numpy.random.RandomState(seed)`` drives every draw (arrival
    counts, lengths, tenant/template picks, token values), so the
    trace is bit-identical across hosts and runs."""
    rs = np.random.RandomState(seed)
    templates = [rs.randint(1, spec.vocab, size=spec.template_len)
                 .tolist() for _ in range(spec.n_templates)]
    weights = np.asarray([t.weight for t in spec.tenants], np.float64)
    cum = np.cumsum(weights / weights.sum())
    q = spec.length_quantum

    def _length(median: float, sigma: float, lo: int, hi: int,
                quantize: bool) -> int:
        n = int(np.round(rs.lognormal(mean=math.log(median),
                                      sigma=sigma)))
        if quantize:
            n = int(math.ceil(max(n, 1) / q) * q)
        return int(np.clip(n, lo, hi))

    requests: List[TraceRequest] = []
    phases: List[PhaseSpan] = []
    it0 = 0
    for ph in spec.phases:
        for i in range(ph.duration):
            for _ in range(int(rs.poisson(ph.rate_at(i)))):
                tenant = spec.tenants[int(np.searchsorted(
                    cum, rs.random_sample()))]
                tid = None
                total = _length(spec.prompt_median, spec.prompt_sigma,
                                q, spec.prompt_max, quantize=True)
                if spec.n_templates and rs.random_sample() \
                        < spec.template_frac:
                    tid = int(rs.randint(spec.n_templates))
                    if total <= spec.template_len:
                        total = min(spec.prompt_max,
                                    spec.template_len + q)
                    prompt = templates[tid] + rs.randint(
                        1, spec.vocab,
                        size=total - spec.template_len).tolist()
                else:
                    prompt = rs.randint(1, spec.vocab,
                                        size=total).tolist()
                out_len = _length(spec.output_median, spec.output_sigma,
                                  1, spec.output_max, quantize=False)
                # conditional draws: with the fractions at their 0.0
                # defaults the RandomState stream is untouched, so
                # pre-existing (spec, seed) pairs keep their traces
                temp, top_p = 0.0, 1.0
                if spec.sampled_frac > 0 and \
                        rs.random_sample() < spec.sampled_frac:
                    temp, top_p = spec.temperature, spec.top_p
                deadline = None
                if spec.deadline_frac > 0 and \
                        rs.random_sample() < spec.deadline_frac:
                    deadline = spec.deadline_iters
                requests.append(TraceRequest(
                    arrival=it0 + i, prompt=tuple(prompt),
                    max_new_tokens=out_len, tenant=tenant.name,
                    priority=tenant.priority, phase=ph.name,
                    template=tid, deadline=deadline,
                    temperature=temp, top_p=top_p))
        phases.append(PhaseSpan(ph.name, it0, it0 + ph.duration))
        it0 += ph.duration
    meta = {"seed": int(seed), "vocab": spec.vocab,
            "total_iterations": spec.total_iterations,
            "spec": {**asdict(spec),
                     "phases": [asdict(p) for p in spec.phases],
                     "tenants": [asdict(t) for t in spec.tenants],
                     "chaos": [asdict(c) for c in spec.chaos]}}
    return Trace(requests=tuple(requests), phases=tuple(phases),
                 meta=meta, chaos=tuple(sorted(
                     spec.chaos, key=lambda c: (c.at, c.point))))


def diurnal_burst_scenario(vocab: int, *, scale: float = 1.0,
                           prompt_max: int = 24, output_max: int = 12,
                           length_quantum: int = 8,
                           tenants: Optional[Sequence[TenantSpec]] = None
                           ) -> WorkloadSpec:
    """THE fixed reference scenario (bench + tests): a diurnal ramp to
    steady state, a 4x step burst, recovery, a short flash crowd, and
    a ramp-down — ~200 iterations end to end. ``scale`` multiplies
    every arrival rate (0.25 for quick tier-1 runs)."""
    s = float(scale)
    return WorkloadSpec(
        vocab=vocab,
        phases=(
            PhaseSpec("ramp_up", 40, rate=0.30 * s, shape="ramp",
                      rate0=0.02 * s),
            PhaseSpec("steady", 50, rate=0.30 * s),
            PhaseSpec("burst", 25, rate=1.20 * s),
            PhaseSpec("recovery", 40, rate=0.25 * s),
            PhaseSpec("flash", 10, rate=2.50 * s),
            PhaseSpec("cooldown", 40, rate=0.05 * s, shape="ramp",
                      rate0=0.25 * s),
        ),
        prompt_median=10.0, prompt_sigma=0.5, prompt_max=prompt_max,
        output_median=6.0, output_sigma=0.5, output_max=output_max,
        length_quantum=length_quantum,
        n_templates=3, template_len=min(8, prompt_max - length_quantum),
        template_frac=0.5,
        tenants=tuple(tenants) if tenants is not None else (
            TenantSpec("interactive", weight=3.0, priority=0),
            TenantSpec("standard", weight=6.0, priority=1),
            TenantSpec("batch", weight=1.0, priority=2)))


def flash_crowd_chaos_scenario(vocab: int, *, scale: float = 1.0,
                               prompt_max: int = 24, output_max: int = 12,
                               length_quantum: int = 8,
                               kill_at: Optional[int] = None,
                               sampled_frac: float = 0.5
                               ) -> WorkloadSpec:
    """THE fixed chaos reference scenario (``bench.py --model
    autoscale`` + tier-1): warm-up to steady state, a flash crowd with
    a scripted ``replica.die`` mid-crowd (``kill_at`` defaults to the
    crowd's first third), then recovery and cooldown — the overload
    and the capacity loss land TOGETHER, which is exactly when an
    autoscaler must not flap. Half the streams sample stochastically
    so failover byte-identity is actually exercised."""
    s = float(scale)
    warm, steady, crowd = 30, 30, 30
    if kill_at is None:
        kill_at = warm + steady + crowd // 3
    return WorkloadSpec(
        vocab=vocab,
        phases=(
            PhaseSpec("warmup", warm, rate=0.20 * s, shape="ramp",
                      rate0=0.02 * s),
            PhaseSpec("steady", steady, rate=0.25 * s),
            PhaseSpec("flash", crowd, rate=2.00 * s),
            PhaseSpec("recovery", 40, rate=0.20 * s),
            PhaseSpec("cooldown", 30, rate=0.04 * s, shape="ramp",
                      rate0=0.20 * s),
        ),
        prompt_median=10.0, prompt_sigma=0.5, prompt_max=prompt_max,
        output_median=6.0, output_sigma=0.5, output_max=output_max,
        length_quantum=length_quantum,
        n_templates=2, template_len=min(8, prompt_max - length_quantum),
        template_frac=0.5, sampled_frac=sampled_frac,
        tenants=(TenantSpec("interactive", weight=3.0, priority=0),
                 TenantSpec("standard", weight=6.0, priority=1)),
        chaos=(ChaosSpec("replica.die", at=int(kill_at)),))


# --- deterministic replay ---------------------------------------------------


class IterationClock:
    """A virtual clock ticking ``dt`` seconds per engine iteration.
    Installed as the metrics/SLO/time-series clock during replay, it
    makes every recorded timestamp, latency and rate a pure function
    of iteration count — deterministic on any host, no sleeps."""

    def __init__(self, dt: float = 1e-3, t0: float = 0.0):
        if dt <= 0:
            raise ValueError(f"dt must be > 0, got {dt}")
        self.dt = float(dt)
        self._t = float(t0)

    def __call__(self) -> float:
        return self._t

    def advance(self, n: int = 1) -> float:
        self._t += n * self.dt
        return self._t


@dataclass
class PhaseResult:
    """One phase's outcome: per-engine metrics-window summaries and
    SLO statuses (single-engine replays are a fleet of one), plus the
    submit/shed counts of arrivals that fell inside the phase."""

    name: str
    start: int                    # iteration span [start, end)
    end: int
    t0: float                     # virtual-clock span
    t1: float
    submitted: int = 0
    shed: int = 0
    summaries: Dict[str, Dict] = field(default_factory=dict)
    slo: Dict[str, Dict] = field(default_factory=dict)


@dataclass
class ReplayResult:
    """Everything :func:`obs.report.build_report` joins: the trace,
    per-phase results, per-request outcomes, and the live handles
    (time series per engine, SLO engines) for timeline slicing."""

    trace: Trace
    phases: List[PhaseResult]
    outcomes: List[Dict]
    iterations: int
    dt: float
    fleet: bool
    engine_ids: List[str]
    timeseries: Dict[str, TimeSeries]
    slo: Dict[str, Optional[SLOEngine]]
    #: chaos triggers observed live: {"t", "iteration", "point"} per
    #: firing (the recovery report's incident anchors)
    incidents: List[Dict] = field(default_factory=list)
    #: fleet-size census at t=0 and after every fleet mutation:
    #: {"t", "iteration", "total", "serving", ...} (router targets)
    fleet_timeline: List[Dict] = field(default_factory=list)
    #: autoscale decisions stamped with virtual time as they appeared
    autoscale_events: List[Dict] = field(default_factory=list)

    @property
    def totals(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for o in self.outcomes:
            counts[o["state"]] = counts.get(o["state"], 0) + 1
        counts["total"] = len(self.outcomes)
        return counts


def _token_crc(tokens) -> int:
    """Cheap deterministic fingerprint of a request's full token
    sequence — two replays are token-identical iff these match."""
    return zlib.crc32(np.ascontiguousarray(
        np.asarray(tokens, np.int64)).tobytes())


def replay(trace: Trace, target, *,
           objectives: Optional[Sequence[Objective]] = None,
           dt: float = 1e-3, max_steps: Optional[int] = None,
           timeseries_capacity: int = 2048) -> ReplayResult:
    """Drive ``trace`` open-loop through ``target`` (a ``ServingEngine``
    or a ``Router`` fleet) on a virtual iteration clock.

    Per engine, the replay installs: a fresh ``ServingMetrics`` window
    on the shared :class:`IterationClock` (swapped again at every
    phase boundary, draining the pipeline first — per-phase windows),
    a clock-matched ``TimeSeries`` scraper following the live window,
    and — when ``objectives`` is given — a per-engine ``SLOEngine``
    evaluated by the engine's own step cadence plus once at each phase
    boundary (router replays: the per-objective registry gauges
    collide across replicas, but each engine's burn-history ring stays
    separate, and that ring is what the report reads).

    Arrivals submit when the iteration clock reaches their trace
    iteration; an ``AdmissionRejected`` records the request as shed.
    Idle gaps fast-forward (no empty stepping — but never past a
    scripted chaos iteration). After the last phase the fleet drains,
    reported as the synthetic ``(drain)`` phase.

    Chaos scenarios: the trace's :class:`ChaosSpec` entries arm their
    ``resilience.faults`` points when the iteration cursor reaches
    ``at`` (disarmed at ``clear_at`` / on exit), every trigger firing
    is recorded as an incident ``{"t", "iteration", "point"}``, and —
    fleet targets — the replay follows mutations the fleet makes to
    itself: replicas an ``AutoscaleController`` adds mid-replay are
    put on the same virtual clock the seed fleet records on, dead
    replicas stop being flushed, the fleet-size census lands in
    ``fleet_timeline`` and controller decisions in
    ``autoscale_events``. Everything is anchored to the iteration
    cursor, so a chaos scenario replays byte-identically twice."""
    fleet = hasattr(target, "replicas")
    # report keys must be identical across two replays of the same
    # scenario, but the obs component registry appends an object-id
    # disambiguator to reused names ("serving[0x..]", "r0#0x.."). Strip
    # it — unless that would collide within THIS run, in which case the
    # unique (nondeterministic) form is the lesser evil.
    def _stable(name: str) -> str:
        return name.split("[", 1)[0].split("#", 1)[0]

    clock = IterationClock(dt)
    engines: Dict[str, "object"] = {}
    tseries: Dict[str, TimeSeries] = {}
    slos: Dict[str, Optional[SLOEngine]] = {}
    known_ids: set = set()

    def _install(eid: str, eng) -> None:
        """Put one engine on the virtual clock: fresh metrics window,
        clock-matched scraper, per-engine SLO engine. Also runs for
        replicas a controller adds MID-replay, so an autoscaled-up
        engine records on the same deterministic clock as the seed
        fleet."""
        eng.metrics = ServingMetrics(clock=clock)
        ts = TimeSeries(
            (lambda e=eng: e.metrics.registry),
            capacity=timeseries_capacity, clock=clock,
            tags={"engine": eid})
        eng.timeseries = ts
        tseries[eid] = ts
        slo = (SLOEngine(list(objectives), clock=clock)
               if objectives else None)
        eng.slo = slo
        slos[eid] = slo
        engines[eid] = eng
        known_ids.add(id(eng))

    pairs = ([(r.name, r.engine) for r in target.replicas] if fleet
             else [(target.engine_id, target)])
    for name, eng in pairs:
        key = _stable(name)
        _install(name if key in engines else key, eng)

    def _busy() -> bool:
        if fleet:
            return target.pending
        if target.scheduler.pending or target._finish_buf:
            return True
        if target._pending is not None:
            # dangling pipelined step: it was launched before the
            # flush that finished the batch's last request, so every
            # stream it covers has retired and step() (which only
            # consumes in-flight work from the decode path) would spin
            # forever. Consume it directly — run()'s drain loop does
            # exactly this; a retired-covered step drops wholesale,
            # anything live lands in _finish_buf
            target._flush_pending()
            return bool(target._finish_buf)
        return False

    reqs = sorted(enumerate(trace.requests), key=lambda p: p[1].arrival)
    outcomes: List[Dict] = [
        {"i": i, "phase": r.phase, "tenant": r.tenant,
         "state": "unsubmitted", "n_tokens": 0}
        for i, r in sorted(
            ((i, r) for i, r in enumerate(trace.requests)))]
    rid_to_idx: Dict[int, int] = {}

    def _submit(idx: int, tr: TraceRequest) -> None:
        prompt = np.asarray(tr.prompt, np.int32)
        kw: Dict = {}
        if tr.deadline is not None:
            # iteration budget -> virtual seconds; the router carries
            # the REMAINING budget across any mid-flight moves
            kw["deadline_s"] = tr.deadline * dt
        if tr.temperature:
            kw["temperature"] = tr.temperature
            kw["top_p"] = tr.top_p
        try:
            rid = target.submit(prompt, tr.max_new_tokens,
                                priority=tr.priority, seed=idx, **kw)
        except AdmissionRejected:
            outcomes[idx]["state"] = "shed"
            return
        rid_to_idx[rid] = idx
        outcomes[idx]["state"] = "submitted"

    def _consume(terminals) -> None:
        items = (terminals.items() if isinstance(terminals, dict)
                 else ((r.rid, r) for r in terminals))
        for rid, req in items:
            idx = rid_to_idx.pop(rid, None)
            if idx is None:
                continue
            o = outcomes[idx]
            o["state"] = req.state.name.lower()
            o["n_tokens"] = len(req.generated)
            o["tokens_crc"] = _token_crc(req.tokens)
            o["failovers"] = getattr(req, "n_failovers", 0)
            o["handoffs"] = getattr(req, "n_handoffs", 0)

    def _close_phase(name: str, start: int, end: int,
                     t0: float, submitted_slice) -> PhaseResult:
        res = PhaseResult(name=name, start=start, end=end,
                          t0=t0, t1=clock())
        for eid, eng in engines.items():
            if id(eng) not in dead_ids:
                # a chaos-killed engine is never flushed (its pipeline
                # died mid-step); its last-scraped window still
                # summarizes below
                eng._flush_pending()
                eng._flush_host_window()
            if eng.timeseries is not None:
                eng.timeseries.sample(iteration=end)
            win = eng.metrics
            if slos[eid] is not None:
                res.slo[eid] = slos[eid].evaluate(win)
            res.summaries[eid] = win.summary()
            # fresh per-phase window; tell the scraper its counter
            # baselines are void (the reset clamp alone cannot detect a
            # swap whose new values coincidentally match the old ones)
            eng.metrics = ServingMetrics(clock=clock)
            if eng.timeseries is not None:
                eng.timeseries.reset_baseline()
        for o in submitted_slice:
            if o["state"] == "shed":
                res.shed += 1
            else:
                res.submitted += 1
        return res

    # -- chaos script + recovery bookkeeping -----------------------------
    if fleet:
        from distkeras_tpu.serving.router.replica import ReplicaState
    dead_ids: set = set()
    incidents: List[Dict] = []
    fleet_timeline: List[Dict] = []
    autoscale_events: List[Dict] = []
    chaos = sorted(trace.chaos, key=lambda c: (c.at, c.point))
    armed: List[ChaosSpec] = []
    pending_clears: List[ChaosSpec] = []
    chaos_i = 0
    cur_it = [0]                    # listener needs the live cursor

    def _on_trigger(point: str) -> None:
        incidents.append({"t": clock(), "iteration": cur_it[0],
                          "point": point})

    def _chaos_tick(i: int) -> None:
        """Arm every script entry whose iteration has arrived; disarm
        expired storms. Arming is anchored to the ITERATION CURSOR —
        pure virtual time — so two replays arm identically."""
        nonlocal chaos_i
        while chaos_i < len(chaos) and chaos[chaos_i].at <= i:
            c = chaos[chaos_i]
            faults.inject(c.point, **c.inject_kwargs())
            armed.append(c)
            if c.clear_at is not None:
                pending_clears.append(c)
            chaos_i += 1
        for c in list(pending_clears):
            if c.clear_at <= i:
                faults.clear(c.point)
                pending_clears.remove(c)

    def _next_chaos_event(after: int) -> Optional[int]:
        cands = ([chaos[chaos_i].at] if chaos_i < len(chaos) else []) \
            + [c.clear_at for c in pending_clears]
        return min((x for x in cands if x > after), default=None)

    def _find_decisions(t):
        ctl = getattr(t, "controller", None)
        if ctl is None:
            return None
        if hasattr(ctl, "decisions"):
            return ctl.decisions
        for c in getattr(ctl, "controllers", ()):
            if hasattr(c, "decisions"):
                return c.decisions
        return None

    ctl_decisions = _find_decisions(target) if fleet else None
    decisions_seen = len(ctl_decisions) if ctl_decisions else 0
    fleet_ver = [getattr(target, "_fleet_version", 0)] if fleet else [0]
    if fleet:
        fleet_timeline.append({"t": clock(), "iteration": 0,
                               **target.fleet_counts()})

    def _post_step(i: int) -> None:
        """After every fleet step: mark newly-dead engines (they are
        never flushed again), install virtual-clock instrumentation on
        replicas a controller just added, extend the fleet-size
        timeline, and timestamp fresh autoscale decisions."""
        nonlocal decisions_seen
        if not fleet:
            return
        for r in target.replicas:
            if r.state is ReplicaState.DEAD:
                dead_ids.add(id(r.engine))
        if target._fleet_version != fleet_ver[0]:
            fleet_ver[0] = target._fleet_version
            for r in target.replicas:
                if id(r.engine) in known_ids:
                    continue
                key = _stable(r.name)
                _install(r.name if key in engines else key, r.engine)
            fleet_timeline.append({"t": clock(), "iteration": i,
                                   **target.fleet_counts()})
        if ctl_decisions is not None:
            while decisions_seen < len(ctl_decisions):
                d = dict(ctl_decisions[decisions_seen])
                d["t"] = clock()
                d["iteration"] = i
                autoscale_events.append(d)
                decisions_seen += 1

    phase_results: List[PhaseResult] = []
    next_i = 0                      # cursor into arrival-sorted reqs
    it = 0
    budget = (max_steps if max_steps is not None
              else trace.meta.get("total_iterations", 0) * 50 + 20000)
    steps = 0
    faults.add_trigger_listener(_on_trigger)
    try:
        for span in trace.phases:
            t0 = clock()
            lo_i = next_i
            while it < span.end:
                cur_it[0] = it
                _chaos_tick(it)
                while next_i < len(reqs) and \
                        reqs[next_i][1].arrival <= it:
                    idx, tr = reqs[next_i]
                    _submit(idx, tr)
                    next_i += 1
                if _busy():
                    _consume(target.step())
                    _post_step(it)
                    steps += 1
                    if steps > budget:
                        raise RuntimeError(
                            f"replay exceeded {budget} steps (phase "
                            f"{span.name!r}, iteration {it}) — engine "
                            "not draining?")
                    clock.advance()
                    it += 1
                else:
                    # idle fast-forward to the next arrival, chaos
                    # event or phase end — a jump must never skip a
                    # scripted arming iteration
                    nxt = (reqs[next_i][1].arrival
                           if next_i < len(reqs) else span.end)
                    ce = _next_chaos_event(it)
                    if ce is not None:
                        nxt = min(nxt, ce)
                    jump = max(1, min(nxt, span.end) - it)
                    clock.advance(jump)
                    it += jump
            phase_results.append(_close_phase(
                span.name, span.start, span.end, t0,
                [outcomes[i] for i, _ in reqs[lo_i:next_i]]))
        # drain tail: everything still in flight finishes here
        t0 = clock()
        start = it
        while _busy():
            cur_it[0] = it
            _chaos_tick(it)
            _consume(target.step())
            _post_step(it)
            steps += 1
            if steps > budget:
                raise RuntimeError(
                    f"replay drain exceeded {budget} steps — engine "
                    "not draining?")
            clock.advance()
            it += 1
        if it > start or any(o["state"] == "submitted"
                             for o in outcomes):
            phase_results.append(
                _close_phase("(drain)", start, it, t0, []))
    finally:
        # leave no script entry armed past the replay (the process
        # global fault table outlives this function)
        for c in armed:
            faults.clear(c.point)
        faults.remove_trigger_listener(_on_trigger)
    return ReplayResult(
        trace=trace, phases=phase_results, outcomes=outcomes,
        iterations=it, dt=dt, fleet=fleet,
        engine_ids=list(engines), timeseries=tseries, slo=slos,
        incidents=incidents, fleet_timeline=fleet_timeline,
        autoscale_events=autoscale_events)
