"""Production-shaped workload generator + deterministic replayer.

``bench.py``'s hand-rolled open-loop traces exercise one arrival
process (exponential inter-arrivals at a fixed rate) with fixed-length
prompts — nothing like production traffic, whose defining features are
exactly what stress a serving fleet: *phased* load (diurnal ramps, step
bursts, flash crowds), *heavy-tailed* prompt/output lengths, and
*structured* prompt populations (shared templates that exercise the
prefix cache, tenants with different priorities). This module
synthesizes such traffic as a replayable artifact and drives it through
an engine or a router fleet deterministically:

* :func:`synthesize` expands a :class:`WorkloadSpec` (phases + length
  distributions + template/tenant mixes) into a :class:`Trace` — every
  request materialized with explicit arrival iteration, prompt tokens,
  output budget, tenant and phase tag — from one numpy seed. Same spec
  + same seed = bit-identical trace, on any host.
* ``Trace.to_jsonl`` / ``Trace.from_jsonl`` round-trip the trace
  through the ``obs.exporters`` JSONL conventions (typed lines under
  the ``SCHEMA_VERSION`` forward-compat contract: the new ``"phase"``
  and ``"request"`` record types are additive — old readers skip
  them, no version bump).
* :func:`replay` drives the trace open-loop on the **engine's own
  iteration clock**: arrivals are indexed by iteration, not wall time,
  and an :class:`IterationClock` (``t = iteration * dt``) is installed
  as the metrics/SLO/time-series clock — no sleeps, no wall-clock
  reads in any recorded number, so a CPU tier-1 test can assert two
  replays produce *identical* per-phase report numbers. Each trace
  phase gets its own ``ServingMetrics`` window (swapped at the
  boundary — the engine drains its pipeline into the old window
  first), so per-phase percentiles and SLO attainment are exact, not
  approximations over a shared reservoir.

The produced :class:`ReplayResult` is the input to ``obs.report``,
which joins phase annotations against the time series into the
scenario SLO report.
"""

from __future__ import annotations

import json
import math
import zlib
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu.obs.exporters import SCHEMA_VERSION
from distkeras_tpu.obs.slo import Objective, SLOEngine
from distkeras_tpu.obs.timeseries import TimeSeries
from distkeras_tpu.serving.metrics import ServingMetrics
from distkeras_tpu.serving.scheduler import AdmissionRejected

__all__ = ["IterationClock", "PhaseSpec", "PhaseResult", "ReplayResult",
           "TenantSpec", "Trace", "TraceRequest", "WorkloadSpec",
           "diurnal_burst_scenario", "replay", "synthesize"]


# --- workload specification -------------------------------------------------


@dataclass(frozen=True)
class PhaseSpec:
    """One arrival-process phase, ``duration`` engine iterations long.

    ``rate`` is the mean arrivals per iteration at the phase's end;
    ``shape="flat"`` holds it constant (a step burst / flash crowd is
    just a short flat phase at a high rate), ``shape="ramp"``
    interpolates linearly from ``rate0`` to ``rate`` (a diurnal ramp
    up, or down when ``rate0 > rate``)."""

    name: str
    duration: int
    rate: float
    shape: str = "flat"
    rate0: float = 0.0

    def __post_init__(self):
        if self.duration < 1:
            raise ValueError(f"phase {self.name!r}: duration must be "
                             f">= 1, got {self.duration}")
        if self.shape not in ("flat", "ramp"):
            raise ValueError(f"phase {self.name!r}: shape must be "
                             f"'flat' or 'ramp', got {self.shape!r}")
        if self.rate < 0 or self.rate0 < 0:
            raise ValueError(f"phase {self.name!r}: rates must be >= 0")

    def rate_at(self, i: int) -> float:
        """Arrival rate at iteration ``i`` of the phase (0-based)."""
        if self.shape == "flat" or self.duration <= 1:
            return self.rate
        frac = i / (self.duration - 1)
        return self.rate0 + (self.rate - self.rate0) * frac


@dataclass(frozen=True)
class TenantSpec:
    """One tenant class in the mix: sampled by ``weight``, submitted at
    ``priority`` (the PriorityScheduler classes)."""

    name: str
    weight: float = 1.0
    priority: int = 1


@dataclass(frozen=True)
class WorkloadSpec:
    """The full workload shape :func:`synthesize` expands.

    Lengths are heavy-tailed lognormals (median/sigma), clipped to
    ``[1, *_max]``; prompt lengths additionally round UP to multiples
    of ``length_quantum`` — production deployments bucket prompt
    lengths to bound prefill-program compiles (see
    ``ServingEngine.MAX_PREFILL_PROGRAMS``), and the generator models
    that. A ``template_frac`` fraction of prompts start with one of
    ``n_templates`` shared ``template_len``-token prefixes (the
    prefix-cache exercise); the rest are fully random."""

    vocab: int
    phases: Tuple[PhaseSpec, ...]
    prompt_median: float = 12.0
    prompt_sigma: float = 0.6
    prompt_max: int = 32
    output_median: float = 8.0
    output_sigma: float = 0.6
    output_max: int = 24
    length_quantum: int = 4
    n_templates: int = 4
    template_len: int = 8
    template_frac: float = 0.5
    tenants: Tuple[TenantSpec, ...] = (TenantSpec("standard"),)

    def __post_init__(self):
        if self.vocab < 3:
            raise ValueError(f"vocab must be >= 3, got {self.vocab}")
        if not self.phases:
            raise ValueError("WorkloadSpec needs at least one phase")
        if self.length_quantum < 1:
            raise ValueError("length_quantum must be >= 1")
        if self.template_len >= self.prompt_max:
            raise ValueError(
                f"template_len ({self.template_len}) must be < "
                f"prompt_max ({self.prompt_max})")
        if not self.tenants:
            raise ValueError("WorkloadSpec needs at least one tenant")
        if not 0.0 <= self.template_frac <= 1.0:
            raise ValueError("template_frac must be in [0, 1]")

    @property
    def total_iterations(self) -> int:
        return sum(p.duration for p in self.phases)


# --- the trace --------------------------------------------------------------


@dataclass(frozen=True)
class TraceRequest:
    """One materialized request: everything replay needs, explicit."""

    arrival: int                  # engine iteration it becomes visible
    prompt: Tuple[int, ...]
    max_new_tokens: int
    tenant: str = "standard"
    priority: int = 1
    phase: str = ""
    template: Optional[int] = None


@dataclass(frozen=True)
class PhaseSpan:
    """Iteration span ``[start, end)`` a phase covered in the trace."""

    name: str
    start: int
    end: int


@dataclass(frozen=True)
class Trace:
    """A replayable workload: requests + phase spans + provenance."""

    requests: Tuple[TraceRequest, ...]
    phases: Tuple[PhaseSpan, ...]
    meta: Dict = field(default_factory=dict, compare=True)

    def __len__(self) -> int:
        return len(self.requests)

    # -- JSONL round trip (exporter conventions) ---------------------

    def to_jsonl(self, path: str) -> None:
        """Typed JSONL lines: one ``meta`` header (carries
        ``schema_version`` + provenance), one ``phase`` line per span,
        one ``request`` line per request. Additive record types under
        the exporter forward-compat contract."""
        with open(path, "w") as f:
            f.write(json.dumps(
                {"type": "meta", "seq": 0,
                 "schema_version": SCHEMA_VERSION,
                 "kind": "loadgen_trace", "n_requests": len(self.requests),
                 **self.meta}) + "\n")
            for p in self.phases:
                f.write(json.dumps(
                    {"type": "phase", "seq": 0, "name": p.name,
                     "start": p.start, "end": p.end}) + "\n")
            for i, r in enumerate(self.requests):
                f.write(json.dumps(
                    {"type": "request", "seq": 0, "i": i,
                     "arrival": r.arrival, "prompt": list(r.prompt),
                     "max_new_tokens": r.max_new_tokens,
                     "tenant": r.tenant, "priority": r.priority,
                     "phase": r.phase, "template": r.template}) + "\n")

    @classmethod
    def from_jsonl(cls, path: str) -> "Trace":
        """Inverse of :meth:`to_jsonl`; skips record types it does not
        know (the same forward-compat stance as
        ``exporters.read_jsonl``)."""
        meta: Dict = {}
        phases: List[PhaseSpan] = []
        reqs: List[Tuple[int, TraceRequest]] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                t = rec.get("type")
                if t == "meta" and rec.get("kind") == "loadgen_trace":
                    meta = {k: v for k, v in rec.items()
                            if k not in ("type", "seq", "schema_version",
                                         "kind", "n_requests")}
                elif t == "phase":
                    phases.append(PhaseSpan(rec["name"], rec["start"],
                                            rec["end"]))
                elif t == "request":
                    reqs.append((rec["i"], TraceRequest(
                        arrival=rec["arrival"],
                        prompt=tuple(rec["prompt"]),
                        max_new_tokens=rec["max_new_tokens"],
                        tenant=rec.get("tenant", "standard"),
                        priority=rec.get("priority", 1),
                        phase=rec.get("phase", ""),
                        template=rec.get("template"))))
        reqs.sort(key=lambda p: p[0])
        return cls(requests=tuple(r for _, r in reqs),
                   phases=tuple(phases), meta=meta)


def synthesize(spec: WorkloadSpec, seed: int = 0) -> Trace:
    """Expand a :class:`WorkloadSpec` into a :class:`Trace` — one
    ``numpy.random.RandomState(seed)`` drives every draw (arrival
    counts, lengths, tenant/template picks, token values), so the
    trace is bit-identical across hosts and runs."""
    rs = np.random.RandomState(seed)
    templates = [rs.randint(1, spec.vocab, size=spec.template_len)
                 .tolist() for _ in range(spec.n_templates)]
    weights = np.asarray([t.weight for t in spec.tenants], np.float64)
    cum = np.cumsum(weights / weights.sum())
    q = spec.length_quantum

    def _length(median: float, sigma: float, lo: int, hi: int,
                quantize: bool) -> int:
        n = int(np.round(rs.lognormal(mean=math.log(median),
                                      sigma=sigma)))
        if quantize:
            n = int(math.ceil(max(n, 1) / q) * q)
        return int(np.clip(n, lo, hi))

    requests: List[TraceRequest] = []
    phases: List[PhaseSpan] = []
    it0 = 0
    for ph in spec.phases:
        for i in range(ph.duration):
            for _ in range(int(rs.poisson(ph.rate_at(i)))):
                tenant = spec.tenants[int(np.searchsorted(
                    cum, rs.random_sample()))]
                tid = None
                total = _length(spec.prompt_median, spec.prompt_sigma,
                                q, spec.prompt_max, quantize=True)
                if spec.n_templates and rs.random_sample() \
                        < spec.template_frac:
                    tid = int(rs.randint(spec.n_templates))
                    if total <= spec.template_len:
                        total = min(spec.prompt_max,
                                    spec.template_len + q)
                    prompt = templates[tid] + rs.randint(
                        1, spec.vocab,
                        size=total - spec.template_len).tolist()
                else:
                    prompt = rs.randint(1, spec.vocab,
                                        size=total).tolist()
                out_len = _length(spec.output_median, spec.output_sigma,
                                  1, spec.output_max, quantize=False)
                requests.append(TraceRequest(
                    arrival=it0 + i, prompt=tuple(prompt),
                    max_new_tokens=out_len, tenant=tenant.name,
                    priority=tenant.priority, phase=ph.name,
                    template=tid))
        phases.append(PhaseSpan(ph.name, it0, it0 + ph.duration))
        it0 += ph.duration
    meta = {"seed": int(seed), "vocab": spec.vocab,
            "total_iterations": spec.total_iterations,
            "spec": {**asdict(spec),
                     "phases": [asdict(p) for p in spec.phases],
                     "tenants": [asdict(t) for t in spec.tenants]}}
    return Trace(requests=tuple(requests), phases=tuple(phases),
                 meta=meta)


def diurnal_burst_scenario(vocab: int, *, scale: float = 1.0,
                           prompt_max: int = 24, output_max: int = 12,
                           length_quantum: int = 8,
                           tenants: Optional[Sequence[TenantSpec]] = None
                           ) -> WorkloadSpec:
    """THE fixed reference scenario (bench + tests): a diurnal ramp to
    steady state, a 4x step burst, recovery, a short flash crowd, and
    a ramp-down — ~200 iterations end to end. ``scale`` multiplies
    every arrival rate (0.25 for quick tier-1 runs)."""
    s = float(scale)
    return WorkloadSpec(
        vocab=vocab,
        phases=(
            PhaseSpec("ramp_up", 40, rate=0.30 * s, shape="ramp",
                      rate0=0.02 * s),
            PhaseSpec("steady", 50, rate=0.30 * s),
            PhaseSpec("burst", 25, rate=1.20 * s),
            PhaseSpec("recovery", 40, rate=0.25 * s),
            PhaseSpec("flash", 10, rate=2.50 * s),
            PhaseSpec("cooldown", 40, rate=0.05 * s, shape="ramp",
                      rate0=0.25 * s),
        ),
        prompt_median=10.0, prompt_sigma=0.5, prompt_max=prompt_max,
        output_median=6.0, output_sigma=0.5, output_max=output_max,
        length_quantum=length_quantum,
        n_templates=3, template_len=min(8, prompt_max - length_quantum),
        template_frac=0.5,
        tenants=tuple(tenants) if tenants is not None else (
            TenantSpec("interactive", weight=3.0, priority=0),
            TenantSpec("standard", weight=6.0, priority=1),
            TenantSpec("batch", weight=1.0, priority=2)))


# --- deterministic replay ---------------------------------------------------


class IterationClock:
    """A virtual clock ticking ``dt`` seconds per engine iteration.
    Installed as the metrics/SLO/time-series clock during replay, it
    makes every recorded timestamp, latency and rate a pure function
    of iteration count — deterministic on any host, no sleeps."""

    def __init__(self, dt: float = 1e-3, t0: float = 0.0):
        if dt <= 0:
            raise ValueError(f"dt must be > 0, got {dt}")
        self.dt = float(dt)
        self._t = float(t0)

    def __call__(self) -> float:
        return self._t

    def advance(self, n: int = 1) -> float:
        self._t += n * self.dt
        return self._t


@dataclass
class PhaseResult:
    """One phase's outcome: per-engine metrics-window summaries and
    SLO statuses (single-engine replays are a fleet of one), plus the
    submit/shed counts of arrivals that fell inside the phase."""

    name: str
    start: int                    # iteration span [start, end)
    end: int
    t0: float                     # virtual-clock span
    t1: float
    submitted: int = 0
    shed: int = 0
    summaries: Dict[str, Dict] = field(default_factory=dict)
    slo: Dict[str, Dict] = field(default_factory=dict)


@dataclass
class ReplayResult:
    """Everything :func:`obs.report.build_report` joins: the trace,
    per-phase results, per-request outcomes, and the live handles
    (time series per engine, SLO engines) for timeline slicing."""

    trace: Trace
    phases: List[PhaseResult]
    outcomes: List[Dict]
    iterations: int
    dt: float
    fleet: bool
    engine_ids: List[str]
    timeseries: Dict[str, TimeSeries]
    slo: Dict[str, Optional[SLOEngine]]

    @property
    def totals(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for o in self.outcomes:
            counts[o["state"]] = counts.get(o["state"], 0) + 1
        counts["total"] = len(self.outcomes)
        return counts


def _token_crc(tokens) -> int:
    """Cheap deterministic fingerprint of a request's full token
    sequence — two replays are token-identical iff these match."""
    return zlib.crc32(np.ascontiguousarray(
        np.asarray(tokens, np.int64)).tobytes())


def replay(trace: Trace, target, *,
           objectives: Optional[Sequence[Objective]] = None,
           dt: float = 1e-3, max_steps: Optional[int] = None,
           timeseries_capacity: int = 2048) -> ReplayResult:
    """Drive ``trace`` open-loop through ``target`` (a ``ServingEngine``
    or a ``Router`` fleet) on a virtual iteration clock.

    Per engine, the replay installs: a fresh ``ServingMetrics`` window
    on the shared :class:`IterationClock` (swapped again at every
    phase boundary, draining the pipeline first — per-phase windows),
    a clock-matched ``TimeSeries`` scraper following the live window,
    and — when ``objectives`` is given — a per-engine ``SLOEngine``
    evaluated by the engine's own step cadence plus once at each phase
    boundary (router replays: the per-objective registry gauges
    collide across replicas, but each engine's burn-history ring stays
    separate, and that ring is what the report reads).

    Arrivals submit when the iteration clock reaches their trace
    iteration; an ``AdmissionRejected`` records the request as shed.
    Idle gaps fast-forward (no empty stepping). After the last phase
    the fleet drains, reported as the synthetic ``(drain)`` phase."""
    fleet = hasattr(target, "replicas")
    # report keys must be identical across two replays of the same
    # scenario, but the obs component registry appends an object-id
    # disambiguator to reused names ("serving[0x..]", "r0#0x.."). Strip
    # it — unless that would collide within THIS run, in which case the
    # unique (nondeterministic) form is the lesser evil.
    def _stable(name: str) -> str:
        return name.split("[", 1)[0].split("#", 1)[0]

    engines: Dict[str, "object"] = {}
    pairs = ([(r.name, r.engine) for r in target.replicas] if fleet
             else [(target.engine_id, target)])
    for name, eng in pairs:
        key = _stable(name)
        engines[name if key in engines else key] = eng
    clock = IterationClock(dt)
    tseries: Dict[str, TimeSeries] = {}
    slos: Dict[str, Optional[SLOEngine]] = {}
    for eid, eng in engines.items():
        eng.metrics = ServingMetrics(clock=clock)
        ts = TimeSeries(
            (lambda e=eng: e.metrics.registry),
            capacity=timeseries_capacity, clock=clock,
            tags={"engine": eid})
        eng.timeseries = ts
        tseries[eid] = ts
        slo = (SLOEngine(list(objectives), clock=clock)
               if objectives else None)
        eng.slo = slo
        slos[eid] = slo

    def _busy() -> bool:
        if fleet:
            return target.pending
        if target.scheduler.pending or target._finish_buf:
            return True
        if target._pending is not None:
            # dangling pipelined step: it was launched before the
            # flush that finished the batch's last request, so every
            # stream it covers has retired and step() (which only
            # consumes in-flight work from the decode path) would spin
            # forever. Consume it directly — run()'s drain loop does
            # exactly this; a retired-covered step drops wholesale,
            # anything live lands in _finish_buf
            target._flush_pending()
            return bool(target._finish_buf)
        return False

    reqs = sorted(enumerate(trace.requests), key=lambda p: p[1].arrival)
    outcomes: List[Dict] = [
        {"i": i, "phase": r.phase, "tenant": r.tenant,
         "state": "unsubmitted", "n_tokens": 0}
        for i, r in sorted(
            ((i, r) for i, r in enumerate(trace.requests)))]
    rid_to_idx: Dict[int, int] = {}

    def _submit(idx: int, tr: TraceRequest) -> None:
        prompt = np.asarray(tr.prompt, np.int32)
        try:
            rid = target.submit(prompt, tr.max_new_tokens,
                                priority=tr.priority, seed=idx)
        except AdmissionRejected:
            outcomes[idx]["state"] = "shed"
            return
        rid_to_idx[rid] = idx
        outcomes[idx]["state"] = "submitted"

    def _consume(terminals) -> None:
        items = (terminals.items() if isinstance(terminals, dict)
                 else ((r.rid, r) for r in terminals))
        for rid, req in items:
            idx = rid_to_idx.pop(rid, None)
            if idx is None:
                continue
            o = outcomes[idx]
            o["state"] = req.state.name.lower()
            o["n_tokens"] = len(req.generated)
            o["tokens_crc"] = _token_crc(req.tokens)

    def _close_phase(name: str, start: int, end: int,
                     t0: float, submitted_slice) -> PhaseResult:
        res = PhaseResult(name=name, start=start, end=end,
                          t0=t0, t1=clock())
        for eid, eng in engines.items():
            eng._flush_pending()
            eng._flush_host_window()
            if eng.timeseries is not None:
                eng.timeseries.sample(iteration=end)
            win = eng.metrics
            if slos[eid] is not None:
                res.slo[eid] = slos[eid].evaluate(win)
            res.summaries[eid] = win.summary()
            # fresh per-phase window; tell the scraper its counter
            # baselines are void (the reset clamp alone cannot detect a
            # swap whose new values coincidentally match the old ones)
            eng.metrics = ServingMetrics(clock=clock)
            if eng.timeseries is not None:
                eng.timeseries.reset_baseline()
        for o in submitted_slice:
            if o["state"] == "shed":
                res.shed += 1
            else:
                res.submitted += 1
        return res

    phase_results: List[PhaseResult] = []
    next_i = 0                      # cursor into arrival-sorted reqs
    it = 0
    budget = (max_steps if max_steps is not None
              else trace.meta.get("total_iterations", 0) * 50 + 20000)
    steps = 0
    for span in trace.phases:
        t0 = clock()
        lo_i = next_i
        while it < span.end:
            while next_i < len(reqs) and \
                    reqs[next_i][1].arrival <= it:
                idx, tr = reqs[next_i]
                _submit(idx, tr)
                next_i += 1
            if _busy():
                _consume(target.step())
                steps += 1
                if steps > budget:
                    raise RuntimeError(
                        f"replay exceeded {budget} steps (phase "
                        f"{span.name!r}, iteration {it}) — engine "
                        "not draining?")
                clock.advance()
                it += 1
            else:
                # idle fast-forward to the next arrival (or phase end)
                nxt = (reqs[next_i][1].arrival
                       if next_i < len(reqs) else span.end)
                jump = max(1, min(nxt, span.end) - it)
                clock.advance(jump)
                it += jump
        phase_results.append(_close_phase(
            span.name, span.start, span.end, t0,
            [outcomes[i] for i, _ in reqs[lo_i:next_i]]))
    # drain tail: everything still in flight finishes here
    t0 = clock()
    start = it
    while _busy():
        _consume(target.step())
        steps += 1
        if steps > budget:
            raise RuntimeError(
                f"replay drain exceeded {budget} steps — engine "
                "not draining?")
        clock.advance()
        it += 1
    if it > start or any(o["state"] == "submitted" for o in outcomes):
        phase_results.append(_close_phase("(drain)", start, it, t0, []))
    return ReplayResult(
        trace=trace, phases=phase_results, outcomes=outcomes,
        iterations=it, dt=dt, fleet=fleet,
        engine_ids=list(engines), timeseries=tseries, slo=slos)
