"""Multi-host job deployment.

Reference parity: ``distkeras/job_deployment.py :: Job`` packages a training
script and submits it to a remote Spark cluster over SSH + ``spark-submit``
(SURVEY §2.1 L0). The TPU-native equivalent launches one Python process per
host participating in a ``jax.distributed`` coordination domain:

  * ``Job.run()`` — LOCAL multi-process launch: N worker processes on this
    machine, each a JAX process in the same coordination service (the
    test/dev analogue of the reference's ``local[*]`` Spark master, and the
    pattern SURVEY §4 prescribes for exercising multi-host behavior without
    a pod).
  * ``ssh_commands(spec, hosts)`` — the per-host command lines for a real
    TPU pod slice, where host i runs the same script under its own
    ``DKT_PROCESS_ID``. Execution transport (ssh loop, k8s, gcloud) is the
    operator's; the reference's embedded SSH client is deliberately not
    reproduced (no credentials handling inside the framework).

Worker processes bootstrap with ``initialize_from_env()``, which reads the
``DKT_*`` variables this module sets and calls
``jax.distributed.initialize`` — XLA's coordination service (Gloo/DCN)
plays the role Spark's driver-executor RPC played in the reference.
"""

from __future__ import annotations

import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from distkeras_tpu.utils.profiling import now

ENV_COORD = "DKT_COORDINATOR"
ENV_NUM_PROCS = "DKT_NUM_PROCESSES"
ENV_PROC_ID = "DKT_PROCESS_ID"
ENV_DEVICES_PER_PROC = "DKT_DEVICES_PER_PROCESS"


def initialize_from_env() -> Dict[str, int]:
    """Bootstrap a worker process from ``DKT_*`` env (call FIRST, before
    any other jax use). On CPU hosts, honors ``DKT_DEVICES_PER_PROCESS``
    virtual devices. Returns ``{"process_id": ..., "num_processes": ...}``.

    No-op (single-process) when the env is absent, so the same training
    script runs standalone and deployed.
    """
    coord = os.environ.get(ENV_COORD)
    if coord is None:
        return {"process_id": 0, "num_processes": 1}
    n = int(os.environ[ENV_NUM_PROCS])
    pid = int(os.environ[ENV_PROC_ID])
    dev = os.environ.get(ENV_DEVICES_PER_PROC)
    if dev:
        # the spec is explicit: REPLACE any inherited device-count flag
        # (e.g. leaked from a parent test process) rather than defer to it
        import re
        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={dev}"
        ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=n, process_id=pid)
    return {"process_id": pid, "num_processes": n}


@dataclass
class JobSpec:
    """A deployable training job (reference: the ``Job`` constructor args —
    script, cluster params, resources)."""
    script: str                       # path to the python entry script
    args: Sequence[str] = ()
    num_processes: int = 1
    devices_per_process: Optional[int] = None  # CPU-virtual; None = real
    coordinator_port: int = 0         # 0 = pick a free port
    env: Dict[str, str] = field(default_factory=dict)
    name: str = "dkt-job"
    timeout: Optional[float] = None   # seconds; None = no limit
    #: whole-job relaunch count on failure — the analogue of Spark's task
    #: retry (SURVEY §5.3): the reference's failed executor re-trains its
    #: partition from the current PS center; here the relaunched job resumes
    #: from the last checkpoint when the script passes
    #: ``checkpoint_dir=..., resume=True``
    max_retries: int = 0

    def to_dict(self) -> Dict:
        return {"script": self.script, "args": list(self.args),
                "num_processes": self.num_processes,
                "devices_per_process": self.devices_per_process,
                "coordinator_port": self.coordinator_port,
                "env": dict(self.env), "name": self.name,
                "timeout": self.timeout, "max_retries": self.max_retries}

    @classmethod
    def from_dict(cls, d: Dict) -> "JobSpec":
        return cls(**d)


@dataclass
class JobResult:
    name: str
    returncodes: List[int]
    logs: List[str]          # per-process combined stdout/stderr
    wall_seconds: float
    attempts: int = 1        # launches used (1 = no retry needed)

    @property
    def ok(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(spec: JobSpec, coord: str, pid: int) -> Dict[str, str]:
    env = dict(os.environ)
    env.update(spec.env)
    env[ENV_COORD] = coord
    env[ENV_NUM_PROCS] = str(spec.num_processes)
    env[ENV_PROC_ID] = str(pid)
    if spec.devices_per_process:
        env[ENV_DEVICES_PER_PROC] = str(spec.devices_per_process)
    return env


class Job:
    """Run a ``JobSpec`` as N worker processes — local by default, or one
    per remote host over SSH (reference: ``job_deployment.py :: Job.run``,
    which packages and submits to a Spark cluster over SSH; SURVEY §2.1 L0).

    ``hosts=None``: N local processes in one ``jax.distributed``
    coordination domain (the reference's ``local[*]`` analogue).

    ``hosts=[...]``: host i runs process i via ``<transport> <host>
    <command>``; the command line embeds the ``DKT_*`` coordination env
    exactly as ``ssh_commands`` prints it. ``transport`` defaults to
    non-interactive ssh and is injectable (tests substitute a loopback
    stub; operators can substitute ``gcloud compute tpus tpu-vm ssh``-style
    wrappers). Logs and whole-job retry behave as in the local path;
    ``spec.timeout`` is additionally enforced on the remote side by
    wrapping the command in coreutils ``timeout -k`` (killing the local
    ssh client alone would leave remote workers holding their devices).
    """

    def __init__(self, spec: JobSpec, hosts: Optional[Sequence[str]] = None,
                 coordinator_host: Optional[str] = None,
                 python: str = "python3",
                 transport: Sequence[str] = ("ssh", "-o", "BatchMode=yes")):
        self.spec = spec
        self.hosts = list(hosts) if hosts else None
        if self.hosts and len(self.hosts) != spec.num_processes:
            raise ValueError(
                f"{len(self.hosts)} hosts for {spec.num_processes} "
                "processes; deployment is one process per host")
        self.coordinator_host = coordinator_host
        self.python = python
        self.transport = list(transport)

    def run(self) -> JobResult:
        """Launch; on failure relaunch up to ``max_retries`` times (each
        attempt gets a fresh coordinator port). Returns the last attempt's
        result with ``attempts`` filled in."""
        attempts = max(1, self.spec.max_retries + 1)
        for attempt in range(attempts):
            result = self._run_once(attempt=attempt)
            result.attempts = attempt + 1
            if result.ok or attempt == attempts - 1:
                return result
        return result  # pragma: no cover

    def _spawn(self, attempt: int) -> List[subprocess.Popen]:
        spec = self.spec
        if self.hosts is None:
            # retries always re-pick: a pinned port can still be held by a
            # not-yet-reaped child of the failed attempt
            port = (spec.coordinator_port
                    if spec.coordinator_port and attempt == 0
                    else _free_port())
            coord = f"127.0.0.1:{port}"
            return [subprocess.Popen(
                [sys.executable, spec.script, *spec.args],
                env=_worker_env(spec, coord, pid),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True) for pid in range(spec.num_processes)]
        # remote: the coordinator port lives on a remote host, so a local
        # free-port probe is meaningless — offset the base port per retry
        base = spec.coordinator_port or 29500
        spec_attempt = JobSpec(**{**spec.to_dict(),
                                  "coordinator_port": base + attempt})
        cmds = ssh_commands(spec_attempt, self.hosts,
                            coordinator_host=self.coordinator_host,
                            python=self.python)
        if spec.timeout:
            # killing the local ssh client does NOT kill the remote worker
            # (a process blocked in a collective never notices the broken
            # pipe and would hold its devices into the retry attempt) —
            # enforce the deadline on the REMOTE side too, TERM then KILL
            # `env` carries the K=V prefix: timeout exec()s its argument
            # directly (no shell), so a bare env-assignment prefix would
            # be taken for the command name. Ceil with a floor of 1 —
            # coreutils treats duration 0 as NO limit
            import math
            secs = max(1, math.ceil(spec.timeout))
            cmds = [f"timeout -k 15 {secs} env {cmd}" for cmd in cmds]
        return [subprocess.Popen(
            [*self.transport, host, cmd],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for host, cmd in zip(self.hosts, cmds)]

    def _run_once(self, attempt: int = 0) -> JobResult:
        spec = self.spec
        t0 = now()
        procs = self._spawn(attempt)
        # drain every pipe CONCURRENTLY: a worker that fills its 64KB stdout
        # pipe would otherwise block mid-collective and hang the whole
        # coordination domain while run() sat in an earlier communicate()
        import threading

        logs = [""] * len(procs)

        def drain(i, p):
            out, _ = p.communicate()
            logs[i] = out or ""

        threads = [threading.Thread(target=drain, args=(i, p), daemon=True)
                   for i, p in enumerate(procs)]
        for t in threads:
            t.start()
        deadline = (now() + spec.timeout
                    if spec.timeout else None)
        for t in threads:
            t.join(max(0.1, deadline - now())
                   if deadline else None)
        killed = [p.poll() is None for p in procs]
        for p, k in zip(procs, killed):
            if k:
                p.kill()
        for t in threads:
            t.join()
        logs = [log + "\n[killed: job timeout]" if k else log
                for log, k in zip(logs, killed)]
        rcs = [p.returncode for p in procs]
        return JobResult(spec.name, rcs, logs,
                         now() - t0)


def ssh_commands(spec: JobSpec, hosts: Sequence[str],
                 coordinator_host: Optional[str] = None,
                 python: str = "python3") -> List[str]:
    """Per-host launch lines for a real multi-host deployment (one JAX
    process per host). The operator runs line i on ``hosts[i]`` (ssh, k8s
    exec, gcloud compute tpus ... ssh); the framework stays out of the
    credential path, unlike the reference's embedded SSH submission."""
    if not hosts:
        raise ValueError("need at least one host")
    coord_host = coordinator_host or hosts[0]
    port = spec.coordinator_port or 29500
    cmds = []
    for pid, host in enumerate(hosts):
        envs = {**spec.env,
                ENV_COORD: f"{coord_host}:{port}",
                ENV_NUM_PROCS: str(len(hosts)),
                ENV_PROC_ID: str(pid)}
        if spec.devices_per_process:
            envs[ENV_DEVICES_PER_PROC] = str(spec.devices_per_process)
        import shlex
        env_str = " ".join(f"{k}={shlex.quote(str(v))}"
                           for k, v in sorted(envs.items()))
        arg_str = " ".join(shlex.quote(a)
                           for a in [spec.script, *spec.args])
        cmds.append(f"{env_str} {python} {arg_str}")
    return cmds
