"""Job deployment layer (reference: ``distkeras/job_deployment.py`` +
``distkeras/punchcard.py``, SURVEY §2.1 L0)."""

from distkeras_tpu.deploy.job import (  # noqa: F401
    Job, JobResult, JobSpec, initialize_from_env, ssh_commands)
from distkeras_tpu.deploy.punchcard import (  # noqa: F401
    Punchcard, PunchcardClient)
