"""Punchcard — long-running job-acceptor daemon.

Reference parity: ``distkeras/punchcard.py`` (SURVEY §2.1 L0, experimental):
a daemon that accepts training-job specs from authenticated users and runs
them against the cluster, with a secrets file gating submission. Here the
daemon accepts ``JobSpec`` dicts over the framed control-plane protocol
(``parallel/networking.py``), authenticates with a shared secret (constant
-time compare), queues jobs, and executes them one at a time via
``deploy.job.Job`` — the queue discipline the reference delegated to Spark's
scheduler.

Protocol (all requests carry ``{"secret": ...}``):
  {"action": "submit", "spec": {...}}      -> {"job_id": int}
  {"action": "status", "job_id": int}      -> {"state", "result"?}
  {"action": "list"}                        -> {"jobs": [...]}
  {"action": "shutdown"}                    -> {"ok": True}
"""

from __future__ import annotations

import hmac
import queue
import threading
from typing import Any, Dict, Optional

from distkeras_tpu.deploy.job import Job, JobSpec
from distkeras_tpu.parallel import networking


class Punchcard:
    """The daemon. ``secret`` gates every request (reference: the punchcard
    secrets file); jobs run sequentially on a worker thread."""

    def __init__(self, secret: str, host: str = "127.0.0.1", port: int = 0):
        self._secret = secret
        self._server = networking.MessageServer(self._handle, host, port)
        self._jobs: Dict[int, Dict[str, Any]] = {}
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._next_id = 0
        self._shutdown = threading.Event()
        self._runner: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        self._server.start()
        self._runner = threading.Thread(target=self._run_jobs, daemon=True)
        self._runner.start()
        return self._server.port

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self):
        self._shutdown.set()
        self._queue.put(None)  # unblock the runner
        self._server.stop()

    # -- job execution -----------------------------------------------------
    def _run_jobs(self):
        while not self._shutdown.is_set():
            job_id = self._queue.get()
            if job_id is None:
                return
            with self._lock:
                entry = self._jobs[job_id]
                entry["state"] = "running"
            try:
                result = Job(JobSpec.from_dict(entry["spec"])).run()
                with self._lock:
                    entry["state"] = "done" if result.ok else "failed"
                    entry["result"] = {
                        "returncodes": result.returncodes,
                        "wall_seconds": result.wall_seconds,
                        "logs": result.logs,
                    }
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                with self._lock:
                    entry["state"] = "error"
                    entry["result"] = {"error": f"{type(e).__name__}: {e}"}

    # -- protocol ----------------------------------------------------------
    def _handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        if not isinstance(msg, dict):
            return {"error": "bad request"}
        supplied = str(msg.get("secret", ""))
        if not hmac.compare_digest(supplied, self._secret):
            return {"error": "authentication failed"}
        action = msg.get("action")
        if action == "submit":
            try:
                spec = JobSpec.from_dict(msg["spec"])
            except (KeyError, TypeError) as e:
                return {"error": f"bad spec: {e}"}
            with self._lock:
                job_id = self._next_id
                self._next_id += 1
                self._jobs[job_id] = {"spec": spec.to_dict(),
                                      "state": "queued", "result": None}
            self._queue.put(job_id)
            return {"job_id": job_id}
        if action == "status":
            with self._lock:
                entry = self._jobs.get(msg.get("job_id"))
                if entry is None:
                    return {"error": f"no job {msg.get('job_id')!r}"}
                return {"state": entry["state"], "result": entry["result"]}
        if action == "list":
            with self._lock:
                return {"jobs": [
                    {"job_id": jid, "name": e["spec"]["name"],
                     "state": e["state"]}
                    for jid, e in sorted(self._jobs.items())]}
        if action == "shutdown":
            threading.Thread(target=self.stop, daemon=True).start()
            return {"ok": True}
        return {"error": f"unknown action {action!r}"}


class PunchcardClient:
    """Submit/query helper (reference: the job-submission side of
    ``punchcard.py``)."""

    def __init__(self, host: str, port: int, secret: str):
        self._addr = (host, port)
        self._secret = secret

    def _request(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        sock = networking.connect(*self._addr)
        try:
            reply = networking.request(sock, {**msg, "secret": self._secret})
        finally:
            sock.close()
        if isinstance(reply, dict) and "error" in reply:
            raise RuntimeError(f"punchcard: {reply['error']}")
        return reply

    def submit(self, spec: JobSpec) -> int:
        return self._request({"action": "submit",
                              "spec": spec.to_dict()})["job_id"]

    def status(self, job_id: int) -> Dict[str, Any]:
        return self._request({"action": "status", "job_id": job_id})

    def list_jobs(self):
        return self._request({"action": "list"})["jobs"]

    def wait(self, job_id: int, timeout: float = 300.0,
             poll: float = 0.2) -> Dict[str, Any]:
        import time
        # deadline bookkeeping, not telemetry: monotonic is the right
        # clock for a client-side timeout and stays raw by design
        deadline = time.monotonic() + timeout  # lint: allow-raw-clock
        while time.monotonic() < deadline:     # lint: allow-raw-clock
            st = self.status(job_id)
            if st["state"] in ("done", "failed", "error"):
                return st
            time.sleep(poll)
        raise TimeoutError(f"job {job_id} still {st['state']} "
                           f"after {timeout}s")

    def shutdown(self) -> None:
        self._request({"action": "shutdown"})
