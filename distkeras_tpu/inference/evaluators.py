"""Evaluators: offline metric computation over dataset columns.

Reference parity: ``distkeras/evaluators.py`` — ``Evaluator.evaluate(df)``
compares a label column against a prediction column over the RDD;
``AccuracyEvaluator`` is the concrete accuracy case used at the end of every
example pipeline (SURVEY §3.4: ModelPredictor -> LabelIndexTransformer ->
AccuracyEvaluator).
"""

from __future__ import annotations

from typing import Callable, Union

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.ops.metrics import get_metric


class Evaluator:
    """Base evaluator: apply a metric to (label_col, prediction_col)."""

    def __init__(self, metric: Union[str, Callable],
                 label_col: str = "label",
                 prediction_col: str = "prediction"):
        self.metric = get_metric(metric)
        self.label_col = label_col
        self.prediction_col = prediction_col

    def evaluate(self, dataset: Dataset) -> float:
        y_true = jnp.asarray(dataset[self.label_col])
        y_pred = jnp.asarray(dataset[self.prediction_col])
        return float(self.metric(y_true, y_pred))


class AccuracyEvaluator(Evaluator):
    """Reference parity: ``evaluators.py :: AccuracyEvaluator``."""

    def __init__(self, label_col: str = "label",
                 prediction_col: str = "prediction"):
        super().__init__("accuracy", label_col=label_col,
                         prediction_col=prediction_col)
