"""Inference layer: sharded predictors + evaluators."""

from distkeras_tpu.inference.evaluators import (  # noqa: F401
    AccuracyEvaluator, Evaluator)
from distkeras_tpu.inference.predictors import (  # noqa: F401
    ModelPredictor, Predictor, StreamingPredictor)
