"""Predictors: sharded batch inference appending a prediction column.

Reference parity: ``distkeras/predictors.py`` — ``Predictor.predict(df)``
maps partitions of a Spark DataFrame through a deserialized Keras model,
appending the raw model output as a new column; ``ModelPredictor`` names the
output column (SURVEY §3.4, which also flags the reference's per-ROW
``model.predict`` as a bottleneck).

TPU-native redesign: inference is one jitted forward over batches that are
**sharded across the device mesh on the batch axis** (the "pmapped batch
over chips" the north star asks for). Rows are padded to the global batch so
every call reuses a single compiled shape.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataset import Dataset, coerce_column
from distkeras_tpu.models.core import Model, user_float
from distkeras_tpu.parallel.mesh import make_mesh


class Predictor:
    """Batched, mesh-sharded inference (reference:
    ``predictors.py :: Predictor``).

    ``predict(dataset)`` returns the dataset with ``output_col`` appended —
    the same DataFrame-in/DataFrame-out contract as the reference.
    """

    def __init__(self, keras_model: Model, features_col: str = "features",
                 output_col: str = "prediction",
                 batch_size_per_device: int = 128,
                 mesh: Optional[Mesh] = None,
                 tp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
        """``tp_axis``/``ep_axis``: shard the model's params over those mesh
        axes (same Megatron/expert rules as SPMDTrainer) instead of
        replicating — inference for models bigger than one chip. The batch
        is sharded over the mesh's FIRST axis either way."""
        self.model = keras_model
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size_per_device = int(batch_size_per_device)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.tp_axis = tp_axis
        self.ep_axis = ep_axis
        self._fn = None

    def _build(self):
        mesh = self.mesh
        batch_axis = mesh.axis_names[0]
        sharded = NamedSharding(mesh, P(batch_axis))
        replicated = NamedSharding(mesh, P())
        model = self.model

        @jax.jit
        def fwd(params, state, xb):
            y, _ = model.module.apply(params, state, xb, training=False)
            return user_float(y)

        self._fn = fwd
        self._in_sharding = sharded
        self._rep = replicated
        if self.tp_axis or self.ep_axis:
            from distkeras_tpu.parallel.sharding import (named_shardings,
                                                         param_specs)
            specs = param_specs(model.module, model.params, mesh,
                                tp_axis=self.tp_axis, ep_axis=self.ep_axis)
            self._param_sh = named_shardings(specs, mesh)
        else:
            self._param_sh = None

    def _place_params(self):
        sh = self._param_sh if self._param_sh is not None else self._rep
        return (jax.device_put(self.model.params, sh),
                jax.device_put(self.model.state, self._rep))

    # the one shared dtype policy (training and inference must agree)
    _coerce = staticmethod(coerce_column)

    @staticmethod
    def _pad_to(xb: np.ndarray, size: int):
        """Zero-pad the batch dim to ``size`` (the ONE compiled shape);
        returns ``(padded, pad)``."""
        pad = size - len(xb)
        if pad:
            xb = np.concatenate(
                [xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
        return xb, pad

    def predict(self, dataset: Dataset) -> Dataset:
        if self._fn is None:
            self._build()
        X = self._coerce(dataset[self.features_col])
        n = len(X)
        n_batch = self.mesh.shape[self.mesh.axis_names[0]]
        global_batch = n_batch * self.batch_size_per_device

        params, state = self._place_params()

        outs = []
        for i in range(0, n, global_batch):
            xb, pad = self._pad_to(X[i:i + global_batch], global_batch)
            xb = jax.device_put(jnp.asarray(xb), self._in_sharding)
            yb = np.asarray(self._fn(params, state, xb))
            outs.append(yb[:global_batch - pad] if pad else yb)
        preds = np.concatenate(outs, axis=0)
        return dataset.with_column(self.output_col, preds)


class ModelPredictor(Predictor):
    """Reference parity: ``predictors.py :: ModelPredictor`` — Predictor
    with a user-named output column (kept as a distinct class so reference
    code ports 1:1)."""

    def __init__(self, keras_model: Model, features_col: str = "features",
                 output_col: str = "prediction", **kwargs):
        super().__init__(keras_model, features_col=features_col,
                         output_col=output_col, **kwargs)


class StreamingPredictor(Predictor):
    """Continuous inference over an unbounded batch stream.

    Reference parity: the Kafka streaming-inference example (SURVEY §2.2 —
    examples consume records from a Kafka topic, predict with a trained
    model, and emit to an output topic). The transport is deliberately out
    of scope (bring any iterator: a Kafka consumer, a socket, a file
    tailer); this class supplies the TPU-side pattern the example needs:

      * ONE compiled forward reused for every stream batch (ragged batches
        are padded to ``batch_size``, so there is exactly one jit shape);
      * a background thread stages the NEXT batch host→device while the
        current one computes, hiding transfer latency behind the MXU.

    ``predict_stream(source)`` yields one output array per input batch, in
    order.
    """

    def __init__(self, keras_model: Model, batch_size: int = 256,
                 mesh: Optional[Mesh] = None, **kwargs):
        mesh = mesh if mesh is not None else make_mesh()
        # batch shards over the FIRST mesh axis only (same semantics as
        # Predictor.predict); other axes hold tp/ep shards
        n_batch = mesh.shape[mesh.axis_names[0]]
        if batch_size % n_batch:
            raise ValueError(
                f"batch_size {batch_size} must divide over the "
                f"{mesh.axis_names[0]!r} axis ({n_batch})")
        super().__init__(keras_model, mesh=mesh,
                         batch_size_per_device=batch_size // n_batch,
                         **kwargs)
        self.batch_size = int(batch_size)

    def predict_stream(self, source):
        """``source``: LAZY iterable of ``[n_i, ...]`` feature arrays
        (n_i <= batch_size) — a generator, a Kafka consumer, a socket
        reader; it is consumed one batch at a time on the staging
        thread, never materialized. Yields ``[n_i, ...]`` prediction
        arrays in order.

        Folded onto :class:`utils.prefetch.Prefetcher` (this PR — the
        predictors.py:210 follow-up): the hand-rolled staging thread
        here and the Prefetcher carried parallel copies of the polling
        shutdown protocol; now there is exactly one, and the
        Prefetcher itself is lazy. Padding/coercion run as the
        prefetch ``fn`` and the H2D ``device_put`` as its ``place``
        hook (on the producer thread, once a queue slot is free — the
        depth-bounded device-memory cap), so the consumer receives
        device-resident batches. Source/validation errors re-raise
        here with their original type; early ``close()`` of the
        generator reaps the staging thread without dropping
        already-staged results."""
        if self._fn is None:
            self._build()
        params, state = self._place_params()

        def stage(batch):
            xb = self._coerce(batch)
            if len(xb) > self.batch_size:
                raise ValueError(
                    f"stream batch of {len(xb)} exceeds "
                    f"batch_size {self.batch_size}")
            return self._pad_to(xb, self.batch_size)

        def place(item):
            xb, pad = item
            return (jax.device_put(jnp.asarray(xb), self._in_sharding),
                    pad)

        from distkeras_tpu.utils.prefetch import Prefetcher
        pf = Prefetcher(stage, source, depth=2, name="predict_stream",
                        place=place)
        # exposed for shutdown tests: callers (and the test suite) can
        # assert the producer actually terminated after gen.close()
        self._stage_thread = pf._thread
        with pf:
            for _, (dev, pad) in pf:
                yb = np.asarray(self._fn(params, state, dev))
                yield yb[:self.batch_size - pad] if pad else yb
